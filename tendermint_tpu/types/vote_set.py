"""VoteSet: 2/3-majority tally for one (height, round, type).

Mirrors types/vote_set.go:56-476: per-validator primary votes, per-block
sub-tallies (``votesByBlock``), conflict tracking for evidence, and
peer-claimed majorities that allow tracking conflicting votes beyond the
first. Thread-safe like the reference (consensus and gossip touch it from
different threads).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
)
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    ExtendedCommit,
    ExtendedCommitSig,
    Vote,
)
from tendermint_tpu.types.validator_set import ValidatorSet


class VoteSetError(ValueError):
    pass


class ConflictingVotesError(Exception):
    """types/vote.go ErrVoteConflictingVotes: evidence material."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        self.vote_a = vote_a
        self.vote_b = vote_b
        super().__init__(
            f"conflicting votes from validator {vote_a.validator_address.hex()}"
        )


class NonDeterministicSignatureError(VoteSetError):
    pass


class _BlockVotes:
    """types/vote_set.go:482-512: tally of one block's votes."""

    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: List[Optional[Vote]] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        val_index = vote.validator_index
        if self.votes[val_index] is None:
            self.bit_array.set_index(val_index, True)
            self.votes[val_index] = vote
            self.sum += voting_power

    def get_by_index(self, index: int) -> Optional[Vote]:
        return self.votes[index]


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: int,
        val_set: ValidatorSet,
        extensions_enabled: bool = False,
    ):
        if height == 0:
            raise ValueError("Cannot make VoteSet for height == 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        self._mtx = threading.Lock()
        self.votes_bit_array = BitArray(len(val_set))
        self.votes: List[Optional[Vote]] = [None] * len(val_set)
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: Dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: Dict[str, BlockID] = {}

    @classmethod
    def extended(
        cls,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: int,
        val_set: ValidatorSet,
    ) -> "VoteSet":
        """NewExtendedVoteSet: verifies vote extensions on every add."""
        return cls(chain_id, height, round_, signed_msg_type, val_set, True)

    def size(self) -> int:
        return len(self.val_set)

    # --- adding votes -------------------------------------------------------

    def add_vote(self, vote: Optional[Vote]) -> bool:
        """types/vote_set.go:150-258. Returns True if added; raises on
        invalid/conflicting votes (ConflictingVotesError carries both)."""
        if vote is None:
            raise VoteSetError("nil vote")
        with self._mtx:
            return self._add_vote(vote)

    def _add_vote(self, vote: Vote) -> bool:
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()

        if val_index < 0:
            raise VoteSetError("index < 0: invalid validator index")
        if not val_addr:
            raise VoteSetError("empty address: invalid validator address")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.signed_msg_type
        ):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/{self.signed_msg_type}, "
                f"got {vote.height}/{vote.round}/{vote.type}: unexpected step"
            )
        val = self.val_set.get_by_index(val_index)
        if val is None:
            raise VoteSetError(
                f"cannot find validator {val_index} in valSet of size "
                f"{len(self.val_set)}: invalid validator index"
            )
        if val_addr != val.address:
            raise VoteSetError(
                "vote.validator_address does not match address for "
                "vote.validator_index: invalid validator address"
            )

        existing = self._get_vote(val_index, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return False  # duplicate
            raise NonDeterministicSignatureError(
                f"existing vote: {existing}; new vote: {vote}"
            )

        # Signature check (the hot single-verify path: vote_set.go:211-222).
        if self.extensions_enabled:
            vote.verify_vote_and_extension(self.chain_id, val.pub_key)
        else:
            vote.verify(self.chain_id, val.pub_key)
            if vote.extension or vote.extension_signature:
                raise VoteSetError("unexpected vote extension data present in vote")

        added, conflicting = self._add_verified_vote(
            vote, block_key, val.voting_power
        )
        if conflicting is not None:
            raise ConflictingVotesError(conflicting, vote)
        if not added:
            raise RuntimeError("expected to add non-conflicting vote")
        return added

    def _get_vote(self, val_index: int, block_key: bytes) -> Optional[Vote]:
        existing = self.votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        by_block = self.votes_by_block.get(block_key)
        if by_block is not None:
            return by_block.get_by_index(val_index)
        return None

    def _add_verified_vote(
        self, vote: Vote, block_key: bytes, voting_power: int
    ) -> Tuple[bool, Optional[Vote]]:
        """types/vote_set.go:264-340."""
        val_index = vote.validator_index
        conflicting: Optional[Vote] = None

        existing = self.votes[val_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                raise RuntimeError("addVerifiedVote does not expect duplicate votes")
            conflicting = existing
            # Replace the primary vote only if this key is the known maj23.
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array.set_index(val_index, True)
        else:
            self.votes[val_index] = vote
            self.votes_bit_array.set_index(val_index, True)
            self.sum += voting_power

        by_block = self.votes_by_block.get(block_key)
        if by_block is not None:
            if conflicting is not None and not by_block.peer_maj23:
                return False, conflicting
        else:
            if conflicting is not None:
                return False, conflicting
            by_block = _BlockVotes(False, len(self.val_set))
            self.votes_by_block[block_key] = by_block

        orig_sum = by_block.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        by_block.add_verified_vote(vote, voting_power)

        if orig_sum < quorum <= by_block.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            for i, v in enumerate(by_block.votes):
                if v is not None:
                    self.votes[i] = v
        return True, conflicting

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """types/vote_set.go:345-388: a peer claims 2/3 on block_id."""
        with self._mtx:
            block_key = block_id.key()
            existing = self.peer_maj23s.get(peer_id)
            if existing is not None:
                if existing == block_id:
                    return
                raise VoteSetError(
                    f"setPeerMaj23: conflicting blockID from peer {peer_id}"
                )
            self.peer_maj23s[peer_id] = block_id
            by_block = self.votes_by_block.get(block_key)
            if by_block is not None:
                by_block.peer_maj23 = True
            else:
                self.votes_by_block[block_key] = _BlockVotes(
                    True, len(self.val_set)
                )

    # --- queries ------------------------------------------------------------

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        with self._mtx:
            by_block = self.votes_by_block.get(block_id.key())
            if by_block is not None:
                return by_block.bit_array.copy()
            return None

    def get_by_index(self, val_index: int) -> Optional[Vote]:
        with self._mtx:
            if not 0 <= val_index < len(self.votes):
                return None
            return self.votes[val_index]

    def get_by_address(self, address: bytes) -> Optional[Vote]:
        with self._mtx:
            val_index, val = self.val_set.get_by_address(address)
            if val is None:
                return None
            return self.votes[val_index]

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self.maj23 is not None

    def has_two_thirds_any(self) -> bool:
        with self._mtx:
            return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        with self._mtx:
            return self.sum == self.val_set.total_voting_power()

    def two_thirds_majority(self) -> Tuple[BlockID, bool]:
        with self._mtx:
            if self.maj23 is not None:
                return self.maj23, True
            return BlockID(), False

    def vote_list(self) -> List[Vote]:
        with self._mtx:
            return [v for v in self.votes if v is not None]

    # --- commit construction ------------------------------------------------

    def make_extended_commit(self) -> ExtendedCommit:
        """types/vote_set.go:658-690."""
        if self.signed_msg_type != SIGNED_MSG_TYPE_PRECOMMIT:
            raise VoteSetError(
                "cannot MakeExtendedCommit unless VoteSet.Type is Precommit"
            )
        with self._mtx:
            if self.maj23 is None:
                raise VoteSetError(
                    "cannot MakeExtendedCommit unless a blockhash has +2/3"
                )
            sigs: List[ExtendedCommitSig] = []
            for v in self.votes:
                if v is None:
                    sigs.append(ExtendedCommitSig())
                    continue
                sig = v.extended_commit_sig()
                if sig.commit_sig.is_commit() and v.block_id != self.maj23:
                    sig = ExtendedCommitSig()
                sigs.append(sig)
            return ExtendedCommit(
                height=self.height,
                round=self.round,
                block_id=self.maj23,
                extended_signatures=sigs,
            )

    def make_commit(self) -> Commit:
        """Plain commit (pre-extension networks)."""
        return self.make_extended_commit().to_commit()


def vote_set_from_commit(
    chain_id: str, commit: Commit, val_set: ValidatorSet
) -> VoteSet:
    """Rebuild the precommit VoteSet a commit came from — the restart path
    reconstructLastCommit (types/vote_set.go CommitToVoteSet)."""
    vs = VoteSet(
        chain_id, commit.height, commit.round, SIGNED_MSG_TYPE_PRECOMMIT, val_set
    )
    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        vote = commit.get_vote(idx)
        if not vs.add_vote(vote):
            raise VoteSetError(f"failed to reconstruct commit vote {idx}")
    return vs
