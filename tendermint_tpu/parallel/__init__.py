"""Multi-chip parallelism: the mesh-native verify engine.

Two halves:

- :mod:`tendermint_tpu.parallel.mesh` — policy (imported eagerly; no
  jax until a plan is requested): mesh sizing via ``TENDERMINT_TPU_MESH``
  / ``[ops] mesh_devices``, per-device health with COOLDOWN
  re-admission, the process-wide :data:`~mesh.manager`.
- :mod:`tendermint_tpu.parallel.sharding` — mechanism (imported lazily;
  pulls jax): sharded kernels for both engines + the table path,
  chunk dispatch with degradation, per-device collect.
"""

from tendermint_tpu.parallel import mesh

_LAZY = (
    "SIG_AXIS",
    "make_mesh",
    "sharded_verify_fn",
    "verify_batch_sharded",
    "verify_batch_sharded_sr",
    "sharding",
)

__all__ = ["mesh", *_LAZY]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        sharding = importlib.import_module("tendermint_tpu.parallel.sharding")
        if name == "sharding":
            return sharding
        return getattr(sharding, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
