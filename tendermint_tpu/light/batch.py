"""One-device-call bisection rounds for the light client.

The sequential skipping loop (light/client.py) costs one
``verify_commit_light_trusting`` + ``verify_commit_light`` round-trip
per pivot — each a separate device launch. This module turns a whole
bisection round into ONE scheduler super-batch: every candidate in the
pivot ladder (and every conflicting witness header in the detector) is
*planned* host-side into raw ed25519 lanes, the union of all lanes is
submitted through the process-wide ``VerifyScheduler`` in a single
atomic ``submit_many`` (one accumulator flush -> one device call), and
the verdicts are then folded back into per-candidate accept / bisect /
error outcomes host-side.

Parity contract: a candidate's outcome is EXACTLY what
``verifier.verify`` would have produced — same exception types, same
messages, same precedence (trusting tally before trusting signatures
before the full 2/3 check, ``NotEnoughVotingPowerError`` from the full
check propagating raw, ``InvalidCommitError`` surfacing as
``InvalidHeaderError``). Anything the lane planner can't express
byte-for-byte (non-ed25519 keys, sub-threshold commits, malformed
entries) falls back to the sequential verifier for that candidate, so
the batch path never changes a verdict, only where the signatures run.

Validator-set reuse rides the existing PR 2/8 paths: every planned set
goes through ``crypto_batch.note_validator_set`` so repeated sets cost
resident-table index-gathers, not rebuilds.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto.keys import ED25519_KEY_TYPE
from tendermint_tpu.libs import tracing
from tendermint_tpu.light import verifier
from tendermint_tpu.types import Fraction
from tendermint_tpu.types.block import BLOCK_ID_FLAG_COMMIT
from tendermint_tpu.types.validation import (
    BATCH_VERIFY_THRESHOLD,
    InvalidCommitError,
    NotEnoughVotingPowerError,
    _safe_mul,
    _verify_basic_vals_and_commit,
)
from tendermint_tpu.verifyd.protocol import CLASS_LIGHT

# outcome kinds
OK = "ok"
BISECT = "bisect"  # NewValSetCantBeTrusted: descend to a deeper pivot
ERROR = "error"  # hard failure: propagate to the caller

DEFAULT_WAIT = 30.0  # verdict wait for one super-batch


def batching_enabled() -> bool:
    """Batched rounds are the default; TENDERMINT_TPU_LIGHT_BATCH=off
    restores the one-call-per-pivot sequential loop (parity baseline)."""
    return os.environ.get("TENDERMINT_TPU_LIGHT_BATCH", "on").lower() not in (
        "off", "0", "false",
    )


class Outcome:
    """Per-candidate verdict of one evaluated ladder."""

    __slots__ = ("kind", "error")

    def __init__(self, kind: str, error: Optional[BaseException] = None):
        self.kind = kind
        self.error = error


class _SigStep:
    """Deferred check over a contiguous lane slice: the first False
    verdict becomes the sequential path's exact wrong-signature error."""

    __slots__ = ("start", "idxs", "commit")

    def __init__(self, start: int, idxs: List[int], commit):
        self.start = start
        self.idxs = idxs
        self.commit = commit


class _RaiseStep:
    """Deferred exception: raised only if every earlier step passed
    (mirrors the sequential check order)."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


class _Plan:
    __slots__ = ("cand", "steps", "outcome", "fallback", "lanes")

    def __init__(self, cand):
        self.cand = cand
        self.steps: list = []
        self.outcome: Optional[Outcome] = None  # decided before any lane runs
        self.fallback = False  # punt this candidate to verifier.verify
        self.lanes: List[Tuple[bytes, bytes, bytes]] = []


def _plannable(vals) -> bool:
    """Every signer must be a well-formed ed25519 key for raw scheduler
    lanes; anything else goes through the sequential verifier (which has
    the multi-key-type sub-batching)."""
    for v in vals.validators:
        pk = v.pub_key
        if pk is None or pk.type != ED25519_KEY_TYPE or len(pk.bytes()) != 32:
            return False
    return True


def _plan_candidate(
    chain_id: str,
    base,
    cand,
    trusting_period: float,
    now,
    max_clock_drift: float,
    trust_level: Fraction,
) -> _Plan:
    """Host-side dry run of ``verifier.verify(base, cand)``: do every
    non-signature check now, emit the signature work as lanes."""
    plan = _Plan(cand)
    sh_t, vals_t = base.signed_header, base.validator_set
    sh_u, vals_u = cand.signed_header, cand.validator_set
    adjacent = sh_u.header.height == sh_t.header.height + 1

    # --- header-shape prechecks (verifier.go:33-60 / 106-130 order) ---------
    try:
        verifier._check_required_header_fields(sh_t)
        if not adjacent:
            verifier.validate_trust_level(trust_level)
        if verifier.header_expired(sh_t, trusting_period, now):
            raise verifier.HeaderExpiredError("old header has expired")
        verifier._verify_new_header_and_vals(
            sh_u, vals_u, sh_t, now, max_clock_drift
        )
        if adjacent and (
            sh_u.header.validators_hash != sh_t.header.next_validators_hash
        ):
            raise verifier.InvalidHeaderError(
                "expected old header's next validators to match those from "
                "new header"
            )
    except Exception as e:
        plan.outcome = Outcome(ERROR, e)
        return plan

    commit = sh_u.commit
    if (
        commit is None
        or vals_t is None
        or vals_u is None
        or len(commit.signatures) < BATCH_VERIFY_THRESHOLD
        or not _plannable(vals_t)
        or not _plannable(vals_u)
        or any(
            cs.signature is not None and len(cs.signature) != 64
            for cs in commit.signatures
            if cs.block_id_flag == BLOCK_ID_FLAG_COMMIT
        )
    ):
        plan.fallback = True
        return plan

    # --- trusting check (verify_commit_light_trusting, batch path) ----------
    if not adjacent:
        try:
            if trust_level.denominator == 0:
                raise InvalidCommitError("trustLevel has zero Denominator")
            total_mul, overflow = _safe_mul(
                vals_t.total_voting_power(), trust_level.numerator
            )
            if overflow:
                raise InvalidCommitError(
                    "int64 overflow while calculating voting power needed"
                )
            needed = total_mul // trust_level.denominator
            crypto_batch.note_validator_set(vals_t)
            tallied = 0
            seen: dict = {}
            lanes: List[Tuple[bytes, bytes, bytes]] = []
            idxs: List[int] = []
            for idx, cs in enumerate(commit.signatures):
                if cs.block_id_flag != BLOCK_ID_FLAG_COMMIT:
                    continue
                val_idx, val = vals_t.get_by_address(cs.validator_address)
                if val is None:
                    continue
                if val_idx in seen:
                    raise InvalidCommitError(
                        f"double vote from validator {val_idx} "
                        f"({seen[val_idx]} and {idx})"
                    )
                seen[val_idx] = idx
                lanes.append(
                    (
                        val.pub_key.bytes(),
                        commit.vote_sign_bytes(chain_id, idx),
                        cs.signature,
                    )
                )
                idxs.append(idx)
                tallied += val.voting_power
                if tallied > needed:
                    break
            if tallied <= needed:
                e = NotEnoughVotingPowerError(got=tallied, needed=needed)
                plan.outcome = Outcome(
                    BISECT, verifier.NewValSetCantBeTrustedError(str(e))
                )
                return plan
            plan.steps.append(_SigStep(len(plan.lanes), idxs, commit))
            plan.lanes.extend(lanes)
        except InvalidCommitError as e:
            # verify_non_adjacent wraps the ValueError family
            plan.outcome = Outcome(ERROR, verifier.InvalidHeaderError(str(e)))
            return plan

    # --- full 2/3 check (verify_commit_light, batch path) --------------------
    try:
        _verify_basic_vals_and_commit(
            vals_u, commit, sh_u.header.height, commit.block_id
        )
        needed2 = vals_u.total_voting_power() * 2 // 3
        crypto_batch.note_validator_set(vals_u)
        tallied2 = 0
        lanes2: List[Tuple[bytes, bytes, bytes]] = []
        idxs2: List[int] = []
        for idx, cs in enumerate(commit.signatures):
            if cs.block_id_flag != BLOCK_ID_FLAG_COMMIT:
                continue
            val = vals_u.validators[idx]
            lanes2.append(
                (
                    val.pub_key.bytes(),
                    commit.vote_sign_bytes(chain_id, idx),
                    cs.signature,
                )
            )
            idxs2.append(idx)
            tallied2 += val.voting_power
            if tallied2 > needed2:
                break
        if tallied2 <= needed2:
            # NotEnoughVotingPowerError is not a ValueError: it escapes
            # verify_non_adjacent RAW (only after earlier steps pass)
            plan.steps.append(
                _RaiseStep(NotEnoughVotingPowerError(got=tallied2, needed=needed2))
            )
        else:
            plan.steps.append(_SigStep(len(plan.lanes), idxs2, commit))
            plan.lanes.extend(lanes2)
    except InvalidCommitError as e:
        plan.steps.append(_RaiseStep(verifier.InvalidHeaderError(str(e))))
    return plan


def _resolve(plan: _Plan, verdicts: List[bool], base_off: int) -> Outcome:
    if plan.outcome is not None:
        return plan.outcome
    for step in plan.steps:
        if isinstance(step, _RaiseStep):
            return Outcome(ERROR, step.error)
        for rel, idx in enumerate(step.idxs):
            if not verdicts[base_off + step.start + rel]:
                sig = step.commit.signatures[idx]
                e = InvalidCommitError(
                    f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
                )
                return Outcome(ERROR, verifier.InvalidHeaderError(str(e)))
    return Outcome(OK)


def _resolve_sequential(
    chain_id, base, cand, trusting_period, now, max_clock_drift, trust_level
) -> Outcome:
    try:
        verifier.verify(
            base.signed_header,
            base.validator_set,
            cand.signed_header,
            cand.validator_set,
            trusting_period,
            now,
            max_clock_drift,
            trust_level,
        )
        return Outcome(OK)
    except verifier.NewValSetCantBeTrustedError as e:
        return Outcome(BISECT, e)
    except Exception as e:
        return Outcome(ERROR, e)


def evaluate_candidates(
    chain_id: str,
    base,
    candidates: list,
    trusting_period: float,
    now,
    max_clock_drift: float,
    trust_level: Fraction,
    scheduler=None,
    timeout: float = DEFAULT_WAIT,
) -> List[Outcome]:
    """Verify every candidate against ``base`` with at most ONE
    scheduler super-batch, returning outcomes aligned with
    ``candidates``. Candidates the planner can't express fall back to
    the sequential verifier individually (still host-side, no extra
    device calls)."""
    plans = [
        _plan_candidate(
            chain_id, base, c, trusting_period, now, max_clock_drift,
            trust_level,
        )
        for c in candidates
    ]
    lanes: List[Tuple[bytes, bytes, bytes]] = []
    offsets: List[int] = []
    for p in plans:
        offsets.append(len(lanes))
        lanes.extend(p.lanes)
    verdicts: List[bool] = []
    if lanes:
        sched = scheduler
        if sched is None:
            sched = crypto_batch.get_shared_scheduler()
        with tracing.span(
            "light_super_batch", lanes=len(lanes), candidates=len(candidates)
        ):
            # flush_by=now: the whole round is already assembled — pull
            # the accumulator's deadline to "immediately" so the batch
            # ships as one device call without waiting out max_delay
            entries = sched.submit_many(
                lanes,
                priority=CLASS_LIGHT,
                flush_by=time.monotonic(),
                tag="light-bisect",
            )
            verdicts = sched.wait_many(entries, timeout=timeout)
    out: List[Outcome] = []
    for p, off in zip(plans, offsets):
        if p.fallback:
            out.append(
                _resolve_sequential(
                    chain_id, base, p.cand, trusting_period, now,
                    max_clock_drift, trust_level,
                )
            )
        else:
            out.append(_resolve(p, verdicts, off))
    return out
