"""The in-repo gRPC stack (libs/grpc.py) and its two consumers: the
ABCI gRPC transport (abci/grpc_client.py, abci/grpc_server.py — parity
with the socket transport; reference abci/client/grpc_client.go:184,
abci/server/grpc_server.go:83) and the gRPC remote signer
(privval/grpc.py; reference privval/grpc/).
"""

import threading

import pytest

from tendermint_tpu.libs.grpc import (
    GRPC_INTERNAL,
    GRPC_UNIMPLEMENTED,
    GrpcChannel,
    GrpcError,
    GrpcServer,
    HpackDecoder,
    hpack_encode,
)


# --- HPACK ------------------------------------------------------------------


def test_hpack_roundtrip_literals():
    headers = [
        (":method", "POST"),
        (":path", "/tendermint.abci.ABCIApplication/Info"),
        ("content-type", "application/grpc"),
        ("te", "trailers"),
        ("x-custom", "v" * 300),  # multi-byte length integer
    ]
    dec = HpackDecoder()
    assert dec.decode(hpack_encode(headers)) == headers


def test_hpack_decodes_indexed_and_incremental():
    # 0x82 = indexed static 2 (":method: GET"); then a literal with
    # incremental indexing (0x40) inserting into the dynamic table;
    # then 0xBE = dynamic index 62 (the entry just inserted).
    block = bytes([0x82])
    block += bytes([0x40, 0x05]) + b"x-abc" + bytes([0x03]) + b"yes"
    block += bytes([0xBE])
    dec = HpackDecoder()
    assert dec.decode(block) == [
        (":method", "GET"),
        ("x-abc", "yes"),
        ("x-abc", "yes"),
    ]


def test_hpack_rejects_huffman():
    dec = HpackDecoder()
    # literal, new name, huffman bit set on the name string
    block = bytes([0x00, 0x81, 0xFF, 0x00])
    from tendermint_tpu.libs.grpc import H2ProtocolError

    with pytest.raises(H2ProtocolError):
        dec.decode(block)


# --- unary transport --------------------------------------------------------


@pytest.fixture()
def echo_server():
    def echo(payload: bytes) -> bytes:
        return payload

    def boom(payload: bytes) -> bytes:
        raise RuntimeError("kaput")

    srv = GrpcServer({"/t.Svc/Echo": echo, "/t.Svc/Boom": boom})
    srv.start()
    yield srv
    srv.stop()


def test_unary_roundtrip_and_errors(echo_server):
    host, port = echo_server.address
    chan = GrpcChannel(host, port)
    try:
        assert chan.unary("/t.Svc/Echo", b"hello") == b"hello"
        assert chan.unary("/t.Svc/Echo", b"") == b""
        with pytest.raises(GrpcError) as ei:
            chan.unary("/t.Svc/Boom", b"x")
        assert ei.value.status == GRPC_INTERNAL
        with pytest.raises(GrpcError) as ei:
            chan.unary("/t.Svc/Nope", b"x")
        assert ei.value.status == GRPC_UNIMPLEMENTED
        # connection survives error responses
        assert chan.unary("/t.Svc/Echo", b"still alive") == b"still alive"
    finally:
        chan.close()


def test_unary_large_payload_flow_control(echo_server):
    """>64KB in both directions: exercises DATA chunking to MAX_FRAME
    and the connection-window replenishment."""
    host, port = echo_server.address
    chan = GrpcChannel(host, port)
    try:
        big = bytes(range(256)) * 1024  # 256 KB
        assert chan.unary("/t.Svc/Echo", big) == big
    finally:
        chan.close()


def test_many_sequential_calls_one_connection(echo_server):
    host, port = echo_server.address
    chan = GrpcChannel(host, port)
    try:
        for i in range(50):
            msg = b"call %d" % i
            assert chan.unary("/t.Svc/Echo", msg) == msg
    finally:
        chan.close()


# --- ABCI transport parity --------------------------------------------------


@pytest.fixture()
def abci_pair():
    from tendermint_tpu.abci.grpc_client import GrpcClient
    from tendermint_tpu.abci.grpc_server import GrpcABCIServer
    from tendermint_tpu.abci.kvstore import KVStoreApplication

    app = KVStoreApplication()
    srv = GrpcABCIServer(app)
    srv.start()
    host, port = srv.address
    client = GrpcClient(host, port)
    client.start()
    yield client, app
    client.stop()
    srv.stop()


def test_abci_grpc_socket_parity(abci_pair):
    """The gRPC transport must return byte-identical results to driving
    the same app locally (the socket-parity criterion)."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication

    client, _ = abci_pair
    local = LocalClient(KVStoreApplication())
    local.start()

    assert client.echo("ping") == "ping"
    client.flush()

    for c in (client, local):
        c.init_chain(abci.RequestInitChain(chain_id="grpc-chain", initial_height=1))

    tx = b"k1=v1"
    for c in (client, local):
        r = c.check_tx(abci.RequestCheckTx(tx=tx))
        assert r.code == 0
        fr = c.finalize_block(
            abci.RequestFinalizeBlock(txs=[tx], height=1)
        )
        assert fr.tx_results[0].code == 0
    g_hash = client.commit()
    l_hash = local.commit()
    # same app type, same txs -> same app state
    gq = client.query(abci.RequestQuery(path="/key", data=b"k1"))
    lq = local.query(abci.RequestQuery(path="/key", data=b"k1"))
    assert gq.value == lq.value == b"v1"


def test_abci_grpc_app_error_surfaces(abci_pair):
    from tendermint_tpu.abci import types as abci

    client, app = abci_pair

    def broken(req):
        raise ValueError("app exploded")

    app.query = broken
    with pytest.raises(RuntimeError, match="app exploded"):
        client.query(abci.RequestQuery(path="/key", data=b"k"))


# --- gRPC remote signer -----------------------------------------------------


@pytest.fixture()
def signer_pair(tmp_path):
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.privval.grpc import GrpcSignerClient, GrpcSignerServer

    pv = FilePV.generate(
        str(tmp_path / "key.json"), str(tmp_path / "state.json")
    )
    srv = GrpcSignerServer(pv, "grpc-chain")
    srv.start()
    host, port = srv.address
    client = GrpcSignerClient(host, port, "grpc-chain")
    yield client, pv
    client.close()
    srv.stop()


def test_signer_pubkey_and_vote_roundtrip(signer_pair):
    from tendermint_tpu.encoding.canonical import (
        SIGNED_MSG_TYPE_PREVOTE,
        Timestamp,
    )
    from tendermint_tpu.types.block import Vote

    client, pv = signer_pair
    assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()

    vote = Vote(
        type=SIGNED_MSG_TYPE_PREVOTE,
        height=7,
        round=0,
        timestamp=Timestamp.from_unix_ns(1_700_000_000_000_000_000),
        validator_address=pv.get_pub_key().address(),
        validator_index=0,
    )
    client.sign_vote("grpc-chain", vote)
    assert vote.signature
    vote.verify("grpc-chain", pv.get_pub_key())


def test_signer_double_sign_refused_over_grpc(signer_pair):
    """FilePV's HRS guard must travel across the transport: a
    conflicting vote at the same HRS is refused, not signed."""
    import hashlib

    from tendermint_tpu.encoding.canonical import (
        SIGNED_MSG_TYPE_PREVOTE,
        Timestamp,
    )
    from tendermint_tpu.privval.remote import RemoteSignerError
    from tendermint_tpu.types.block import BlockID, PartSetHeader, Vote

    client, pv = signer_pair

    def vote_for(salt):
        h = hashlib.sha256(salt).digest()
        return Vote(
            type=SIGNED_MSG_TYPE_PREVOTE,
            height=9,
            round=0,
            block_id=BlockID(h, PartSetHeader(1, h)),
            timestamp=Timestamp.from_unix_ns(1_700_000_000_000_000_000),
            validator_address=pv.get_pub_key().address(),
            validator_index=0,
        )

    client.sign_vote("grpc-chain", vote_for(b"block-a"))
    with pytest.raises(RemoteSignerError):
        client.sign_vote("grpc-chain", vote_for(b"block-b"))


def test_signer_chain_id_mismatch(signer_pair):
    from tendermint_tpu.privval.grpc import GrpcSignerClient
    from tendermint_tpu.privval.remote import RemoteSignerError

    client, _ = signer_pair
    host, port = client._chan._addr
    wrong = GrpcSignerClient(host, port, "other-chain")
    try:
        with pytest.raises(RemoteSignerError, match="chain id"):
            wrong.get_pub_key()
    finally:
        wrong.close()


# --- config selection -------------------------------------------------------


def test_proxy_app_grpc_selected(tmp_path, monkeypatch):
    from tendermint_tpu.abci.grpc_client import GrpcClient
    from tendermint_tpu.cli import _make_app_client
    from tendermint_tpu.config import Config

    cfg = Config()
    cfg.base.proxy_app = "grpc://127.0.0.1:29999"
    client = _make_app_client(cfg)
    assert isinstance(client, GrpcClient)


# --- full node over both gRPC transports ------------------------------------


def test_node_runs_with_grpc_app_and_grpc_signer(tmp_path):
    """A validator whose ABCI app lives behind the gRPC transport AND
    whose key lives in a gRPC remote signer commits blocks — the
    end-to-end wiring of proxy_app="grpc://..." and
    priv_validator_laddr="grpc://..." (node/node.go createPrivval +
    internal/proxy ClientFactory, gRPC flavors)."""
    import time

    from tendermint_tpu.abci.grpc_client import GrpcClient
    from tendermint_tpu.abci.grpc_server import GrpcABCIServer
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.privval.grpc import GrpcSignerServer

    from tests.test_node import fast_genesis

    pv = FilePV.generate(
        str(tmp_path / "gk.json"), str(tmp_path / "gs.json")
    )
    genesis = fast_genesis([pv])

    signer_srv = GrpcSignerServer(pv, genesis.chain_id)
    signer_srv.start()
    abci_srv = GrpcABCIServer(KVStoreApplication())
    abci_srv.start()

    app_client = GrpcClient(*abci_srv.address)
    # Build the node the way cli._build_node does, but in-process.
    from tendermint_tpu.node.node import NodeConfig

    cfg = NodeConfig(
        chain_id=genesis.chain_id,
        listen_addr="127.0.0.1:0",
        wal_enabled=False,
        moniker="grpc-node",
        priv_validator_laddr="grpc://%s:%d" % signer_srv.address,
    )
    node = Node(cfg, genesis, app_client, priv_validator=None)
    node.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and node.height < 2:
            time.sleep(0.05)
        assert node.height >= 2, f"stuck at height {node.height}"
    finally:
        node.stop()
        abci_srv.stop()
        signer_srv.stop()


# --- flow-control accounting ------------------------------------------------


def test_settings_initial_window_applies_to_open_streams():
    """RFC 9113 6.9.2: an INITIAL_WINDOW_SIZE change adjusts every open
    stream's send window by the delta."""
    import socket as socketlib
    import struct

    from tendermint_tpu.libs.grpc import (
        SETTINGS_INITIAL_WINDOW_SIZE,
        _ConnState,
    )

    a, b = socketlib.socketpair()
    try:
        conn = _ConnState(a)
        conn.open_stream(1)
        assert conn.stream_send[1] == 65535
        conn._apply_settings(
            struct.pack("!HI", SETTINGS_INITIAL_WINDOW_SIZE, 100_000)
        )
        assert conn.peer_initial_window == 100_000
        assert conn.stream_send[1] == 65535 + (100_000 - 65535)
        conn._apply_settings(
            struct.pack("!HI", SETTINGS_INITIAL_WINDOW_SIZE, 50_000)
        )
        assert conn.stream_send[1] == 50_000
    finally:
        a.close()
        b.close()


def test_window_update_credits_named_stream_only():
    import socket as socketlib

    from tendermint_tpu.libs.grpc import (
        FRAME_WINDOW_UPDATE,
        _ConnState,
        write_frame,
    )

    a, b = socketlib.socketpair()
    try:
        conn = _ConnState(a)
        conn.open_stream(3)
        base_conn = conn.send_window
        base_stream = conn.stream_send[3]
        write_frame(b, FRAME_WINDOW_UPDATE, 0, 3, (500).to_bytes(4, "big"))
        conn.pump_once()
        assert conn.stream_send[3] == base_stream + 500
        assert conn.send_window == base_conn  # connection window untouched
        write_frame(b, FRAME_WINDOW_UPDATE, 0, 0, (700).to_bytes(4, "big"))
        conn.pump_once()
        assert conn.send_window == base_conn + 700
        assert conn.stream_send[3] == base_stream + 500
    finally:
        a.close()
        b.close()


# --- frame-level protocol regressions ---------------------------------------


def test_client_trailers_split_across_continuation():
    """END_STREAM rides the trailers HEADERS frame, but the header block
    may finish in a CONTINUATION frame. Honoring END_STREAM before
    END_HEADERS loses the trailers — including grpc-status."""
    import socket as socketlib

    from tendermint_tpu.libs.grpc import (
        FLAG_END_HEADERS,
        FLAG_END_STREAM,
        FRAME_CONTINUATION,
        FRAME_DATA,
        FRAME_HEADERS,
        _ConnState,
        grpc_frame,
        read_frame,
        write_frame,
    )

    a, b = socketlib.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    ch = GrpcChannel("127.0.0.1", 1)
    ch._conn = _ConnState(a)  # bypass connect: the peer is scripted

    def fake_server():
        # drain the request until END_STREAM
        while True:
            ftype, flags, sid, frame = read_frame(b)
            if flags & FLAG_END_STREAM:
                break
        hdrs = hpack_encode(
            [(":status", "200"), ("content-type", "application/grpc")]
        )
        write_frame(b, FRAME_HEADERS, FLAG_END_HEADERS, sid, hdrs)
        write_frame(b, FRAME_DATA, 0, sid, grpc_frame(b"ignored"))
        trailers = hpack_encode(
            [("grpc-status", "7"), ("grpc-message", "denied")]
        )
        # END_STREAM on HEADERS, END_HEADERS only on the CONTINUATION
        write_frame(b, FRAME_HEADERS, FLAG_END_STREAM, sid, trailers[:3])
        write_frame(
            b, FRAME_CONTINUATION, FLAG_END_HEADERS, sid, trailers[3:]
        )

    t = threading.Thread(target=fake_server, daemon=True)
    t.start()
    try:
        with pytest.raises(GrpcError) as ei:
            ch.unary("/svc/method", b"req")
        assert ei.value.status == 7
        assert "denied" in ei.value.message
    finally:
        t.join(timeout=5)
        a.close()
        b.close()


def _drive_server_conn(payload_frames):
    """Feed raw bytes (after the client preface) into a GrpcServer
    connection handler and return normally iff the server treated the
    input as a handled protocol error (not an unhandled crash)."""
    import socket as socketlib

    from tendermint_tpu.libs.grpc import PREFACE

    srv = GrpcServer({}, port=0)
    a, b = socketlib.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    try:
        b.sendall(PREFACE + payload_frames)
        b.shutdown(socketlib.SHUT_WR)
        # runs in THIS thread: an uncaught KeyError/IndexError escapes
        # and fails the test
        srv._serve_conn(a)
    finally:
        a.close()
        b.close()
        srv.stop()


def _frame_bytes(ftype, flags, sid, payload):
    import struct

    return struct.pack("!I", len(payload))[1:] + bytes(
        [ftype, flags]
    ) + struct.pack("!I", sid) + payload


def test_server_continuation_without_headers_is_protocol_error():
    from tendermint_tpu.libs.grpc import FLAG_END_HEADERS, FRAME_CONTINUATION

    _drive_server_conn(
        _frame_bytes(
            FRAME_CONTINUATION, FLAG_END_HEADERS, 1, hpack_encode([("a", "b")])
        )
    )


def test_server_continuation_on_wrong_stream_is_protocol_error():
    from tendermint_tpu.libs.grpc import (
        FLAG_END_HEADERS,
        FRAME_CONTINUATION,
        FRAME_HEADERS,
    )

    block = hpack_encode([(":path", "/x")])
    _drive_server_conn(
        _frame_bytes(FRAME_HEADERS, 0, 1, block)
        + _frame_bytes(FRAME_CONTINUATION, FLAG_END_HEADERS, 3, b"")
    )


def test_server_empty_padded_headers_is_protocol_error():
    from tendermint_tpu.libs.grpc import FLAG_END_HEADERS, FLAG_PADDED, FRAME_HEADERS

    _drive_server_conn(
        _frame_bytes(FRAME_HEADERS, FLAG_END_HEADERS | FLAG_PADDED, 1, b"")
    )


def test_strip_padding_rejects_malformed():
    from tendermint_tpu.libs.grpc import (
        FLAG_PADDED,
        H2ProtocolError,
        _strip_padding,
    )

    assert _strip_padding(0, b"") == b""
    assert _strip_padding(FLAG_PADDED, b"\x02abXX") == b"ab"
    with pytest.raises(H2ProtocolError):
        _strip_padding(FLAG_PADDED, b"")
    with pytest.raises(H2ProtocolError):
        _strip_padding(FLAG_PADDED, b"\x05abc")  # pad > remaining payload
    # all-padding is legal and yields empty content
    assert _strip_padding(FLAG_PADDED, b"\x03\x00\x00\x00") == b""


def test_read_frame_rejects_oversized_declared_length():
    """A peer-declared frame length past our advertised
    SETTINGS_MAX_FRAME_SIZE is a typed H2ProtocolError raised from the
    9-byte header alone — before the fix, read_frame would trust the
    declared length and block allocating up to 16MB-1 of peer-chosen
    payload buffer."""
    import socket as socketlib

    from tendermint_tpu.libs.grpc import (
        FRAME_DATA,
        H2ProtocolError,
        MAX_FRAME,
        read_frame,
    )

    a, b = socketlib.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    try:
        hdr = (
            (MAX_FRAME + 1).to_bytes(3, "big")
            + bytes([FRAME_DATA, 0])
            + (1).to_bytes(4, "big")
        )
        b.sendall(hdr)  # header only: the guard must not wait for payload
        with pytest.raises(H2ProtocolError, match="exceeds"):
            read_frame(a)
    finally:
        a.close()
        b.close()


def test_server_rejects_oversized_declared_frame():
    from tendermint_tpu.libs.grpc import FRAME_DATA, MAX_FRAME

    # declared length MAX_FRAME+1 with no payload behind it: the server
    # must fail the connection as a protocol error instead of buffering
    # forever waiting for 16MB that never comes
    hdr = (
        (MAX_FRAME + 1).to_bytes(3, "big")
        + bytes([FRAME_DATA, 0])
        + (1).to_bytes(4, "big")
    )
    _drive_server_conn(hdr)


# --- server loop: split header blocks and padded frames ----------------------
# ROADMAP known debt (ISSUE 6 satellite): pin that PR 5's hardening of
# the SERVER loop holds for the same frame shapes the client loop was
# hardened against — END_STREAM riding a HEADERS frame whose block only
# finishes in a CONTINUATION, and PADDED/PRIORITY decoration.


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise AssertionError("server closed the connection early")
        buf += chunk
    return buf


def _read_response(sock, dec):
    """Read server frames until trailers carrying grpc-status; returns
    (status, concatenated DATA payload)."""
    from tendermint_tpu.libs.grpc import FRAME_DATA, FRAME_HEADERS

    data = b""
    while True:
        head = _recv_exact(sock, 9)
        length = int.from_bytes(b"\x00" + head[:3], "big")
        ftype = head[3]
        payload = _recv_exact(sock, length) if length else b""
        if ftype == FRAME_HEADERS:
            hdrs = dict(dec.decode(payload))
            if "grpc-status" in hdrs:
                return int(hdrs["grpc-status"]), data
        elif ftype == FRAME_DATA:
            data += payload


def _raw_echo_conn():
    """(driver socket, server thread, server, decoder) — a live echo
    GrpcServer connection fed by hand-rolled frames."""
    import socket as socketlib

    from tendermint_tpu.libs.grpc import PREFACE

    srv = GrpcServer({"/t.Svc/Echo": lambda p: p}, port=0)
    a, b = socketlib.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    t = threading.Thread(target=srv._serve_conn, args=(a,), daemon=True)
    t.start()
    b.sendall(PREFACE)
    return b, t, srv, HpackDecoder()


def test_server_request_headers_split_across_continuation_echoes():
    """Request header block split into HEADERS + CONTINUATION (END_HEADERS
    only on the CONTINUATION), body in a DATA frame: the server must
    assemble the block before dispatch and serve the call normally."""
    from tendermint_tpu.libs.grpc import (
        FLAG_END_HEADERS,
        FLAG_END_STREAM,
        FRAME_CONTINUATION,
        FRAME_DATA,
        FRAME_HEADERS,
        grpc_frame,
        grpc_unframe,
    )

    b, t, srv, dec = _raw_echo_conn()
    try:
        block = hpack_encode([(":method", "POST"), (":path", "/t.Svc/Echo")])
        b.sendall(_frame_bytes(FRAME_HEADERS, 0, 1, block[:3]))
        b.sendall(
            _frame_bytes(FRAME_CONTINUATION, FLAG_END_HEADERS, 1, block[3:])
        )
        b.sendall(
            _frame_bytes(FRAME_DATA, FLAG_END_STREAM, 1, grpc_frame(b"ping"))
        )
        status, data = _read_response(b, dec)
        assert status == 0
        assert grpc_unframe(data) == b"ping"
    finally:
        b.close()
        t.join(timeout=5)
        srv.stop()


def test_server_end_stream_before_end_headers_dispatches_once_decoded():
    """END_STREAM rides the HEADERS frame but the block finishes in a
    CONTINUATION: the server must hold the dispatch until END_HEADERS
    (the empty-body call errors *inside* gRPC, with trailers), and the
    connection must stay usable for the next call — honoring END_STREAM
    early or dropping it would either crash the loop or hang the
    stream."""
    from tendermint_tpu.libs.grpc import (
        FLAG_END_HEADERS,
        FLAG_END_STREAM,
        FRAME_CONTINUATION,
        FRAME_DATA,
        FRAME_HEADERS,
        GRPC_INTERNAL,
        grpc_frame,
        grpc_unframe,
    )

    b, t, srv, dec = _raw_echo_conn()
    try:
        block = hpack_encode([(":method", "POST"), (":path", "/t.Svc/Echo")])
        # stream 1: END_STREAM first, END_HEADERS later, no body
        b.sendall(_frame_bytes(FRAME_HEADERS, FLAG_END_STREAM, 1, block[:4]))
        b.sendall(
            _frame_bytes(FRAME_CONTINUATION, FLAG_END_HEADERS, 1, block[4:])
        )
        status, _ = _read_response(b, dec)
        assert status == GRPC_INTERNAL  # empty body = short gRPC message
        # stream 3: a normal call on the SAME connection still round-trips
        # (the HPACK dynamic table and stream bookkeeping were not torn)
        b.sendall(
            _frame_bytes(FRAME_HEADERS, FLAG_END_HEADERS, 3, block)
        )
        b.sendall(
            _frame_bytes(FRAME_DATA, FLAG_END_STREAM, 3, grpc_frame(b"alive"))
        )
        status, data = _read_response(b, dec)
        assert status == 0
        assert grpc_unframe(data) == b"alive"
    finally:
        b.close()
        t.join(timeout=5)
        srv.stop()


def test_server_padded_priority_headers_and_padded_data_echo():
    """PADDED|PRIORITY HEADERS and a PADDED DATA frame: padding and the
    5-byte priority field must be stripped before HPACK/body assembly."""
    from tendermint_tpu.libs.grpc import (
        FLAG_END_HEADERS,
        FLAG_END_STREAM,
        FLAG_PADDED,
        FLAG_PRIORITY,
        FRAME_DATA,
        FRAME_HEADERS,
        grpc_frame,
        grpc_unframe,
    )

    b, t, srv, dec = _raw_echo_conn()
    try:
        block = hpack_encode([(":method", "POST"), (":path", "/t.Svc/Echo")])
        pad = b"\x00" * 4
        priority = b"\x00\x00\x00\x00\x10"  # stream dep 0, weight 16
        b.sendall(
            _frame_bytes(
                FRAME_HEADERS,
                FLAG_END_HEADERS | FLAG_PADDED | FLAG_PRIORITY,
                1,
                bytes([len(pad)]) + priority + block + pad,
            )
        )
        body = grpc_frame(b"pad-me")
        b.sendall(
            _frame_bytes(
                FRAME_DATA,
                FLAG_END_STREAM | FLAG_PADDED,
                1,
                bytes([len(pad)]) + body + pad,
            )
        )
        status, data = _read_response(b, dec)
        assert status == 0
        assert grpc_unframe(data) == b"pad-me"
    finally:
        b.close()
        t.join(timeout=5)
        srv.stop()


# --- torn-connection resilience ---------------------------------------------


def test_server_survives_mid_frame_disconnect(echo_server):
    """A client that vanishes mid-frame (torn TCP connection) must not
    kill the serving thread or the accept loop: later clients on fresh
    connections still get served."""
    import socket as socketlib

    from tendermint_tpu.libs.grpc import PREFACE

    host, port = echo_server.address
    for payload in (
        b"",  # connect + immediate close (no preface)
        PREFACE[: len(PREFACE) // 2],  # torn preface
        # preface + frame header claiming 32 payload bytes, then gone
        PREFACE + b"\x00\x00\x20\x01\x04\x00\x00\x00\x01",
    ):
        s = socketlib.create_connection((host, port))
        if payload:
            s.sendall(payload)
        s.close()
    # the accept loop and handler threads are still alive: a real call
    # on a fresh connection round-trips
    ch = GrpcChannel(host, port)
    try:
        assert ch.unary("/t.Svc/Echo", b"still alive") == b"still alive"
    finally:
        ch.close()


def test_accept_loop_survives_transient_oserror(echo_server):
    """The accept loop retries transient OSErrors (ECONNABORTED from a
    client tearing off mid-handshake) instead of exiting; only stop()/a
    closed listener end it."""

    class FlakyListener:
        """Raises once on accept, then delegates to the real socket."""

        def __init__(self, inner):
            self.inner = inner
            self.n = 0

        def accept(self):
            self.n += 1
            if self.n == 1:
                raise OSError(103, "Software caused connection abort")
            return self.inner.accept()

        def __getattr__(self, name):
            return getattr(self.inner, name)

    host, port = echo_server.address
    proxy = FlakyListener(echo_server._lsock)
    echo_server._lsock = proxy
    # the loop is still blocked in the REAL socket's accept from before
    # the swap: the first call is absorbed there, the next loop
    # iteration reads the proxy and hits the injected OSError
    ch1 = GrpcChannel(host, port)
    try:
        assert ch1.unary("/t.Svc/Echo", b"one") == b"one"
    finally:
        ch1.close()
    ch2 = GrpcChannel(host, port)
    try:
        assert ch2.unary("/t.Svc/Echo", b"two") == b"two"
    finally:
        ch2.close()
    assert proxy.n >= 2  # the error was hit AND retried past
