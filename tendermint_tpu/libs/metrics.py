"""Prometheus-style metrics: registry, instruments, text exposition.

The reference generates per-package metric structs with ``metricsgen``
(e.g. internal/consensus/metrics.gen.go) and serves a node-level
registry over HTTP (node/node.go:575-605). Here the instruments are
hand-rolled — Counter, Gauge, Histogram with label support — gathered
into the standard text exposition format and served by the RPC server
at ``GET /metrics``.

Every subsystem struct offers ``nop()`` so library construction without
a registry measures nothing and costs (almost) nothing — the same role
as the reference's NopMetrics constructors.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

NAMESPACE = "tendermint"

# Flight-recorder sink (libs/flightrec installs itself here): counter
# increments and gauge sets mirror into the post-mortem ring. Read
# racily on the hot path, same contract as the tracer's observer slot —
# a mid-install event lands in the old or new sink, either is fine.
_flight_sink: Optional[Callable[[str, Tuple, float], None]] = None


def set_flight_sink(fn: Optional[Callable[[str, Tuple, float], None]]) -> None:
    global _flight_sink
    _flight_sink = fn


def _flight_note(name: str, key: Tuple, value: float) -> None:
    sink = _flight_sink
    if sink is not None:
        try:
            sink(name, key, value)
        except Exception:
            pass  # the post-mortem ring must never fail a metric write

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _escape(v: str) -> str:
    # Prometheus text format: label values escape backslash, quote, LF.
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def collect(self) -> List[str]:  # exposition lines
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple, float] = {}  # guarded-by: _lock

    def labels(self, **labels: str) -> "_BoundCounter":
        return _BoundCounter(self, _label_key(labels))

    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def collect(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            # A labeled metric with no samples exposes no series — a
            # synthetic unlabeled `name 0` line would be invalid for it.
            if self.label_names:
                return []
            items = [((), 0.0)]
        return [
            f"{self.name}{_label_str(k)} {_fmt(v)}" for k, v in items
        ]


class _BoundCounter:
    __slots__ = ("_m", "_k")

    def __init__(self, metric: Counter, key: Tuple):
        self._m = metric
        self._k = key

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._m._lock:
            self._m._values[self._k] = self._m._values.get(self._k, 0.0) + n
        _flight_note(self._m.name, self._k, n)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple, float] = {}  # guarded-by: _lock

    def labels(self, **labels: str) -> "_BoundGauge":
        return _BoundGauge(self, _label_key(labels))

    def set(self, v: float) -> None:
        self.labels().set(v)

    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self.labels().inc(-n)

    def collect(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            if self.label_names:
                return []
            items = [((), 0.0)]
        return [
            f"{self.name}{_label_str(k)} {_fmt(v)}" for k, v in items
        ]


class _BoundGauge:
    __slots__ = ("_m", "_k")

    def __init__(self, metric: Gauge, key: Tuple):
        self._m = metric
        self._k = key

    def set(self, v: float) -> None:
        with self._m._lock:
            self._m._values[self._k] = float(v)
        _flight_note(self._m.name, self._k, v)

    def inc(self, n: float = 1.0) -> None:
        with self._m._lock:
            v = self._m._values.get(self._k, 0.0) + n
            self._m._values[self._k] = v
        _flight_note(self._m.name, self._k, v)

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        # per label key: (bucket counts, sum, count)
        self._values: Dict[Tuple, Tuple[List[int], float, int]] = {}  # guarded-by: _lock
        # per (label key, bucket index): last (exemplar labels, value,
        # unix ts) — bounded by keys x (buckets+1), OpenMetrics-style
        self._exemplars: Dict[Tuple[Tuple, int], Tuple[Dict[str, str], float, float]] = {}  # guarded-by: _lock

    def labels(self, **labels: str) -> "_BoundHistogram":
        return _BoundHistogram(self, _label_key(labels))

    def observe(self, v: float, exemplar: Optional[Dict[str, str]] = None) -> None:
        self.labels().observe(v, exemplar=exemplar)

    def has_exemplars(self) -> bool:
        with self._lock:
            return bool(self._exemplars)

    def collect(self, exemplars: bool = False) -> List[str]:
        with self._lock:
            # deep-copy counts: observe() mutates the aliased list in
            # place, and a torn snapshot yields non-monotonic buckets
            items = sorted(
                (k, (list(c), t, n))
                for k, (c, t, n) in self._values.items()
            )
            exem = dict(self._exemplars) if exemplars else {}
        out: List[str] = []
        for key, (counts, total, n) in items:
            cum = 0
            for i, (b, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                lk = dict(key)
                lk["le"] = _fmt(b)
                line = f"{self.name}_bucket{_label_str(_label_key(lk))} {cum}"
                out.append(line + _exemplar_suffix(exem.get((key, i))))
            lk = dict(key)
            lk["le"] = "+Inf"
            line = f"{self.name}_bucket{_label_str(_label_key(lk))} {n}"
            out.append(
                line + _exemplar_suffix(exem.get((key, len(self.buckets))))
            )
            out.append(f"{self.name}_sum{_label_str(key)} {_fmt(total)}")
            out.append(f"{self.name}_count{_label_str(key)} {n}")
        return out


def _exemplar_suffix(
    ex: Optional[Tuple[Dict[str, str], float, float]]
) -> str:
    """OpenMetrics exemplar rendering: `` # {trace_id="..."} v ts``.
    Empty when the bucket has no exemplar (plain exposition stays
    byte-identical unless exemplars were requested AND recorded)."""
    if ex is None:
        return ""
    labels, v, ts = ex
    inner = ",".join(f'{k}="{_escape(val)}"' for k, val in sorted(labels.items()))
    return " # {%s} %s %s" % (inner, _fmt(round(v, 9)), _fmt(round(ts, 3)))


class _BoundHistogram:
    __slots__ = ("_m", "_k")

    def __init__(self, metric: Histogram, key: Tuple):
        self._m = metric
        self._k = key

    def observe(self, v: float, exemplar: Optional[Dict[str, str]] = None) -> None:
        m = self._m
        bucket = len(m.buckets)  # +Inf
        with m._lock:
            counts, total, n = m._values.get(
                self._k, ([0] * len(m.buckets), 0.0, 0)
            )
            for i, b in enumerate(m.buckets):
                if v <= b:
                    counts[i] += 1
                    bucket = i
                    break
            m._values[self._k] = (counts, total + v, n + 1)
            if exemplar:
                m._exemplars[(self._k, bucket)] = (
                    dict(exemplar), v, time.time()
                )


class Registry:
    """Collects metrics and renders the text exposition format."""

    def __init__(self):
        self._metrics: List[_Metric] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if any(m.name == metric.name for m in self._metrics):
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics.append(metric)
        return metric

    def counter(self, name: str, help_: str, labels: Sequence[str] = ()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str, labels: Sequence[str] = ()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help_: str,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self.register(Histogram(name, help_, labels, buckets))  # type: ignore[return-value]

    def expose(self, exemplars: bool = False) -> str:
        """Text exposition; ``exemplars=True`` appends OpenMetrics-style
        trace-ID exemplars to histogram bucket lines (the default stays
        plain-Prometheus-parseable)."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if exemplars and isinstance(m, Histogram):
                lines.extend(m.collect(exemplars=True))
            else:
                lines.extend(m.collect())
        return "\n".join(lines) + "\n"


# --- per-subsystem metric structs (metrics.gen.go analogs) -------------------


def _name(subsystem: str, name: str) -> str:
    return f"{NAMESPACE}_{subsystem}_{name}"


class _NopMixin:
    """Shared, cached no-op instance per metrics class (NOP_LOGGER's
    pattern): library construction without a registry costs one
    allocation total, not a throwaway registry per component."""

    @classmethod
    def nop(cls):
        inst = cls.__dict__.get("_nop_instance")
        if inst is None:
            inst = cls(None)
            cls._nop_instance = inst
        return inst


class ConsensusMetrics(_NopMixin):
    """internal/consensus/metrics.gen.go (core subset)."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "consensus"
        self.height = reg.gauge(_name(s, "height"), "Height of the chain.")
        self.rounds = reg.gauge(
            _name(s, "rounds"), "Number of rounds at the latest height."
        )
        self.validators = reg.gauge(
            _name(s, "validators"), "Number of validators."
        )
        self.missing_validators = reg.gauge(
            _name(s, "missing_validators"),
            "Number of validators who did not sign the last block.",
        )
        self.byzantine_validators = reg.gauge(
            _name(s, "byzantine_validators"),
            "Number of validators who tried to double sign.",
        )
        self.block_interval_seconds = reg.histogram(
            _name(s, "block_interval_seconds"),
            "Time between this and the last block.",
        )
        self.num_txs = reg.gauge(
            _name(s, "num_txs"), "Number of transactions in the latest block."
        )
        self.block_size_bytes = reg.gauge(
            _name(s, "block_size_bytes"), "Size of the latest block in bytes."
        )
        self.total_txs = reg.counter(
            _name(s, "total_txs"), "Total number of transactions committed."
        )
        self.wal_writes = reg.counter(
            _name(s, "wal_writes"), "Consensus WAL records written."
        )
        # Fed by the tracer's metrics observer (libs/tracing.py): one
        # observation per consensus step span, same clock as the trace.
        self.step_duration_seconds = reg.histogram(
            _name(s, "step_duration_seconds"),
            "Wall-clock duration of consensus step transitions, seconds.",
            labels=("step",),
        )



class P2PMetrics(_NopMixin):
    """internal/p2p/metrics.gen.go (core subset)."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "p2p"
        self.peers = reg.gauge(_name(s, "peers"), "Number of connected peers.")
        self.message_receive_bytes_total = reg.counter(
            _name(s, "message_receive_bytes_total"),
            "Total bytes received from peers.",
            labels=("chID",),
        )
        self.message_send_bytes_total = reg.counter(
            _name(s, "message_send_bytes_total"),
            "Total bytes sent to peers.",
            labels=("chID",),
        )



class MempoolMetrics(_NopMixin):
    """internal/mempool/metrics.gen.go (core subset)."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "mempool"
        self.size = reg.gauge(
            _name(s, "size"), "Number of uncommitted transactions."
        )
        self.tx_size_bytes = reg.histogram(
            _name(s, "tx_size_bytes"),
            "Transaction sizes in bytes.",
            buckets=(1, 16, 64, 256, 1024, 4096, 16384, 65536, 262144, 1048576),
        )
        self.failed_txs = reg.counter(
            _name(s, "failed_txs"), "Number of failed CheckTx."
        )
        self.evicted_txs = reg.counter(
            _name(s, "evicted_txs"), "Number of evicted transactions."
        )



class OpsMetrics(_NopMixin):
    """Accelerator verification path: device health state machine
    (ops/device_policy.py), per-engine CPU fallbacks, probe latency.
    No metrics.gen.go analog — the reference has no device boundary."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "ops"
        self.device_health_state = reg.gauge(
            _name(s, "device_health_state"),
            "Device health state: 0=healthy 1=degraded 2=cooldown 3=disabled.",
        )
        self.device_transitions = reg.counter(
            _name(s, "device_health_transitions_total"),
            "Device health state transitions.",
            labels=("from_state", "to_state"),
        )
        self.device_failures = reg.counter(
            _name(s, "device_failures_total"),
            "Device-path failures by classification.",
            labels=("kind",),
        )
        self.device_fallbacks = reg.counter(
            _name(s, "device_fallbacks_total"),
            "Batches (or chunks) served by the CPU fallback path.",
            labels=("engine",),
        )
        self.device_fallback_lanes = reg.counter(
            _name(s, "device_fallback_lanes_total"),
            "Signature lanes served by the CPU fallback path.",
            labels=("engine",),
        )
        self.device_probe_seconds = reg.histogram(
            _name(s, "device_probe_seconds"),
            "Latency of half-open re-probe attempts, seconds.",
        )
        # Validator-set precompute cache (ops/precompute.py).
        self.precompute_hits = reg.counter(
            _name(s, "precompute_hits_total"),
            "Lanes served from the per-validator precompute table cache.",
        )
        self.precompute_misses = reg.counter(
            _name(s, "precompute_misses_total"),
            "Lanes that needed an in-kernel table build (cache miss).",
        )
        self.precompute_builds = reg.counter(
            _name(s, "precompute_builds_total"),
            "Host-side precompute table builds.",
        )
        self.precompute_evictions = reg.counter(
            _name(s, "precompute_evictions_total"),
            "Precompute table entries evicted by the LRU bound.",
        )
        self.precompute_invalidations = reg.counter(
            _name(s, "precompute_invalidations_total"),
            "Precompute table entries dropped on validator-set rotation.",
        )
        self.table_build_seconds = reg.histogram(
            _name(s, "table_build_seconds"),
            "Latency of host-side precompute table builds, seconds.",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05),
        )
        # Digest-keyed verification result cache (ops/precompute.py).
        self.result_cache_hits = reg.counter(
            _name(s, "result_cache_hits_total"),
            "Verifications answered from the digest-keyed result cache.",
        )
        self.result_cache_misses = reg.counter(
            _name(s, "result_cache_misses_total"),
            "Verifications that missed the digest-keyed result cache.",
        )
        # Device-resident table store (ops/resident.py) and the fused
        # kernel campaign: per-batch table shipping vs resident gather,
        # on-device challenge hashing, autotuned field-mul selection.
        self.table_resident_hits = reg.counter(
            _name(s, "table_resident_hits_total"),
            "Lanes served by the device-resident table store "
            "(gather indices shipped, no per-batch table H2D).",
        )
        self.table_resident_misses = reg.counter(
            _name(s, "table_resident_misses_total"),
            "Cached-table lanes absent from the resident store "
            "(shipped via the per-batch gathered path).",
        )
        self.table_h2d_bytes = reg.counter(
            _name(s, "table_h2d_bytes_total"),
            "Precompute table bytes shipped host-to-device "
            "(resident uploads plus per-batch gathered tensors).",
        )
        self.hash_device_lanes = reg.counter(
            _name(s, "hash_device_lanes_total"),
            "Challenge scalars computed by the on-device SHA-512 kernel.",
        )
        self.autotune_selections = reg.counter(
            _name(s, "autotune_selections_total"),
            "Field-mul impl selections adopted by the autotuner, "
            "per (platform, batch-bucket) key.",
            labels=("impl",),
        )
        # Mesh-sharded verify engine (parallel/mesh.py): which mesh the
        # sharded path is running on and how lanes spread across it.
        self.mesh_devices = reg.gauge(
            _name(s, "mesh_devices"),
            "Devices in the most recently dispatched verify mesh "
            "(0 = sharding unused).",
        )
        self.mesh_dispatches = reg.counter(
            _name(s, "mesh_dispatches_total"),
            "Lane-sharded chunk dispatches, by mesh size.",
            labels=("devices",),
        )
        self.mesh_lanes = reg.counter(
            _name(s, "mesh_lanes_total"),
            "Padded signature lanes dispatched per device of the mesh.",
            labels=("device",),
        )
        self.mesh_exclusions = reg.counter(
            _name(s, "mesh_exclusions_total"),
            "Devices excluded from the mesh after an attributed failure.",
            labels=("device",),
        )
        self.mesh_readmissions = reg.counter(
            _name(s, "mesh_readmissions_total"),
            "Excluded devices re-admitted after a successful probe.",
            labels=("device",),
        )
        # Per-stage pipeline timing, fed by the tracer's metrics
        # observer (libs/tracing.py): every span tagged stage+engine
        # lands exactly one observation here.
        self.verify_stage_seconds = reg.histogram(
            _name(s, "verify_stage_seconds"),
            "Per-stage latency of the batch verify pipeline, seconds.",
            labels=("stage", "engine"),
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )
        self.inflight_lanes = reg.gauge(
            _name(s, "inflight_lanes"),
            "Signature lanes currently dispatched to the device.",
            labels=("engine",),
        )
        # Device-tier introspection (ops/introspect.py). owner values
        # are a closed set (resident_tables, shm_slabs) plus
        # resident_tables/<tenant>, whose tenant names are already
        # sanitized+capped by verifyd admission; bucket labels come
        # exclusively from introspect.bucket_label (power-of-two,
        # "other" overflow — tpulint TPM004 audits every call site), so
        # all three families are cardinality-bounded by construction.
        self.device_bytes = reg.gauge(
            _name(s, "device_bytes"),
            "Device-resident bytes currently held, by owner.",
            labels=("owner",),
        )
        self.compile_events = reg.counter(
            _name(s, "compile_events_total"),
            "XLA kernel (re)compilations observed, by engine.",
            labels=("engine",),
        )
        self.kernel_bucket_seconds = reg.histogram(
            _name(s, "kernel_bucket_seconds"),
            "Kernel dispatch wall time by engine and power-of-two"
            " batch bucket (continuous profiler).",
            labels=("engine", "bucket"),
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )


class VerifydMetrics(_NopMixin):
    """The verifyd verification service (verifyd/server.py): shared-
    scheduler serving metrics — queue depth and sheds by priority
    class, batch occupancy, flush reasons, wire latency. No reference
    analog; the shape follows inference-serving practice."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "verifyd"
        self.queue_depth = reg.gauge(
            _name(s, "queue_depth"),
            "Lanes pending in the shared scheduler, by priority class.",
            labels=("klass",),
        )
        self.admission_rejections = reg.counter(
            _name(s, "admission_rejections_total"),
            "Requests shed by the admission controller.",
            labels=("klass", "reason"),
        )
        self.requests = reg.counter(
            _name(s, "requests_total"),
            "Wire requests served, by request kind and response status.",
            labels=("kind", "status"),
        )
        self.lanes = reg.counter(
            _name(s, "lanes_total"),
            "Signature lanes accepted into the scheduler, by class.",
            labels=("klass",),
        )
        self.request_seconds = reg.histogram(
            _name(s, "request_seconds"),
            "Wire latency per request (decode to respond), seconds.",
            labels=("kind",),
        )
        self.batch_occupancy = reg.histogram(
            _name(s, "batch_occupancy"),
            "Lanes per scheduler flush (cross-client batch size).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        )
        self.flushes = reg.counter(
            _name(s, "flushes_total"),
            "Scheduler flushes, by trigger reason (size/deadline/shutdown).",
            labels=("reason",),
        )
        self.cross_client_flushes = reg.counter(
            _name(s, "cross_client_flushes_total"),
            "Flushes whose lanes came from more than one client connection.",
            labels=("reason",),
        )
        self.dispatch_occupancy = reg.histogram(
            _name(s, "dispatch_occupancy"),
            "Outstanding dispatches (queued + in flight) at each"
            " scheduler hand-off — the continuous-batching pipeline"
            " depth.",
            buckets=(1, 2, 3, 4, 6, 8),
        )
        self.brownout_level = reg.gauge(
            _name(s, "brownout_level"),
            "Current degradation-ladder rung (0=normal .."
            " 5=host_consensus).",
        )
        self.brownout_transitions = reg.counter(
            _name(s, "brownout_transitions_total"),
            "Degradation-ladder moves, by direction (up/down).",
            labels=("direction",),
        )
        # tenant labels are sanitized AND capped server-side (at most
        # max_tenants distinct values, overflow collapses to "other"),
        # so this family's cardinality is bounded by construction
        self.tenant_lanes = reg.counter(
            _name(s, "tenant_lanes_total"),
            "Signature lanes admitted, by tenant namespace.",
            labels=("tenant",),
        )
        self.tenant_rejections = reg.counter(
            _name(s, "tenant_rejections_total"),
            "Requests shed, by tenant namespace and shed reason.",
            labels=("tenant", "reason"),
        )
        self.tenant_queue_depth = reg.gauge(
            _name(s, "tenant_queue_depth"),
            "Outstanding (admitted, unresolved) lanes, by tenant.",
            labels=("tenant",),
        )
        self.tenant_request_seconds = reg.histogram(
            _name(s, "tenant_request_seconds"),
            "Wire latency per request, by tenant namespace.",
            labels=("tenant",),
        )
        # Client-side end-to-end latency attribution (verifyd/client.py):
        # the server's per-response stage-time vector observed one
        # histogram sample per stage, with trace-ID exemplars linking a
        # bucket back to the causal trace (ISSUE 15).
        self.e2e_stage_seconds = reg.histogram(
            _name(s, "e2e_stage_seconds"),
            "Per-stage share of verifyd request latency as attributed"
            " by the server's stage-time vector, seconds.",
            labels=("stage",),
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )
        self.host_direct_lanes = reg.counter(
            _name(s, "host_direct_lanes_total"),
            "Consensus lanes verified on the host oracle by the"
            " brownout ladder's shrink_shares/host_consensus rungs.",
        )
        # shared-memory slab-ring ingress (verifyd/shm.py)
        self.shm_lanes = reg.counter(
            _name(s, "shm_lanes_total"),
            "Signature lanes that arrived through the shared-memory"
            " slab-ring transport (before admission).",
        )
        self.shm_fallbacks = reg.counter(
            _name(s, "shm_fallbacks_total"),
            "Shm attach/session failures that pushed a caller back onto"
            " the TCP path.",
        )
        self.shm_torn_slabs = reg.counter(
            _name(s, "shm_torn_slabs_total"),
            "Committed slabs rejected by the seqlock generation check"
            " (writer died or raced mid-write); each one is answered"
            " with an explicit INVALID, never dropped silently.",
        )
        self.shm_ring_occupancy = reg.gauge(
            _name(s, "shm_ring_occupancy"),
            "Lanes committed to slab rings and not yet drained into the"
            " scheduler, summed over live shm sessions.",
        )


class EvloopMetrics(_NopMixin):
    """The shared selector event loop (libs/evloop.py): connection
    gauge per server so operators can see 10k sockets multiplexing onto
    one loop thread. No reference analog — the reference is
    thread-per-connection."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "evloop"
        self.connections = reg.gauge(
            _name(s, "connections"),
            "Open connections multiplexed on the event loop, per server.",
            labels=("server",),
        )


class LightMetrics(_NopMixin):
    """The light-client serving tier (light/cache.py, lightd): verified-
    header cache traffic, bisection depth, and end-to-end serve latency.
    No metrics.gen.go analog; the shape follows the PR 9 serving SLOs."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "light"
        self.cache_hits = reg.counter(
            _name(s, "cache_hits_total"),
            "Verified-header cache hits.",
        )
        self.cache_misses = reg.counter(
            _name(s, "cache_misses_total"),
            "Verified-header cache misses.",
        )
        self.cache_evictions = reg.counter(
            _name(s, "cache_evictions_total"),
            "Verified-header cache entries evicted (LRU or invalidation).",
        )
        self.bisection_rounds = reg.histogram(
            _name(s, "bisection_rounds"),
            "Scheduler super-batch rounds per skipping verification.",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
        )
        self.serve_latency_seconds = reg.histogram(
            _name(s, "serve_latency_seconds"),
            "End-to-end light_header serve latency, seconds.",
            labels=("outcome",),
        )


class StateMetrics(_NopMixin):
    """internal/state/metrics.gen.go."""

    def __init__(self, reg: Optional[Registry]):
        reg = reg or Registry()
        s = "state"
        self.block_processing_time = reg.histogram(
            _name(s, "block_processing_time"),
            "Time spent processing FinalizeBlock, seconds.",
        )
        self.consensus_param_updates = reg.counter(
            _name(s, "consensus_param_updates"),
            "Number of consensus parameter updates by the application.",
        )
        self.validator_set_updates = reg.counter(
            _name(s, "validator_set_updates"),
            "Number of validator set updates by the application.",
        )

