"""Seeded fuzz tests (test/fuzz analog: mempool, secretconnection,
jsonrpc targets, plus this build's own wire surfaces).

Contract under fuzz: decoders and servers either parse or raise a
CONTROLLED error — never segfault, hang, or leak an unexpected exception
type past their documented boundary. Deterministic seeds keep failures
reproducible.
"""

import json
import struct

import numpy as np
import pytest

from tendermint_tpu.types.block import Block, BlockID, Commit, Header, Proposal, Vote

SEED = 20260730
N_CASES = 300


def _rng():
    return np.random.default_rng(SEED)


def _random_blobs(rng, n, max_len=512):
    for _ in range(n):
        ln = int(rng.integers(0, max_len))
        yield bytes(rng.integers(0, 256, ln, dtype="uint8"))


def _mutations(rng, valid: bytes, n):
    """Bit flips, truncations, extensions, splices of a valid encoding."""
    for _ in range(n):
        b = bytearray(valid)
        op = int(rng.integers(0, 4))
        if op == 0 and b:
            b[int(rng.integers(0, len(b)))] ^= int(rng.integers(1, 256))
        elif op == 1 and b:
            del b[int(rng.integers(0, len(b))) :]
        elif op == 2:
            b += bytes(rng.integers(0, 256, int(rng.integers(1, 64)), dtype="uint8"))
        elif op == 3 and len(b) > 8:
            i = int(rng.integers(0, len(b) - 4))
            b[i : i + 4] = bytes(rng.integers(0, 256, 4, dtype="uint8"))
        yield bytes(b)


class TestProtoDecoders:
    """Wire decoders fed garbage must raise ValueError-family errors
    (or parse), never anything else."""

    ALLOWED = (ValueError, KeyError, IndexError, struct.error, OverflowError)

    def _hammer(self, decode, corpus):
        for blob in corpus:
            try:
                decode(blob)
            except self.ALLOWED:
                pass

    def test_vote_decoder(self):
        rng = _rng()
        valid = Vote(type=1, height=5, round=0).to_proto_bytes()
        self._hammer(
            Vote.from_proto_bytes,
            list(_random_blobs(rng, N_CASES)) + list(_mutations(rng, valid, N_CASES)),
        )

    def test_proposal_decoder(self):
        rng = _rng()
        valid = Proposal(height=5, round=0, pol_round=-1).to_proto_bytes()
        self._hammer(
            Proposal.from_proto_bytes,
            list(_random_blobs(rng, N_CASES)) + list(_mutations(rng, valid, N_CASES)),
        )

    def test_header_and_block_decoders(self):
        rng = _rng()
        self._hammer(Header.from_proto_bytes, _random_blobs(rng, N_CASES))
        self._hammer(Block.from_proto_bytes, _random_blobs(rng, N_CASES))
        self._hammer(Commit.from_proto_bytes, _random_blobs(rng, N_CASES))
        self._hammer(BlockID.from_proto_bytes, _random_blobs(rng, N_CASES))

    def test_pubkey_decoder(self):
        from tendermint_tpu.crypto.keys import pubkey_from_proto

        rng = _rng()
        self._hammer(pubkey_from_proto, _random_blobs(rng, N_CASES))


class TestWALFuzz:
    def test_torn_and_corrupt_tails_recoverable(self, tmp_path):
        """internal/consensus/wal_fuzz.go analog: arbitrary garbage after
        (or inside) the tail never prevents start + replay of the intact
        prefix."""
        from tendermint_tpu.consensus.wal import (
            WAL,
            EndHeightMessage,
            WALCorruptionError,
        )

        rng = _rng()
        for trial in range(20):
            path = str(tmp_path / f"wal{trial}")
            w = WAL(path)
            w.start()
            for h in range(1, 6):
                w.write_sync(EndHeightMessage(h))
            w.stop()
            with open(path, "ab") as fh:
                fh.write(
                    bytes(rng.integers(0, 256, int(rng.integers(1, 40)), dtype="uint8"))
                )
            w2 = WAL(path)
            w2.start()  # torn-tail repair must not raise
            msgs = list(w2.iter_messages())
            heights = [
                m.height for _, m in msgs if isinstance(m, EndHeightMessage)
            ]
            # the intact prefix must replay fully: repair only trims the
            # appended garbage, never valid records before it
            assert heights == [1, 2, 3, 4, 5], heights
            w2.stop()


class TestSecretConnectionFuzz:
    def test_garbage_handshake_rejected(self):
        """p2p_secretconnection fuzz target: a peer speaking garbage at
        any handshake stage produces a clean failure."""
        from tendermint_tpu.crypto.keys import Ed25519PrivKey
        from tendermint_tpu.p2p.secret_connection import (
            SecretConnection,
            SecretConnectionError,
        )

        rng = _rng()

        class GarbageStream:
            def __init__(self, blob):
                self.blob = bytearray(blob)

            def sendall(self, data):
                pass

            def recv_exact(self, n):
                if len(self.blob) < n:
                    raise ConnectionError("eof")
                out = bytes(self.blob[:n])
                del self.blob[:n]
                return out

        priv = Ed25519PrivKey.generate()
        for blob in _random_blobs(rng, 60, max_len=600):
            with pytest.raises(
                (SecretConnectionError, ConnectionError, ValueError)
            ):
                SecretConnection(GarbageStream(blob), priv)


class TestRPCServerFuzz:
    @pytest.fixture(scope="class")
    def server(self):
        from tendermint_tpu.rpc.server import RPCServer

        srv = RPCServer({"echo": lambda **kw: kw})
        srv.start()
        yield srv
        srv.stop()

    def test_malformed_json_bodies(self, server):
        """rpc_jsonrpc_server fuzz target: arbitrary POST bodies always
        get an HTTP response, never kill the server."""
        import urllib.request

        rng = _rng()
        url = server.url
        for blob in _random_blobs(rng, 60, max_len=200):
            req = urllib.request.Request(
                url, blob, {"Content-Type": "application/json"}
            )
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    resp.read()
            except urllib.error.HTTPError:
                pass
        # server still alive and correct afterward
        body = json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": "echo", "params": {"a": 1}}
        ).encode()
        with urllib.request.urlopen(
            urllib.request.Request(url, body, {"Content-Type": "application/json"}),
            timeout=5,
        ) as resp:
            doc = json.load(resp)
        assert doc["result"] == {"a": 1}

    def test_deterministic_malformed_cases(self, server):
        """The specific failure classes the fuzzer uncovered, pinned:
        invalid UTF-8 -> parse error; valid-JSON non-objects -> invalid
        request; batches with scalar entries -> per-entry invalid."""
        import urllib.request

        def post(body):
            req = urllib.request.Request(
                server.url, body, {"Content-Type": "application/json"}
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                return json.load(resp)

        assert post(b"\xb1\xff\xfe")["error"]["code"] == -32700
        assert post(b"42")["error"]["code"] == -32600
        assert post(b'"a string"')["error"]["code"] == -32600
        assert post(b"null")["error"]["code"] == -32600
        assert post(b"[]")["error"]["code"] == -32600  # empty batch
        batch = post(b'[7, {"jsonrpc":"2.0","id":1,"method":"echo","params":{}}]')
        assert batch[0]["error"]["code"] == -32600
        assert batch[1]["result"] == {}


class TestMConnFuzz:
    def test_garbage_frames_error_cleanly(self):
        """Feeding random frames into MConnection's recv routine must end
        in on_error, not a hang or stray exception."""
        import queue as queue_mod
        import time

        from tendermint_tpu.p2p.mconn import MConnection

        rng = _rng()
        for trial in range(20):
            frames = list(_random_blobs(rng, 10, max_len=100))
            frames_q: "queue_mod.Queue" = queue_mod.Queue()
            for f in frames:
                frames_q.put(f)
            errs = []
            conn = MConnection(
                send_frame=lambda b: None,
                recv_frame=lambda: frames_q.get(timeout=2),
                on_receive=lambda c, m: None,
                on_error=errs.append,
            )
            conn.start()
            deadline = time.monotonic() + 5
            while not errs and time.monotonic() < deadline:
                time.sleep(0.01)
            conn.stop()
            assert errs, f"trial {trial}: garbage frames never errored"
            # the error must come from a rejected frame, not from the
            # feed queue draining (queue.Empty also routes to on_error)
            assert "Empty" not in str(errs[0]), errs[0]
