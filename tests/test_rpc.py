"""RPC + eventbus + indexer integration.

Covers: query language (internal/pubsub/query), pubsub fanout, event
log long-poll, the JSON-RPC server routes against a live single-node
chain (internal/rpc/core/routes.go surface), the HTTP client, and the
light-client HTTP provider building verifiable LightBlocks over RPC.
"""

import threading
import time

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.eventbus import EventBus, EventDataTx, EVENT_TX, QUERY_TX
from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs.pubsub import PubSubServer, Query, QueryError
from tendermint_tpu.node import Node, NodeConfig
from tendermint_tpu.privval import FilePV
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.params import ConsensusParams, TimeoutParams

CHAIN = "rpc-chain"
BASE_NS = 1_700_000_000_000_000_000


# --- query language ---------------------------------------------------------


class TestQuery:
    def test_equality_string(self):
        q = Query.parse("tm.event = 'NewBlock'")
        assert q.matches({"tm.event": ["NewBlock"]})
        assert not q.matches({"tm.event": ["Tx"]})
        assert not q.matches({})

    def test_and(self):
        q = Query.parse("tm.event = 'Tx' AND tx.height = 5")
        assert q.matches({"tm.event": ["Tx"], "tx.height": ["5"]})
        assert not q.matches({"tm.event": ["Tx"], "tx.height": ["6"]})

    def test_numeric_comparisons(self):
        q = Query.parse("tx.height > 3 AND tx.height <= 10")
        assert q.matches({"tx.height": ["4"]})
        assert q.matches({"tx.height": ["10"]})
        assert not q.matches({"tx.height": ["3"]})
        assert not q.matches({"tx.height": ["11"]})

    def test_exists_and_contains(self):
        q = Query.parse("transfer.amount EXISTS")
        assert q.matches({"transfer.amount": ["7"]})
        assert not q.matches({"other": ["7"]})
        q2 = Query.parse("tx.hash CONTAINS 'AB'")
        assert q2.matches({"tx.hash": ["00ABFF"]})
        assert not q2.matches({"tx.hash": ["0011"]})

    def test_parse_errors(self):
        for bad in ("", "AND", "tm.event =", "= 'x'", "a = 'b' OR c = 'd'"):
            with pytest.raises(QueryError):
                Query.parse(bad)


class TestPubSub:
    def test_fanout_and_unsubscribe(self):
        srv = PubSubServer()
        s1 = srv.subscribe("a", "tm.event = 'X'")
        s2 = srv.subscribe("b", "tm.event = 'Y'")
        srv.publish("m1", {"tm.event": ["X"]})
        srv.publish("m2", {"tm.event": ["Y"]})
        assert s1.next(timeout=1).data == "m1"
        assert s2.next(timeout=1).data == "m2"
        assert s1.next(timeout=0.05) is None
        srv.unsubscribe_all("a")
        assert srv.num_subscriptions() == 1

    def test_eventlog_truncation_resume(self):
        """A truncated scan must hand back a resume cursor that skips
        nothing (code-review finding: oldest-kept + global-newest lost
        the tail)."""
        bus = EventBus()
        for i in range(10):
            bus.publish_event_tx(
                EventDataTx(height=i, index=0, tx=b"x%d" % i, result=abci.ExecTxResult())
            )
        items, more, resume = bus.eventlog.scan(max_items=4)
        assert [it.data.height for it in items] == [0, 1, 2, 3]
        assert more is True
        seen = [it.data.height for it in items]
        while more:
            items, more, resume = bus.eventlog.scan(after=resume, max_items=4)
            seen.extend(it.data.height for it in items)
        assert seen == list(range(10))

    def test_eventlog_longpoll(self):
        bus = EventBus()
        got = []

        def waiter():
            items, more, resume = bus.eventlog.scan(wait=5.0)
            got.extend(items)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        bus.publish_event_tx(
            EventDataTx(height=1, index=0, tx=b"k=v", result=abci.ExecTxResult())
        )
        t.join(timeout=5)
        assert len(got) == 1 and got[0].type == EVENT_TX


# --- live node RPC ----------------------------------------------------------


def fast_genesis(privs):
    params = ConsensusParams()
    params.timeout = TimeoutParams(
        propose=0.6, propose_delta=0.2, vote=0.3, vote_delta=0.1, commit=0.1
    )
    return GenesisDoc(
        chain_id=CHAIN,
        genesis_time=Timestamp.from_unix_ns(BASE_NS),
        consensus_params=params,
        validators=[
            GenesisValidator(pub_key=pv.get_pub_key(), power=10) for pv in privs
        ],
    )


def wait_for(fn, timeout=60.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="class")
def rpc_node(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("rpcnode")
    pv = FilePV.generate(str(tmp / "pk.json"), str(tmp / "ps.json"))
    node = Node(
        NodeConfig(
            chain_id=CHAIN,
            blocksync=False,
            wal_enabled=False,
            rpc_laddr="127.0.0.1:0",
        ),
        fast_genesis([pv]),
        LocalClient(KVStoreApplication()),
        priv_validator=pv,
    )
    node.start()
    assert wait_for(lambda: node.height >= 1, timeout=30)
    client = HTTPClient(node.rpc_server.url)
    yield node, client
    node.stop()


class TestRPCServer:
    def test_health_and_status(self, rpc_node):
        node, client = rpc_node
        assert client.health() == {}
        st = client.status()
        assert st["node_info"]["network"] == CHAIN
        assert int(st["sync_info"]["latest_block_height"]) >= 1
        assert st["sync_info"]["catching_up"] is False
        assert st["validator_info"]["voting_power"] == "10"

    def test_block_commit_validators(self, rpc_node):
        node, client = rpc_node
        blk = client.block(1)
        assert blk["block"]["header"]["height"] == "1"
        assert blk["block"]["header"]["chain_id"] == CHAIN
        commit = client.commit(1)
        assert commit["signed_header"]["commit"]["height"] == "1"
        vals = client.validators(1)
        assert vals["total"] == "1"
        assert vals["validators"][0]["voting_power"] == "10"

    def test_blockchain_and_headers(self, rpc_node):
        node, client = rpc_node
        bc = client.call("blockchain", {"minHeight": 1, "maxHeight": 2})
        assert int(bc["last_height"]) >= 1
        assert len(bc["block_metas"]) >= 1
        h = client.call("header", {"height": 1})
        assert h["header"]["height"] == "1"

    def test_genesis_and_consensus(self, rpc_node):
        node, client = rpc_node
        g = client.call("genesis")
        assert g["genesis"]["chain_id"] == CHAIN
        cp = client.call("consensus_params")
        assert int(cp["consensus_params"]["block"]["max_bytes"]) > 0
        cs = client.call("consensus_state")
        assert int(cs["round_state"]["height"]) >= 1

    def test_broadcast_tx_commit_and_query(self, rpc_node):
        node, client = rpc_node
        res = client.broadcast_tx_commit(b"fruit=apple", timeout=30)
        assert res["check_tx"]["code"] == 0
        assert res["tx_result"] is not None, res
        assert int(res["height"]) >= 1
        q = client.abci_query("", b"fruit")
        import base64

        assert base64.b64decode(q["response"]["value"]) == b"apple"

    def test_tx_indexing_and_search(self, rpc_node):
        node, client = rpc_node
        res = client.broadcast_tx_commit(b"car=fast", timeout=30)
        height = int(res["height"])
        tx_hash = bytes.fromhex(res["hash"])
        assert wait_for(lambda: node.indexer.get_tx(tx_hash) is not None, timeout=10)
        got = client.tx(tx_hash)
        assert got["height"] == str(height)
        found = client.tx_search(f"tx.height = {height}")
        assert int(found["total_count"]) >= 1
        # the canonical CometBFT query form must also match
        canonical = client.tx_search(f"tm.event = 'Tx' AND tx.height = {height}")
        assert int(canonical["total_count"]) >= 1
        by_hash = client.tx_search(f"tx.hash = '{res['hash']}'")
        assert int(by_hash["total_count"]) == 1
        blocks = client.block_search(f"tm.event = 'NewBlock' AND block.height = {height}")
        assert int(blocks["total_count"]) == 1

    def test_events_observe_full_round_lifecycle(self, rpc_node):
        """An /events subscriber sees the consensus round unfold: NewRound,
        NewRoundStep, CompleteProposal, Vote, then NewBlock — the events
        internal/consensus/state.go fires via its eventbus."""
        node, client = rpc_node
        h0 = node.height
        assert wait_for(lambda: node.height >= h0 + 2, timeout=30)
        seen = set()
        after = 0
        deadline = time.monotonic() + 20
        want = {"NewRound", "NewRoundStep", "CompleteProposal", "Vote", "NewBlock"}
        while time.monotonic() < deadline and not want <= seen:
            ev = client.events(after=after, wait_time=2.0, max_items=200)
            for item in ev["items"]:
                seen.add(item["event"])
            after = int(ev["newest"])
        assert want <= seen, f"missing events: {want - seen}"

    def test_dump_consensus_state_full(self, rpc_node):
        node, client = rpc_node
        dump = client.call("dump_consensus_state")
        rs = dump["round_state"]
        assert "height/round/step" in rs
        assert "height_vote_set" in rs
        assert "validators" in rs and rs["validators"]["count"] == 1
        assert "peers" in dump  # empty list on a single node
        # the prevote/precommit bitmaps reflect the single validator
        assert all(
            set(r["prevotes_bit_array"]) <= {"x", "_"}
            for r in rs["height_vote_set"]
        )

    def test_events_longpoll(self, rpc_node):
        node, client = rpc_node
        ev = client.events(query="tm.event = 'NewBlock'", wait_time=10.0)
        assert ev["items"], "expected at least one NewBlock in the event log"
        cursor = int(ev["newest"])
        ev2 = client.events(
            query="tm.event = 'NewBlock'", after=cursor, wait_time=10.0
        )
        assert all(int(i["cursor"]) > cursor for i in ev2["items"])

    def test_abci_info_and_mempool_routes(self, rpc_node):
        node, client = rpc_node
        info = client.abci_info()
        assert int(info["response"]["last_block_height"]) >= 1
        n = client.call("num_unconfirmed_txs")
        assert "n_txs" in n

    def test_method_not_found(self, rpc_node):
        node, client = rpc_node
        with pytest.raises(RPCClientError) as ei:
            client.call("nonsense_route")
        assert ei.value.code == -32601

    def test_uri_get_requests(self, rpc_node):
        node, client = rpc_node
        import json
        import urllib.request

        with urllib.request.urlopen(node.rpc_server.url + "/status", timeout=5) as r:
            body = json.loads(r.read())
        assert body["result"]["node_info"]["network"] == CHAIN
        with urllib.request.urlopen(
            node.rpc_server.url + "/block?height=1", timeout=5
        ) as r:
            body = json.loads(r.read())
        assert body["result"]["block"]["header"]["height"] == "1"


class TestLightHTTPProvider:
    def test_light_block_roundtrip(self, rpc_node):
        node, client = rpc_node
        from tendermint_tpu.light.provider import HTTPProvider

        prov = HTTPProvider(CHAIN, node.rpc_server.url)
        lb = prov.light_block(1)
        assert lb.height == 1
        # validators hash in the header must match the decoded set —
        # the provider round-trip preserves byte-exact identity.
        assert lb.signed_header.header.validators_hash == lb.validator_set.hash()
        # and the commit verifies against that set (light verifier seam)
        from tendermint_tpu.types.validation import verify_commit_light

        verify_commit_light(
            CHAIN,
            lb.validator_set,
            lb.signed_header.commit.block_id,
            1,
            lb.signed_header.commit,
        )

    def test_height_too_high(self, rpc_node):
        node, client = rpc_node
        from tendermint_tpu.light.provider import HTTPProvider, ProviderError

        prov = HTTPProvider(CHAIN, node.rpc_server.url)
        with pytest.raises(ProviderError):
            prov.light_block(10_000_000)


class TestProfilingRoutes:
    """The pprof-analog surface (node.go pprof server): thread dumps
    always-on, CPU profiler behind the unsafe opt-in."""

    @pytest.fixture()
    def env(self):
        from tendermint_tpu.rpc.core import Environment

        return Environment()

    def test_dump_routines_lists_threads(self, env):
        out = env.dump_routines()
        assert out["count"] >= 1
        names = [r["thread"] for r in out["routines"]]
        assert any("MainThread" in n for n in names)
        assert all(isinstance(r["stack"], list) for r in out["routines"])

    def test_profiler_roundtrip(self, env):
        env.unsafe_start_profiler()
        sum(i * i for i in range(50_000))  # some work to sample
        out = env.unsafe_stop_profiler(top=5)
        assert "cumulative" in out["stats"] or "function calls" in out["stats"]

    def test_profiler_double_start_rejected(self, env):
        from tendermint_tpu.rpc.server import RPCError

        env.unsafe_start_profiler()
        try:
            with pytest.raises(RPCError):
                env.unsafe_start_profiler()
        finally:
            env.unsafe_stop_profiler()

    def test_unsafe_routes_gated(self, env):
        routes_safe = env.routes()
        # the whole diagnostic surface (thread dumps leak peer thread
        # names) requires the [rpc] unsafe opt-in
        assert "dump_routines" not in routes_safe
        assert "unsafe_start_profiler" not in routes_safe
        env.unsafe = True
        routes_unsafe = env.routes()
        assert "dump_routines" in routes_unsafe
        assert "unsafe_start_profiler" in routes_unsafe
        assert "unsafe_disconnect_peers" in routes_unsafe
        env.unsafe = False
