"""Span tracing for the verify pipeline: Chrome-trace export, per-stage
device timing, consensus step latency.

The reference ships opaque wall-clock numbers; here every hot stage of
the batch-verification pipeline (scheduler assembly, cache lookup, host
prep, table gather, device dispatch, readback, CPU fallback) and every
consensus step transition records a nestable span into a process-wide
``Tracer``. Completed spans land in a bounded ring buffer and export as
Chrome ``trace_events`` JSON, so a capture opens directly in
``chrome://tracing`` / https://ui.perfetto.dev.

Modes, driven by ``TENDERMINT_TPU_TRACE`` (or the ``[base] trace``
config knob / ``--trace`` CLI flag):

- ``off``  — spans are shared no-op objects; nothing is timed or stored
  (unless a metrics observer is bound, in which case spans are timed for
  the histograms but still not stored).
- ``ring`` — completed spans accumulate in the in-memory ring buffer,
  served at ``GET /debug/traces``.
- ``<path>`` — ring behavior plus a Chrome-trace JSON dump written to
  ``<path>`` at interpreter exit (and on explicit ``flush()``).

Span durations double as metric samples: a bound observer (see
``metrics_observer``) feeds spans tagged ``stage``+``engine`` into
``tendermint_ops_verify_stage_seconds`` and spans tagged ``step`` into
``tendermint_consensus_step_duration_seconds``, so the histograms and
the trace always agree — one clock, one count.

Nesting is per thread (a thread-local span stack); concurrency is safe
because each thread only touches its own stack and the ring append
takes the tracer lock.

Fleet scope (ISSUE 15): every recorded span carries a ``trace_id`` /
``span_id`` / ``parent_span_id``, and a compact :class:`TraceContext`
(17 bytes on the wire) rides verifyd frames, shm slab headers, and
JSON-RPC requests so a client's causal span and the server-side
scheduler/dispatch spans it provoked share one trace. ``attach()``
splices a remote parent into the local thread's span stack;
``current_context()`` reads the innermost active span for propagation.
``scripts/trace_merge.py`` fuses per-process exports (each export
records ``epoch_unix_us``, the wall-clock anchor of its perf-counter
epoch, for clock-skew correction).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import struct
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, NamedTuple, Optional

TRACE_ENV = "TENDERMINT_TPU_TRACE"
CAP_ENV = "TENDERMINT_TPU_TRACE_CAP"
DEFAULT_CAP = 4096

OFF = "off"
RING = "ring"

# --- cross-process trace context ---------------------------------------------

_CTX_STRUCT = struct.Struct("<8s8sB")  # trace_id, span_id, flags
CTX_WIRE_LEN = _CTX_STRUCT.size  # 17 bytes

# Span IDs: a per-process random prefix + a monotonically increasing
# suffix. itertools.count is atomic under the GIL, so the hot path pays
# no lock and no urandom read per span.
_ID_PREFIX = os.urandom(4).hex()
_ID_COUNTER = itertools.count(1)


def _new_span_id() -> str:
    return "%s%08x" % (_ID_PREFIX, next(_ID_COUNTER) & 0xFFFFFFFF)


def _new_trace_id() -> str:
    return os.urandom(8).hex()


class TraceContext(NamedTuple):
    """Compact propagation context: 16-hex-char trace and span IDs plus
    a flags byte (bit 0 = sampled). ``to_bytes`` is the 17-byte wire
    form carried by verifyd frames and shm slab headers; ``to_header``
    is the string form for JSON-RPC request members."""

    trace_id: str
    span_id: str
    flags: int = 1

    def to_bytes(self) -> bytes:
        return _CTX_STRUCT.pack(
            bytes.fromhex(self.trace_id), bytes.fromhex(self.span_id), self.flags
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> Optional["TraceContext"]:
        if len(raw) != CTX_WIRE_LEN:
            return None
        tid, sid, flags = _CTX_STRUCT.unpack(raw)
        if tid == b"\x00" * 8:
            return None
        return cls(tid.hex(), sid.hex(), flags)

    def to_header(self) -> str:
        return "%s-%s-%02x" % (self.trace_id, self.span_id, self.flags)

    @classmethod
    def from_header(cls, header: Any) -> Optional["TraceContext"]:
        if not isinstance(header, str):
            return None
        parts = header.split("-")
        if len(parts) != 3 or len(parts[0]) != 16 or len(parts[1]) != 16:
            return None
        try:
            bytes.fromhex(parts[0])
            bytes.fromhex(parts[1])
            flags = int(parts[2], 16)
        except ValueError:
            return None
        return cls(parts[0], parts[1], flags)


class _RemoteAnchor:
    """A remote parent spliced into the thread's span stack by
    ``attach()``: children link under the caller's span_id without a
    local span event being recorded for the anchor itself."""

    __slots__ = ("name", "trace_id", "span_id")

    def __init__(self, ctx: TraceContext):
        self.name = "remote"
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id


class _NopSpan:
    """Shared do-nothing span: the disabled tracer hands out this one
    instance, so `with tracer.span(...)` costs an attribute lookup and
    two no-op calls — no allocation, no clock reads."""

    __slots__ = ()

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **tags: Any) -> None:
        pass


NOP_SPAN = _NopSpan()


class _Span:
    """One live span; a context manager recording on exit."""

    __slots__ = (
        "_tracer",
        "name",
        "args",
        "parent",
        "_t0",
        "trace_id",
        "span_id",
        "parent_span_id",
        "_remote",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        args: Dict[str, Any],
        remote: Optional[TraceContext] = None,
    ):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.parent = ""
        self._t0 = 0.0
        self.trace_id = ""
        self.span_id = ""
        self.parent_span_id = ""
        self._remote = remote

    def set(self, **tags: Any) -> None:
        """Attach tags discovered mid-span (hit counts, verdicts)."""
        self.args.update(tags)

    def context(self) -> TraceContext:
        """Propagation context naming this span as the remote parent."""
        return TraceContext(self.trace_id, self.span_id, 1)

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        if self._remote is not None:
            # explicit remote parent beats local nesting: this span IS
            # the local continuation of the caller's cross-process span
            self.parent = "remote"
            self.trace_id = self._remote.trace_id
            self.parent_span_id = self._remote.span_id
        elif stack:
            top = stack[-1]
            self.parent = top.name
            self.trace_id = top.trace_id
            self.parent_span_id = top.span_id
        else:
            self.trace_id = _new_trace_id()
        self.span_id = _new_span_id()
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter()
        stack = self._tracer._stack()
        # Pop self specifically: a sibling span leaked across a generator
        # boundary must not tear another thread of the stack.
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:
            stack.remove(self)
        self._tracer._complete(self, t1)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring of completed spans."""

    def __init__(self, cap: int = DEFAULT_CAP):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._ring: deque = deque(maxlen=cap)  # guarded-by: _lock
        # mode/path/recording/observer are written under _lock but read
        # racily on the hot path: a span started mid-configure() may land
        # in the old or new mode, which is fine for a tracer.
        self._mode = OFF  # guarded-by: none(racy hot-path read, see above)
        self._path: Optional[str] = None  # guarded-by: none(racy hot-path read)
        self._recording = False  # guarded-by: none(racy hot-path read)
        self._observer: Optional[Callable[[str, Dict[str, Any], float], None]] = None  # guarded-by: none(racy hot-path read)
        # flight-recorder sink: (kind, name, args, ts_s, dur_s) for every
        # completed span / instant, read racily like _observer
        self._flight: Optional[Callable[[str, str, Dict[str, Any], float, float], None]] = None  # guarded-by: none(racy hot-path read)
        # third sink slot: the kernel profiler (ops/introspect.py), fed
        # (name, args, seconds) like _observer, read racily like it
        self._profile: Optional[Callable[[str, Dict[str, Any], float], None]] = None  # guarded-by: none(racy hot-path read)
        self._epoch = time.perf_counter()
        self._pid = os.getpid()
        self._thread_names: Dict[int, str] = {}  # guarded-by: _lock
        self._atexit_registered = False  # guarded-by: _lock
        self.recorded = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    # --- configuration -------------------------------------------------------

    def configure(self, mode: Optional[str] = None) -> "Tracer":
        """Set the mode: ``off`` | ``ring`` | a file path (ring + dump at
        exit). ``None``/empty reads ``TENDERMINT_TPU_TRACE``."""
        if not mode:
            mode = os.environ.get(TRACE_ENV, OFF) or OFF
        mode = mode.strip()
        cap = DEFAULT_CAP
        try:
            cap = max(1, int(os.environ.get(CAP_ENV, DEFAULT_CAP)))
        except ValueError:
            pass  # unparseable env override keeps the default cap
        with self._lock:
            self._mode = mode
            self._path = None if mode in (OFF, RING) else mode
            self._recording = mode != OFF
            if self._ring.maxlen != cap:
                self._ring = deque(self._ring, maxlen=cap)
            if self._path and not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self.flush)
        return self

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def enabled(self) -> bool:
        return self._recording

    def set_metrics_observer(
        self, observer: Optional[Callable[[str, Dict[str, Any], float], None]]
    ) -> None:
        """Single observer slot (last binder wins, like
        device_policy.bind_metrics): called with (name, args, seconds)
        for every completed span, even in ``off`` mode, so metric
        histograms stay live when the ring is not kept."""
        with self._lock:
            self._observer = observer

    def set_flight_sink(
        self,
        sink: Optional[Callable[[str, str, Dict[str, Any], float, float], None]],
    ) -> None:
        """Single flight-recorder slot (libs/flightrec installs itself
        here): called with (kind, name, args, ts_seconds, dur_seconds)
        for every completed span and instant, even in ``off`` mode, so
        the post-mortem ring stays warm when the trace ring is not."""
        with self._lock:
            self._flight = sink

    def set_profile_sink(
        self, sink: Optional[Callable[[str, Dict[str, Any], float], None]]
    ) -> None:
        """Single profiler slot (ops/introspect installs itself here):
        called with (name, args, seconds) for every completed span, even
        in ``off`` mode, so the rolling kernel/compile digests stay live
        when the ring is not kept. None uninstalls (profiler off)."""
        with self._lock:
            self._profile = sink

    # --- recording -----------------------------------------------------------

    def _stack(self) -> List[Any]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(
        self,
        name: str,
        parent_ctx: Optional[TraceContext] = None,
        **args: Any,
    ) -> Any:
        """``with tracer.span("prep_chunk", lane_count=n):`` — nested
        spans inherit this one as parent (per-thread). ``parent_ctx``
        splices the span under a remote caller's context instead."""
        if (
            not self._recording
            and self._observer is None
            and self._flight is None
            and self._profile is None
        ):
            return NOP_SPAN
        return _Span(self, name, args, remote=parent_ctx)

    @contextmanager
    def attach(self, ctx: Optional[TraceContext]):
        """Make ``ctx`` the parent of every span this thread opens
        inside the block (no-op when ``ctx`` is None)."""
        if ctx is None or not self._recording:
            yield None
            return
        stack = self._stack()
        anchor = _RemoteAnchor(ctx)
        stack.append(anchor)
        try:
            yield anchor
        finally:
            if stack and stack[-1] is anchor:
                stack.pop()
            elif anchor in stack:
                stack.remove(anchor)

    def current_context(self) -> Optional[TraceContext]:
        """Context of this thread's innermost active span (None when no
        span is open or the tracer is not recording)."""
        if not self._recording:
            return None
        stack = self._stack()
        if not stack:
            return None
        top = stack[-1]
        if not top.trace_id:
            return None
        return TraceContext(top.trace_id, top.span_id, 1)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration event (device health transitions etc.)."""
        flight = self._flight
        if flight is None and not self._recording:
            return
        now = time.perf_counter()
        if flight is not None:
            try:
                flight("instant", name, args, now, 0.0)
            except Exception:
                pass  # the post-mortem ring must not fail the op
        if not self._recording:
            return
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",
            "pid": self._pid,
            "tid": threading.get_ident(),
            "ts": round((now - self._epoch) * 1e6, 3),
            "args": args,
        }
        stack = self._stack()
        if stack and stack[-1].trace_id:
            ev["trace_id"] = stack[-1].trace_id
            ev["parent_span_id"] = stack[-1].span_id
        self._append(ev)

    def _complete(self, span: _Span, t1: float) -> None:
        duration = t1 - span._t0
        observer = self._observer
        if observer is not None:
            try:
                observer(span.name, span.args, duration)
            except Exception:
                pass  # a broken metrics binding must not fail the traced op
        profile = self._profile
        if profile is not None:
            try:
                profile(span.name, span.args, duration)
            except Exception:
                pass  # a broken profiler must not fail the traced op
        flight = self._flight
        if flight is not None:
            try:
                flight("span", span.name, span.args, span._t0, duration)
            except Exception:
                pass  # the post-mortem ring must not fail the traced op
        if not self._recording:
            return
        args = span.args
        if span.parent:
            args.setdefault("parent", span.parent)
        ev = {
            "name": span.name,
            "ph": "X",
            "pid": self._pid,
            "tid": threading.get_ident(),
            "ts": round((span._t0 - self._epoch) * 1e6, 3),
            "dur": round(duration * 1e6, 3),
            "args": args,
        }
        if span.trace_id:
            ev["trace_id"] = span.trace_id
            ev["span_id"] = span.span_id
            if span.parent_span_id:
                ev["parent_span_id"] = span.parent_span_id
        self._append(ev)

    def _append(self, ev: Dict[str, Any]) -> None:
        tid = ev["tid"]
        name = threading.current_thread().name
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)
            self.recorded += 1
            self._thread_names.setdefault(tid, name)

    # --- export --------------------------------------------------------------

    def _epoch_unix_us(self) -> float:
        """Wall-clock instant (unix microseconds) of the perf-counter
        epoch every event ``ts`` is relative to — the per-process anchor
        scripts/trace_merge.py uses for clock-skew correction."""
        return (time.time() - (time.perf_counter() - self._epoch)) * 1e6

    def _snapshot(
        self, limit: Optional[int], clear: bool
    ) -> "tuple[List[Dict[str, Any]], List[Dict[str, Any]], Dict[str, Any]]":
        """(meta_events, events, otherData) — the only part of an export
        that runs under the tracer lock is the ring copy."""
        with self._lock:
            events = list(self._ring)
            recorded, dropped = self.recorded, self.dropped
            names = dict(self._thread_names)
            if clear:
                self._ring.clear()
                self.dropped = 0
        if limit is not None and len(events) > limit:
            events = events[-limit:]
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": self._pid,
                "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in sorted(names.items())
        ]
        other = {
            "mode": self._mode,
            "recorded": recorded,
            "dropped": dropped,
            "pid": self._pid,
            "epoch_unix_us": round(self._epoch_unix_us(), 1),
        }
        return meta, events, other

    def export(
        self, limit: Optional[int] = None, clear: bool = False
    ) -> Dict[str, Any]:
        """Chrome ``trace_events`` JSON object; ``limit`` keeps the most
        recent N events (the response stays bounded)."""
        meta, events, other = self._snapshot(limit, clear)
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def export_chunks(
        self,
        limit: Optional[int] = None,
        clear: bool = False,
        fmt: str = "full",
    ) -> Iterator[bytes]:
        """Streamed export: the tracer lock is held only for the ring
        snapshot (O(events) pointer copies); all JSON serialization
        happens outside it, yielded in bounded chunks. ``fmt="chrome"``
        emits a pure Chrome/Perfetto document (no ``otherData``)."""
        meta, events, other = self._snapshot(limit, clear)
        yield b'{"traceEvents": ['
        first = True
        batch: List[str] = []
        for ev in meta + events:
            batch.append(("" if first else ",") + json.dumps(ev))
            first = False
            if len(batch) >= 256:
                yield "".join(batch).encode()
                batch = []
        if batch:
            yield "".join(batch).encode()
        tail = '], "displayTimeUnit": "ms"'
        if fmt != "chrome":
            tail += ', "otherData": %s' % json.dumps(other)
        yield (tail + "}").encode()

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-stage p50/p95/total over the ring's completed spans,
        grouped by the ``stage`` tag (falling back to the span name)."""
        with self._lock:
            events = [e for e in self._ring if e.get("ph") == "X"]
        groups: Dict[str, List[float]] = {}
        for ev in events:
            key = str(ev["args"].get("stage") or ev["name"])
            groups.setdefault(key, []).append(ev["dur"])
        out: Dict[str, Dict[str, float]] = {}
        for key in sorted(groups):
            durs = sorted(groups[key])
            n = len(durs)
            out[key] = {
                "count": n,
                "p50_ms": round(durs[n // 2] / 1e3, 4),
                "p95_ms": round(durs[min(n - 1, int(n * 0.95))] / 1e3, 4),
                "total_ms": round(sum(durs) / 1e3, 4),
            }
        return out

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome-trace JSON to ``path`` (default: the
        configured file mode's path). No-op without a destination."""
        path = path or self._path
        if not path:
            return None
        try:
            with open(path, "w") as f:
                json.dump(self.export(), f)
        except OSError:
            return None
        return path

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def metrics_observer(ops=None, consensus=None):
    """Bridge span durations into the metric histograms: spans tagged
    ``stage`` + ``engine`` -> tendermint_ops_verify_stage_seconds, spans
    tagged ``step`` -> tendermint_consensus_step_duration_seconds. One
    timing source for both the trace and the histograms."""

    def observe(name: str, args: Dict[str, Any], seconds: float) -> None:
        stage = args.get("stage")
        engine = args.get("engine")
        if ops is not None and stage and engine:
            ops.verify_stage_seconds.labels(
                stage=str(stage), engine=str(engine)
            ).observe(seconds)
        step = args.get("step")
        if consensus is not None and step:
            consensus.step_duration_seconds.labels(step=str(step)).observe(
                seconds
            )

    return observe


# The process-wide instance every instrumentation site uses (the ops
# modules have no node handle — same pattern as device_policy.shared).
tracer = Tracer()
tracer.configure()


def configure(mode: Optional[str] = None) -> Tracer:
    return tracer.configure(mode)


def span(
    name: str, parent_ctx: Optional[TraceContext] = None, **args: Any
) -> Any:
    return tracer.span(name, parent_ctx=parent_ctx, **args)


def instant(name: str, **args: Any) -> None:
    tracer.instant(name, **args)


def attach(ctx: Optional[TraceContext]):
    """``with tracing.attach(ctx): ...`` — remote-parent splice."""
    return tracer.attach(ctx)


def current_context() -> Optional[TraceContext]:
    return tracer.current_context()
