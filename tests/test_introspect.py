"""Device-tier introspection (ops/introspect.py, ISSUE 18).

The acceptance contract: the byte ledger stays EXACT — the
``resident_tables`` owner always equals the nbytes of the tensor the
store actually has installed (and 0 when none is), across upload,
committee rotation, eviction, and clear; slab-ring attach/retire is
symmetric to the byte; and every surface (/debug/memstats, flight
recorder dumps, verifyd stats) reports the same ledger.
"""

import json

import pytest

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.libs.metrics import OpsMetrics, Registry
from tendermint_tpu.ops import introspect, precompute, resident

jax = pytest.importorskip("jax")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Resident store on (auto keeps CPU off); ledger + caches isolated
    per test."""
    monkeypatch.setenv("TENDERMINT_TPU_RESIDENT", "on")
    precompute.reset()
    resident.reset()
    introspect.accountant.clear()
    introspect.profiler.clear()
    yield
    precompute.reset()
    resident.reset()
    introspect.accountant.clear()
    introspect.profiler.clear()


def _batch(n, seed=60):
    pks, msgs, sigs = [], [], []
    for i in range(n):
        sk, pk = ref.keypair_from_seed(bytes([seed + i]) * 32)
        m = b"introspect lane %03d" % i
        pks.append(pk)
        msgs.append(m)
        sigs.append(ref.sign(sk, m))
    return pks, msgs, sigs


# --- bucket labeler -----------------------------------------------------------


class TestBucketLabel:
    def test_rounds_up_to_power_of_two(self):
        assert introspect.bucket_label(1) == "1"
        assert introspect.bucket_label(2) == "2"
        assert introspect.bucket_label(3) == "4"
        assert introspect.bucket_label(100) == "128"
        assert introspect.bucket_label(8192) == "8192"

    def test_overflow_and_junk_collapse_to_other(self):
        assert introspect.bucket_label(1 << 15) == "other"
        assert introspect.bucket_label(0) == "other"
        assert introspect.bucket_label(-5) == "other"
        assert introspect.bucket_label(None) == "other"
        assert introspect.bucket_label("lots") == "other"

    def test_cardinality_is_bounded(self):
        labels = {introspect.bucket_label(n) for n in range(0, 100_000, 7)}
        assert len(labels) <= 16


# --- resident-table byte accounting ------------------------------------------


def _resident_bytes():
    return introspect.accountant.bytes_for("resident_tables")


class TestResidentBytes:
    def test_exact_across_upload_rotation_evict_clear(self):
        """Acceptance: ledger bytes == the store's actual upload sizes
        across a full rotation/evict cycle."""
        from tendermint_tpu.ops import ed25519_batch

        assert _resident_bytes() == 0
        pks, msgs, sigs = _batch(8)
        precompute.pin_pubkeys(pks)
        assert all(ed25519_batch.verify_batch(pks, msgs, sigs))

        first_upload = int(resident.stats()["h2d_bytes"])
        assert first_upload > 0
        assert _resident_bytes() == first_upload

        # committee growth: the re-upload replaces the install; the
        # ledger must track the NEW tensor size, not accumulate
        p2, m2, s2 = _batch(4, seed=120)
        precompute.pin_pubkeys(p2)
        assert all(
            ed25519_batch.verify_batch(pks + p2, msgs + m2, sigs + s2)
        )
        stats = resident.stats()
        assert stats["uploads"] == 2
        second_upload = int(stats["h2d_bytes"]) - first_upload
        assert _resident_bytes() == second_upload > first_upload

        # clear (rotation observed by consensus): device copy dies,
        # ledger drops to zero with it
        resident.note_validator_rotation()
        assert _resident_bytes() == 0
        assert introspect.accountant.snapshot()["device_bytes"] == {}

    def test_invalidate_zeroes_then_reupload_restores(self):
        from tendermint_tpu.ops import ed25519_batch

        pks, msgs, sigs = _batch(6)
        precompute.pin_pubkeys(pks)
        ed25519_batch.verify_batch(pks, msgs, sigs)
        installed = _resident_bytes()
        assert installed > 0

        # host-cache eviction of a resident key invalidates the device
        # copy in lockstep; the ledger must not hold stale bytes
        resident.store.invalidate([pks[0]])
        assert _resident_bytes() == 0

        ed25519_batch.verify_batch(pks, msgs, sigs)
        assert _resident_bytes() == installed

    def test_gauge_mirrors_ledger(self):
        ops = OpsMetrics(Registry())
        introspect.bind_metrics(ops)
        key = (("owner", "resident_tables"),)
        introspect.set_bytes("resident_tables", 12345)
        assert ops.device_bytes._values.get(key) == 12345
        introspect.set_bytes("resident_tables", 0)
        assert ops.device_bytes._values.get(key) == 0
        introspect.bind_metrics(None)


# --- slab-ring attach / retire ------------------------------------------------


class TestShmSlabBytes:
    def _endpoint(self):
        from tendermint_tpu.verifyd.shm import ShmEndpoint

        return ShmEndpoint(serve=lambda *a, **k: None)

    def _session(self, size):
        import types

        return types.SimpleNamespace(_seg=types.SimpleNamespace(size=size))

    def test_attach_retire_symmetry(self):
        ep = self._endpoint()
        a, b = self._session(64 * 1024), self._session(128 * 1024)
        ep.register(a)
        assert introspect.accountant.bytes_for("shm_slabs") == 64 * 1024
        ep.register(b)
        assert introspect.accountant.bytes_for("shm_slabs") == 192 * 1024
        ep.unregister(a)
        assert introspect.accountant.bytes_for("shm_slabs") == 128 * 1024
        ep.unregister(b)
        assert introspect.accountant.bytes_for("shm_slabs") == 0

    def test_double_unregister_does_not_go_negative(self):
        ep = self._endpoint()
        a = self._session(4096)
        ep.register(a)
        ep.unregister(a)
        ep.unregister(a)  # connection_lost racing stop(): second is a no-op
        assert introspect.accountant.bytes_for("shm_slabs") == 0


# --- continuous profiler ------------------------------------------------------


class TestProfiler:
    def test_digests_fed_from_dispatch_spans(self):
        from tendermint_tpu.libs import tracing

        introspect.profiler.configure("on")
        try:
            for _ in range(4):
                with tracing.tracer.span(
                    "dispatch_chunk", stage="dispatch", engine="ed25519",
                    kind="raw", lanes=100,
                ):
                    pass
            with tracing.tracer.span(
                "kernel_compile", engine="ed25519", kernel="verify", lanes=128
            ):
                pass
            snap = introspect.profiler.snapshot()
        finally:
            introspect.profiler.configure("off")
        k = snap["kernel"]["ed25519/b128"]
        assert k["count"] == 4
        assert k["p50_ms"] >= 0.0 and k["p99_ms"] >= k["p50_ms"]
        assert snap["compile"]["ed25519/b128"]["count"] == 1

    def test_profile_sink_keeps_spans_live_when_ring_off(self):
        """The tracer's NOP gate must treat the profile sink as a
        reason to record — otherwise an off-mode process profiles
        nothing."""
        from tendermint_tpu.libs.tracing import Tracer

        t = Tracer()  # default mode is off: ring never records
        seen = []
        t.set_profile_sink(lambda name, args, dur: seen.append(name))
        with t.span("dispatch_chunk", engine="x", lanes=4):
            pass
        assert seen == ["dispatch_chunk"]

    def test_off_profiler_uninstalls_sink(self):
        from tendermint_tpu.libs import tracing

        introspect.profiler.configure("off")
        assert tracing.tracer._profile is None
        introspect.profiler.configure("on")
        assert tracing.tracer._profile is not None
        introspect.profiler.configure("off")

    def test_non_kernel_spans_ignored(self):
        introspect.profiler.sink("verify_batch", {"lanes": 8}, 0.001)
        snap = introspect.profiler.snapshot()
        assert snap["kernel"] == {} and snap["compile"] == {}


# --- compile accounting -------------------------------------------------------


class TestCompileAccounting:
    def test_traced_first_call_counts_once(self):
        calls = []
        fn = introspect.traced_first_call(
            lambda x: calls.append(x) or x, "ed25519", "verify", 64
        )
        before = introspect.accountant.snapshot()["compile_events"].get(
            "ed25519", 0
        )
        assert fn(1) == 1 and fn(2) == 2 and fn(3) == 3
        after = introspect.accountant.snapshot()["compile_events"]
        assert after.get("ed25519", 0) == before + 1
        assert calls == [1, 2, 3]

    def test_counter_mirrors(self):
        ops = OpsMetrics(Registry())
        introspect.bind_metrics(ops)
        introspect.note_compile("sr25519")
        assert ops.compile_events._values.get((("engine", "sr25519"),)) == 1
        introspect.bind_metrics(None)


# --- surfaces -----------------------------------------------------------------


class TestSurfaces:
    def test_debug_memstats_endpoint(self):
        from tendermint_tpu.rpc.server import RPCServer

        introspect.set_bytes("resident_tables", 777)
        status, ctype, body = RPCServer(routes={})._get_response(
            "/debug/memstats"
        )
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["device_bytes"]["resident_tables"] == 777
        assert "profile" in doc and "resident" in doc

    def test_verifyd_stats_carry_ledger(self):
        from tendermint_tpu.verifyd.server import VerifydServer

        introspect.set_bytes("shm_slabs", 4096)
        introspect.note_compile("ed25519")
        srv = VerifydServer(verify_fn=lambda pks, msgs, sigs: [])
        stats = srv.stats()
        assert stats["device_bytes"]["shm_slabs"] == 4096
        assert stats["compile_events"]["ed25519"] >= 1

    def test_flightrec_dump_embeds_memstats(self, tmp_path, monkeypatch):
        from tendermint_tpu.libs import flightrec

        monkeypatch.setenv(flightrec.DIR_ENV, str(tmp_path))
        introspect.set_bytes("resident_tables", 2048)
        rec = flightrec.FlightRecorder()
        rec.mark("unit_test", n=1)
        path = rec.dump("test")
        assert path is not None
        doc = json.loads(open(path).read())
        assert doc["memstats"]["device_bytes"]["resident_tables"] == 2048

    def test_memstats_json_respects_size_bound(self):
        # fill the profiler so the full doc is large, then bound it
        for i in range(64):
            introspect.profiler.sink(
                "dispatch_chunk", {"engine": "e%d" % (i % 4), "lanes": i + 1},
                0.001,
            )
        full = introspect.memstats_json()
        assert len(full) > 200
        bounded = introspect.memstats_json(limit_bytes=200)
        assert len(bounded) <= 200
        doc = json.loads(bounded)
        assert "device_bytes_total" in doc
