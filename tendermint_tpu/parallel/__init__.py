"""Multi-chip parallelism: device meshes and sharded batch verification."""

from tendermint_tpu.parallel.sharding import (
    make_mesh,
    sharded_verify_fn,
    verify_batch_sharded,
)

__all__ = ["make_mesh", "sharded_verify_fn", "verify_batch_sharded"]
