"""Canonical sign-bytes encoders.

Mirrors the reference's canonicalization + delimited marshalling
(types/canonical.go, types/vote.go:141-170, proto/tendermint/types/
canonical.proto, internal/libs/protoio/writer.go:110): sign-bytes are the
varint-length-prefixed protobuf encoding of the canonical struct.

Field-presence rules were verified against the generated gogo marshaller
(canonical.pb.go:590-640): proto3 zero values are omitted, EXCEPT the
non-nullable Timestamp in CanonicalVote/CanonicalProposal, which is always
serialized (possibly as an empty message), and the non-nullable
PartSetHeader inside CanonicalBlockID.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from tendermint_tpu.encoding.proto import (
    encode_bytes_field,
    encode_message_field,
    encode_sfixed64_field,
    encode_string_field,
    encode_varint_field,
    length_delimited,
)

# SignedMsgType values (proto/tendermint/types/types.proto)
SIGNED_MSG_TYPE_UNKNOWN = 0
SIGNED_MSG_TYPE_PREVOTE = 1
SIGNED_MSG_TYPE_PRECOMMIT = 2
SIGNED_MSG_TYPE_PROPOSAL = 32


class Timestamp(NamedTuple):
    """google.protobuf.Timestamp: seconds + nanos since the Unix epoch."""

    seconds: int = 0
    nanos: int = 0

    def encode(self) -> bytes:
        return encode_varint_field(1, self.seconds) + encode_varint_field(
            2, self.nanos
        )

    @classmethod
    def from_unix_ns(cls, ns: int) -> "Timestamp":
        return cls(ns // 1_000_000_000, ns % 1_000_000_000)

    def to_unix_ns(self) -> int:
        return self.seconds * 1_000_000_000 + self.nanos


ZERO_TIME = Timestamp(0, 0)


def encode_canonical_part_set_header(total: int, hash_: bytes) -> bytes:
    return encode_varint_field(1, total) + encode_bytes_field(2, hash_)


def encode_canonical_block_id(
    hash_: bytes, psh_total: int, psh_hash: bytes
) -> Optional[bytes]:
    """Returns None for a nil BlockID (omitted entirely from the canonical
    vote; reference: types/canonical.go CanonicalizeBlockID)."""
    if not hash_ and psh_total == 0 and not psh_hash:
        return None
    psh = encode_canonical_part_set_header(psh_total, psh_hash)
    return encode_bytes_field(1, hash_) + encode_message_field(2, psh, always=True)


def canonical_vote_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: Optional[bytes],
    timestamp: Timestamp,
) -> bytes:
    """Encoded CanonicalVote (NOT length-prefixed); ``block_id`` is the
    pre-encoded canonical block ID or None."""
    out = encode_varint_field(1, msg_type)
    out += encode_sfixed64_field(2, height)
    out += encode_sfixed64_field(3, round_)
    if block_id is not None:
        out += encode_message_field(4, block_id, always=True)
    out += encode_message_field(5, timestamp.encode(), always=True)
    out += encode_string_field(6, chain_id)
    return out


def vote_sign_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id_hash: bytes,
    psh_total: int,
    psh_hash: bytes,
    timestamp: Timestamp,
) -> bytes:
    """types.VoteSignBytes equivalent: delimited canonical vote."""
    bid = encode_canonical_block_id(block_id_hash, psh_total, psh_hash)
    return length_delimited(
        canonical_vote_bytes(chain_id, msg_type, height, round_, bid, timestamp)
    )


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id_hash: bytes,
    psh_total: int,
    psh_hash: bytes,
    timestamp: Timestamp,
) -> bytes:
    """types.ProposalSignBytes equivalent (canonical.proto CanonicalProposal)."""
    bid = encode_canonical_block_id(block_id_hash, psh_total, psh_hash)
    out = encode_varint_field(1, SIGNED_MSG_TYPE_PROPOSAL)
    out += encode_sfixed64_field(2, height)
    out += encode_sfixed64_field(3, round_)
    out += encode_varint_field(4, pol_round)
    if bid is not None:
        out += encode_message_field(5, bid, always=True)
    out += encode_message_field(6, timestamp.encode(), always=True)
    out += encode_string_field(7, chain_id)
    return length_delimited(out)


def vote_extension_sign_bytes(
    chain_id: str, extension: bytes, height: int, round_: int
) -> bytes:
    """types.VoteExtensionSignBytes equivalent (CanonicalVoteExtension)."""
    out = encode_bytes_field(1, extension)
    out += encode_sfixed64_field(2, height)
    out += encode_sfixed64_field(3, round_)
    out += encode_string_field(4, chain_id)
    return length_delimited(out)
