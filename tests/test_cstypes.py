"""HeightVoteSet tests (internal/consensus/types/height_vote_set_test.go)."""

import pytest

from tendermint_tpu.consensus.cstypes import (
    GotVoteFromUnwantedRoundError,
    HeightVoteSet,
    RoundState,
    RoundStep,
)
from tests.helpers import CHAIN_ID, make_block_id, make_validators
from tests.test_vote_set import signed_vote
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
)


def test_round_progression_and_pol():
    privs, vset = make_validators(4, power=1)
    hvs = HeightVoteSet(CHAIN_ID, 1, vset)
    bid = make_block_id()
    hvs.set_round(1)
    for i in range(4):
        assert hvs.add_vote(
            signed_vote(privs[i], vset, i, height=1, round_=1, block_id=bid)
        )
    pol_round, pol_bid = hvs.pol_info()
    assert pol_round == 1 and pol_bid == bid
    assert hvs.prevotes(1).has_two_thirds_majority()
    assert hvs.prevotes(0) is not None
    assert hvs.prevotes(5) is None


def test_peer_catchup_round_limit():
    privs, vset = make_validators(4)
    hvs = HeightVoteSet(CHAIN_ID, 1, vset)
    # A peer may introduce at most 2 unexpected rounds.
    v1 = signed_vote(privs[0], vset, 0, height=1, round_=5, block_id=make_block_id())
    assert hvs.add_vote(v1, peer_id="peerA")
    v2 = signed_vote(privs[1], vset, 1, height=1, round_=6, block_id=make_block_id())
    assert hvs.add_vote(v2, peer_id="peerA")
    v3 = signed_vote(privs[2], vset, 2, height=1, round_=7, block_id=make_block_id())
    with pytest.raises(GotVoteFromUnwantedRoundError):
        hvs.add_vote(v3, peer_id="peerA")
    # A different peer still has its allowance.
    assert hvs.add_vote(v3, peer_id="peerB")


def test_duplicate_vote_returns_false():
    privs, vset = make_validators(4)
    hvs = HeightVoteSet(CHAIN_ID, 1, vset)
    v = signed_vote(privs[0], vset, 0, height=1, round_=0, block_id=make_block_id())
    assert hvs.add_vote(v)
    assert not hvs.add_vote(v)


def test_precommits_tracked_separately():
    privs, vset = make_validators(4)
    hvs = HeightVoteSet(CHAIN_ID, 1, vset)
    bid = make_block_id()
    hvs.add_vote(signed_vote(privs[0], vset, 0, height=1, block_id=bid))
    hvs.add_vote(
        signed_vote(
            privs[0], vset, 0, height=1, type_=SIGNED_MSG_TYPE_PRECOMMIT, block_id=bid
        )
    )
    assert hvs.prevotes(0).get_by_index(0) is not None
    assert hvs.precommits(0).get_by_index(0) is not None


def test_round_state_defaults():
    rs = RoundState()
    assert rs.step == RoundStep.NEW_HEIGHT
    assert rs.locked_round == -1 and rs.valid_round == -1
    assert rs.height_round_step() == "0/0/1"
