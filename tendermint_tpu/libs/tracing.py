"""Span tracing for the verify pipeline: Chrome-trace export, per-stage
device timing, consensus step latency.

The reference ships opaque wall-clock numbers; here every hot stage of
the batch-verification pipeline (scheduler assembly, cache lookup, host
prep, table gather, device dispatch, readback, CPU fallback) and every
consensus step transition records a nestable span into a process-wide
``Tracer``. Completed spans land in a bounded ring buffer and export as
Chrome ``trace_events`` JSON, so a capture opens directly in
``chrome://tracing`` / https://ui.perfetto.dev.

Modes, driven by ``TENDERMINT_TPU_TRACE`` (or the ``[base] trace``
config knob / ``--trace`` CLI flag):

- ``off``  — spans are shared no-op objects; nothing is timed or stored
  (unless a metrics observer is bound, in which case spans are timed for
  the histograms but still not stored).
- ``ring`` — completed spans accumulate in the in-memory ring buffer,
  served at ``GET /debug/traces``.
- ``<path>`` — ring behavior plus a Chrome-trace JSON dump written to
  ``<path>`` at interpreter exit (and on explicit ``flush()``).

Span durations double as metric samples: a bound observer (see
``metrics_observer``) feeds spans tagged ``stage``+``engine`` into
``tendermint_ops_verify_stage_seconds`` and spans tagged ``step`` into
``tendermint_consensus_step_duration_seconds``, so the histograms and
the trace always agree — one clock, one count.

Nesting is per thread (a thread-local span stack); concurrency is safe
because each thread only touches its own stack and the ring append
takes the tracer lock.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

TRACE_ENV = "TENDERMINT_TPU_TRACE"
CAP_ENV = "TENDERMINT_TPU_TRACE_CAP"
DEFAULT_CAP = 4096

OFF = "off"
RING = "ring"


class _NopSpan:
    """Shared do-nothing span: the disabled tracer hands out this one
    instance, so `with tracer.span(...)` costs an attribute lookup and
    two no-op calls — no allocation, no clock reads."""

    __slots__ = ()

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **tags: Any) -> None:
        pass


NOP_SPAN = _NopSpan()


class _Span:
    """One live span; a context manager recording on exit."""

    __slots__ = ("_tracer", "name", "args", "parent", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.parent = ""
        self._t0 = 0.0

    def set(self, **tags: Any) -> None:
        """Attach tags discovered mid-span (hit counts, verdicts)."""
        self.args.update(tags)

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        if stack:
            self.parent = stack[-1].name
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter()
        stack = self._tracer._stack()
        # Pop self specifically: a sibling span leaked across a generator
        # boundary must not tear another thread of the stack.
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:
            stack.remove(self)
        self._tracer._complete(self, t1)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring of completed spans."""

    def __init__(self, cap: int = DEFAULT_CAP):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._ring: deque = deque(maxlen=cap)  # guarded-by: _lock
        # mode/path/recording/observer are written under _lock but read
        # racily on the hot path: a span started mid-configure() may land
        # in the old or new mode, which is fine for a tracer.
        self._mode = OFF  # guarded-by: none(racy hot-path read, see above)
        self._path: Optional[str] = None  # guarded-by: none(racy hot-path read)
        self._recording = False  # guarded-by: none(racy hot-path read)
        self._observer: Optional[Callable[[str, Dict[str, Any], float], None]] = None  # guarded-by: none(racy hot-path read)
        self._epoch = time.perf_counter()
        self._pid = os.getpid()
        self._thread_names: Dict[int, str] = {}  # guarded-by: _lock
        self._atexit_registered = False  # guarded-by: _lock
        self.recorded = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    # --- configuration -------------------------------------------------------

    def configure(self, mode: Optional[str] = None) -> "Tracer":
        """Set the mode: ``off`` | ``ring`` | a file path (ring + dump at
        exit). ``None``/empty reads ``TENDERMINT_TPU_TRACE``."""
        if not mode:
            mode = os.environ.get(TRACE_ENV, OFF) or OFF
        mode = mode.strip()
        cap = DEFAULT_CAP
        try:
            cap = max(1, int(os.environ.get(CAP_ENV, DEFAULT_CAP)))
        except ValueError:
            pass  # unparseable env override keeps the default cap
        with self._lock:
            self._mode = mode
            self._path = None if mode in (OFF, RING) else mode
            self._recording = mode != OFF
            if self._ring.maxlen != cap:
                self._ring = deque(self._ring, maxlen=cap)
            if self._path and not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self.flush)
        return self

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def enabled(self) -> bool:
        return self._recording

    def set_metrics_observer(
        self, observer: Optional[Callable[[str, Dict[str, Any], float], None]]
    ) -> None:
        """Single observer slot (last binder wins, like
        device_policy.bind_metrics): called with (name, args, seconds)
        for every completed span, even in ``off`` mode, so metric
        histograms stay live when the ring is not kept."""
        with self._lock:
            self._observer = observer

    # --- recording -----------------------------------------------------------

    def _stack(self) -> List[_Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, name: str, **args: Any) -> Any:
        """``with tracer.span("prep_chunk", lane_count=n):`` — nested
        spans inherit this one as parent (per-thread)."""
        if not self._recording and self._observer is None:
            return NOP_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration event (device health transitions etc.)."""
        if not self._recording:
            return
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",
            "pid": self._pid,
            "tid": threading.get_ident(),
            "ts": round((time.perf_counter() - self._epoch) * 1e6, 3),
            "args": args,
        }
        self._append(ev)

    def _complete(self, span: _Span, t1: float) -> None:
        duration = t1 - span._t0
        observer = self._observer
        if observer is not None:
            try:
                observer(span.name, span.args, duration)
            except Exception:
                pass  # a broken metrics binding must not fail the traced op
        if not self._recording:
            return
        args = span.args
        if span.parent:
            args.setdefault("parent", span.parent)
        ev = {
            "name": span.name,
            "ph": "X",
            "pid": self._pid,
            "tid": threading.get_ident(),
            "ts": round((span._t0 - self._epoch) * 1e6, 3),
            "dur": round(duration * 1e6, 3),
            "args": args,
        }
        self._append(ev)

    def _append(self, ev: Dict[str, Any]) -> None:
        tid = ev["tid"]
        name = threading.current_thread().name
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)
            self.recorded += 1
            self._thread_names.setdefault(tid, name)

    # --- export --------------------------------------------------------------

    def export(
        self, limit: Optional[int] = None, clear: bool = False
    ) -> Dict[str, Any]:
        """Chrome ``trace_events`` JSON object; ``limit`` keeps the most
        recent N events (the response stays bounded)."""
        with self._lock:
            events = list(self._ring)
            recorded, dropped = self.recorded, self.dropped
            names = dict(self._thread_names)
            if clear:
                self._ring.clear()
                self.dropped = 0
        if limit is not None and len(events) > limit:
            events = events[-limit:]
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": self._pid,
                "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in sorted(names.items())
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "mode": self._mode,
                "recorded": recorded,
                "dropped": dropped,
            },
        }

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-stage p50/p95/total over the ring's completed spans,
        grouped by the ``stage`` tag (falling back to the span name)."""
        with self._lock:
            events = [e for e in self._ring if e.get("ph") == "X"]
        groups: Dict[str, List[float]] = {}
        for ev in events:
            key = str(ev["args"].get("stage") or ev["name"])
            groups.setdefault(key, []).append(ev["dur"])
        out: Dict[str, Dict[str, float]] = {}
        for key in sorted(groups):
            durs = sorted(groups[key])
            n = len(durs)
            out[key] = {
                "count": n,
                "p50_ms": round(durs[n // 2] / 1e3, 4),
                "p95_ms": round(durs[min(n - 1, int(n * 0.95))] / 1e3, 4),
                "total_ms": round(sum(durs) / 1e3, 4),
            }
        return out

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome-trace JSON to ``path`` (default: the
        configured file mode's path). No-op without a destination."""
        path = path or self._path
        if not path:
            return None
        try:
            with open(path, "w") as f:
                json.dump(self.export(), f)
        except OSError:
            return None
        return path

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def metrics_observer(ops=None, consensus=None):
    """Bridge span durations into the metric histograms: spans tagged
    ``stage`` + ``engine`` -> tendermint_ops_verify_stage_seconds, spans
    tagged ``step`` -> tendermint_consensus_step_duration_seconds. One
    timing source for both the trace and the histograms."""

    def observe(name: str, args: Dict[str, Any], seconds: float) -> None:
        stage = args.get("stage")
        engine = args.get("engine")
        if ops is not None and stage and engine:
            ops.verify_stage_seconds.labels(
                stage=str(stage), engine=str(engine)
            ).observe(seconds)
        step = args.get("step")
        if consensus is not None and step:
            consensus.step_duration_seconds.labels(step=str(step)).observe(
                seconds
            )

    return observe


# The process-wide instance every instrumentation site uses (the ops
# modules have no node handle — same pattern as device_policy.shared).
tracer = Tracer()
tracer.configure()


def configure(mode: Optional[str] = None) -> Tracer:
    return tracer.configure(mode)


def span(name: str, **args: Any) -> Any:
    return tracer.span(name, **args)


def instant(name: str, **args: Any) -> None:
    tracer.instant(name, **args)
