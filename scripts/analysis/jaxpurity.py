"""JAX-purity checker (TPJ): trace-time hygiene for the kernel stacks.

Everything reachable from a ``jax.jit`` / ``pl.pallas_call`` entry
point in ``tendermint_tpu/ops/`` executes at TRACE time and is baked
into the compiled graph. Host side effects there are at best silently
frozen into the kernel (a ``time.monotonic()`` reads once, at trace)
and at worst a concretization error three layers away from the cause.
The three rules:

- TPJ001 — impure call in a jit-reachable function: ``time.*``,
  ``random``/``np.random``/``os.urandom``, ``print``/``open``/
  ``input``, ``os.environ``, logger methods, and ``tracing`` spans
  (spans belong AROUND the compiled call, never inside the trace).
- TPJ002 — Python-side branch (``if``/``while``/``assert``) on a traced
  value: the test references a parameter or local of the kernel
  function. Branching on static config (module globals like
  ``_MUL_IMPL``), on ``.shape``/``.ndim``/``.dtype``/``len()``/
  ``isinstance()`` of a traced value, or on comprehension/loop
  variables of static ranges is allowed — those are trace-time
  constants.
- TPJ003 — dtype discipline: the field kernels are exact in f32 with
  uint8 wire I/O and int32/int8 MXU contractions; 64-bit and 16-bit
  dtypes (``int64``/``float64``/``float16``/``bfloat16``) anywhere in
  ``ops/`` are either silently downcast by jax's x64 default or break
  the exact-integer range proofs, so both spellings (attribute and
  string literal) are flagged.

Reachability is a cross-module call graph over the ``ops/`` package:
entry points are functions passed to ``jax.jit(...)`` (including the
nested ``def run`` closures in the compiled-kernel caches), functions
decorated ``@jax.jit``/``@partial(jax.jit, ...)``, and kernels passed
to ``pl.pallas_call``. ``jax.jit(factory(...))`` — the autotuner's
timing-kernel pattern — resolves through the factory to the closure it
returns, so those bodies are checked too. Calls resolve by simple name
within a module and through ``from tendermint_tpu.ops import field32
as field``-style aliases across ops modules; impure names pulled in
via ``from time import perf_counter``-style imports are flagged under
their source module just like dotted calls.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from scripts.analysis.core import (
    Checker,
    Finding,
    Module,
    Project,
    dotted_name,
    parent_map,
)

OPS_PREFIX = "tendermint_tpu/ops/"

_BAD_DTYPES = {"int64", "float64", "float16", "bfloat16"}
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance", "range", "enumerate", "zip", "getattr",
                 "hasattr", "min", "max"}
_LOGGER_METHODS = {"debug", "info", "warn", "warning", "error"}
# ``from <module> import name`` sources whose names are impure when
# called bare inside a trace (TPJ001 via _impure_from_imports).
_IMPURE_FROM_MODULES = {"time", "random", "os", "secrets"}


def _fn_key(mod_rel: str, name: str) -> Tuple[str, str]:
    return (mod_rel, name)


class _FnInfo:
    def __init__(self, module: Module, node: ast.AST, qualname: str):
        self.module = module
        self.node = node
        self.qualname = qualname


class JaxPurityChecker(Checker):
    name = "jaxpurity"
    codes = {
        "TPJ001": "impure call reachable from a jit/pallas entry point",
        "TPJ002": "Python-side branch on a traced value in a kernel",
        "TPJ003": "dtype outside the uint8/int32/f32 kernel discipline",
    }

    def check_project(self, project: Project) -> Iterator[Finding]:
        ops_modules = [
            m for m in project.modules if m.rel.startswith(OPS_PREFIX)
        ]
        if not ops_modules:
            return
        fns: Dict[Tuple[str, str], _FnInfo] = {}
        aliases: Dict[str, Dict[str, str]] = {}  # mod.rel -> alias -> mod.rel
        impure: Dict[str, Dict[str, str]] = {}  # mod.rel -> name -> origin
        for mod in ops_modules:
            aliases[mod.rel] = self._import_aliases(mod, ops_modules)
            impure[mod.rel] = self._impure_from_imports(mod)
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fns.setdefault(
                        _fn_key(mod.rel, node.name),
                        _FnInfo(mod, node, node.name),
                    )
        entries = self._entry_points(ops_modules, fns)
        reachable = self._reach(entries, fns, aliases)
        for key in sorted(reachable):
            info = fns.get(key)
            if info is not None:
                yield from self._check_fn(info, impure.get(key[0], {}))
        for mod in ops_modules:
            yield from self._check_dtypes(mod)

    # --- call graph ----------------------------------------------------------

    def _import_aliases(
        self, mod: Module, ops_modules: List[Module]
    ) -> Dict[str, str]:
        """alias name -> ops module rel path (``field`` -> ops/field32.py)."""
        by_stem = {
            m.rel.rsplit("/", 1)[-1][:-3]: m.rel for m in ops_modules
        }
        out: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    if alias.name in by_stem:
                        out[alias.asname or alias.name] = by_stem[alias.name]
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    stem = alias.name.rsplit(".", 1)[-1]
                    if stem in by_stem:
                        out[alias.asname or stem] = by_stem[stem]
        return out

    def _impure_from_imports(self, mod: Module) -> Dict[str, str]:
        """Bare names that resolve to impure modules: ``from time import
        perf_counter`` makes a later ``perf_counter()`` as much a
        trace-time side effect as ``time.perf_counter()``."""
        out: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.ImportFrom)
                and node.module in _IMPURE_FROM_MODULES
            ):
                for alias in node.names:
                    out[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )
        return out

    def _entry_points(
        self,
        ops_modules: List[Module],
        fns: Dict[Tuple[str, str], _FnInfo],
    ) -> Set[Tuple[str, str]]:
        entries: Set[Tuple[str, str]] = set()
        for mod in ops_modules:
            for node in ast.walk(mod.tree):
                # jax.jit(fn, ...) / jit(fn) / pl.pallas_call(kernel, ...)
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func) or ""
                    if callee.endswith("jit") or callee.endswith("pallas_call"):
                        for arg in node.args[:1]:
                            if isinstance(arg, ast.Name):
                                key = _fn_key(mod.rel, arg.id)
                                if key in fns:
                                    entries.add(key)
                            elif isinstance(arg, ast.Call) and isinstance(
                                arg.func, ast.Name
                            ):
                                # jax.jit(factory(...)): the traced body
                                # is whatever closure the factory returns.
                                entries.update(
                                    self._factory_returns(
                                        mod.rel, arg.func.id, fns
                                    )
                                )
                # decorators
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        target = dec.func if isinstance(dec, ast.Call) else dec
                        name = dotted_name(target) or ""
                        inner = ""
                        if isinstance(dec, ast.Call) and dec.args:
                            inner = dotted_name(dec.args[0]) or ""
                        if (
                            name.endswith("jit")
                            or (name.endswith("partial") and inner.endswith("jit"))
                        ):
                            entries.add(_fn_key(mod.rel, node.name))
        return entries

    def _factory_returns(
        self,
        mod_rel: str,
        factory_name: str,
        fns: Dict[Tuple[str, str], _FnInfo],
    ) -> Set[Tuple[str, str]]:
        """Functions a local factory returns by name — those closures
        are the real jit entry points of ``jax.jit(factory(...))``."""
        info = fns.get(_fn_key(mod_rel, factory_name))
        if info is None:
            return set()
        out: Set[Tuple[str, str]] = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                key = _fn_key(mod_rel, node.value.id)
                if key in fns:
                    out.add(key)
        return out

    def _calls_of(
        self, info: _FnInfo, aliases: Dict[str, Dict[str, str]]
    ) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        mod_aliases = aliases.get(info.module.rel, {})
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name):
                out.add(_fn_key(info.module.rel, fn.id))
            elif (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mod_aliases
            ):
                out.add(_fn_key(mod_aliases[fn.value.id], fn.attr))
        return out

    def _reach(
        self,
        entries: Set[Tuple[str, str]],
        fns: Dict[Tuple[str, str], _FnInfo],
        aliases: Dict[str, Dict[str, str]],
    ) -> Set[Tuple[str, str]]:
        seen: Set[Tuple[str, str]] = set()
        work = [k for k in entries if k in fns]
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            for callee in self._calls_of(fns[key], aliases):
                if callee in fns and callee not in seen:
                    work.append(callee)
        return seen

    # --- per-function rules --------------------------------------------------

    def _check_fn(
        self, info: _FnInfo, impure_names: Dict[str, str]
    ) -> Iterator[Finding]:
        mod = info.module
        node = info.node
        params = {
            a.arg
            for a in (
                list(node.args.posonlyargs)
                + list(node.args.args)
                + list(node.args.kwonlyargs)
            )
        }
        if node.args.vararg:
            params.add(node.args.vararg.arg)
        if node.args.kwarg:
            params.add(node.args.kwarg.arg)
        local_names = set(params)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                local_names.add(sub.id)
        nested: Set[ast.AST] = set()
        for sub in ast.walk(node):
            if sub is not node and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                nested.add(sub)
                nested.update(ast.walk(sub))
        for sub in ast.walk(node):
            if sub in nested:
                continue  # nested defs are reached (or not) on their own
            if isinstance(sub, ast.Call):
                reason = self._impure_call(sub, impure_names)
                if reason:
                    yield Finding(
                        mod.rel,
                        sub.lineno,
                        "TPJ001",
                        f"{reason} inside jit-reachable "
                        f"'{info.qualname}' (trace-time side effect)",
                    )
            elif isinstance(sub, ast.Attribute):
                path = dotted_name(sub) or ""
                if path == "os.environ":
                    yield Finding(
                        mod.rel,
                        sub.lineno,
                        "TPJ001",
                        f"os.environ read inside jit-reachable "
                        f"'{info.qualname}' (trace-time side effect)",
                    )
            elif isinstance(sub, (ast.If, ast.While, ast.Assert)):
                if self._is_string_compare(sub.test):
                    continue  # comparing to string constants = host config
                traced = self._traced_test_names(sub.test, local_names)
                if traced:
                    names = ", ".join(sorted(traced))
                    kind = type(sub).__name__.lower()
                    yield Finding(
                        mod.rel,
                        sub.lineno,
                        "TPJ002",
                        f"Python-side {kind} on possibly-traced "
                        f"value(s) {names} in jit-reachable "
                        f"'{info.qualname}' (use lax.cond/select)",
                    )

    def _impure_call(
        self, call: ast.Call, impure_names: Dict[str, str]
    ) -> Optional[str]:
        path = dotted_name(call.func) or ""
        head = path.split(".", 1)[0]
        if isinstance(call.func, ast.Name) and call.func.id in impure_names:
            return f"{impure_names[call.func.id]}() call (via from-import)"
        if head == "time" and "." in path:
            return f"{path}() call"
        if path.startswith(("random.", "np.random.", "numpy.random.")):
            return f"{path}() call"
        if path in ("os.urandom", "os.getenv"):
            return f"{path}() call"
        if path in ("print", "open", "input"):
            return f"{path}() call"
        if head == "tracing" and "." in path:
            return f"{path}() span"
        if isinstance(call.func, ast.Attribute):
            recv = dotted_name(call.func.value) or ""
            if (
                call.func.attr in _LOGGER_METHODS
                and "log" in recv.rsplit(".", 1)[-1].lower()
            ):
                return f"logger .{call.func.attr}() call"
        return None

    def _is_string_compare(self, test: ast.expr) -> bool:
        """``impl == "mxu"`` / ``impl not in ("vpu", "mxu")``: traced
        arrays are never strings, so a comparison whose right-hand side
        is all string constants is host-side configuration."""
        if not isinstance(test, ast.Compare):
            return False

        def all_strings(node: ast.expr) -> bool:
            if isinstance(node, ast.Constant):
                return isinstance(node.value, str)
            if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                return all(all_strings(e) for e in node.elts)
            return False

        return all(all_strings(c) for c in test.comparators)

    def _traced_test_names(
        self, test: ast.expr, local_names: Set[str]
    ) -> Set[str]:
        """Names of params/locals the test depends on as VALUES (not via
        static projections like .shape / len() / isinstance())."""
        parents = parent_map(test)
        traced: Set[str] = set()
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name) and node.id in local_names):
                continue
            parent = parents.get(node)
            # x.shape / x.ndim / x.dtype / x.size are static under trace
            if (
                isinstance(parent, ast.Attribute)
                and parent.attr in _SHAPE_ATTRS
            ):
                continue
            # len(x), isinstance(x, T), range(x) ... are static
            if isinstance(parent, ast.Call):
                callee = parent.func
                if (
                    isinstance(callee, ast.Name)
                    and callee.id in _STATIC_CALLS
                    and node in parent.args
                ):
                    continue
                if callee is node:
                    continue  # calling a local fn, not branching on data
            traced.add(node.id)
        return traced

    # --- dtype rule ----------------------------------------------------------

    def _check_dtypes(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr in _BAD_DTYPES:
                base = dotted_name(node.value) or ""
                if base in ("jnp", "np", "jax.numpy", "numpy", "jnp.dtypes"):
                    yield Finding(
                        mod.rel,
                        node.lineno,
                        "TPJ003",
                        f"{base}.{node.attr} breaks the uint8/int32/f32 "
                        "field-kernel dtype discipline",
                    )
            elif (
                isinstance(node, ast.keyword)
                and node.arg == "dtype"
                and isinstance(node.value, ast.Constant)
                and node.value.value in _BAD_DTYPES
            ):
                yield Finding(
                    mod.rel,
                    node.value.lineno,
                    "TPJ003",
                    f"dtype={node.value.value!r} breaks the uint8/int32/f32 "
                    "field-kernel dtype discipline",
                )
