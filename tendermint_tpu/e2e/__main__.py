"""``python -m tendermint_tpu.e2e <manifest.toml>`` (test/e2e/runner)."""

from tendermint_tpu.e2e.runner import main

raise SystemExit(main())
