"""State syncer: discover snapshots, restore the app, bootstrap state.

The client half of state sync (internal/statesync/syncer.go:353-535 +
stateprovider.go:33-361): broadcast discovery, rank offered snapshots,
build a verified sm.State at the snapshot height from light blocks
(anchored at a configured trust (height, hash), walked to the target
through the light-client verifier), OfferSnapshot to the app, fetch
chunks with concurrent fetchers feeding an in-order applier, check the
restored app against the trusted app hash, then bootstrap the stores
and optionally backfill verified headers for the evidence window
(reactor.go:416 Backfill).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Set, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.light import verifier as light_verifier
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.state.state import State
from tendermint_tpu.types.block import Consensus
from tendermint_tpu.types.light import LightBlock
from tendermint_tpu.types.validation import Fraction


class SyncAbortedError(RuntimeError):
    pass


class NoSnapshotError(RuntimeError):
    pass


class StateSyncFatalError(RuntimeError):
    """Failure AFTER the app was mutated by a snapshot restore: the node
    must not retry other snapshots or degrade to block sync from genesis
    on top of restored state."""


# Bound attacker-controlled chunk counts before any allocation.
MAX_SNAPSHOT_CHUNKS = 16384


@dataclass
class StateSyncConfig:
    """config/config.go StateSyncConfig condensed."""

    enabled: bool = False
    trust_height: int = 0
    trust_hash: bytes = b""
    trust_period: float = 14 * 86400.0
    discovery_time: float = 2.0
    chunk_fetchers: int = 4  # config.go:863-882 Fetchers default
    chunk_timeout: float = 10.0
    light_block_timeout: float = 10.0
    backfill_blocks: int = 0
    max_clock_drift: float = 10.0


_SnapKey = Tuple[int, int, bytes, int]  # height, format, hash, chunks


class StateSyncer:
    def __init__(self, reactor, app_client, state_store, block_store, genesis, config):
        if not config.trust_hash or config.trust_height <= 0:
            # Without a verified anchor every light block is accepted on a
            # single peer's say-so — refuse the configuration (the
            # reference requires trust_height+trust_hash the same way).
            raise ValueError(
                "state sync requires trust_height > 0 and a non-empty "
                "trust_hash anchor"
            )
        self.reactor = reactor
        self.app = app_client
        self.state_store = state_store
        self.block_store = block_store
        self.genesis = genesis
        self.config = config
        self._mtx = threading.Lock()
        self._cond = threading.Condition(self._mtx)
        self._snapshots: Dict[_SnapKey, Set[str]] = {}  # -> peers serving it
        self._light_blocks: Dict[int, LightBlock] = {}
        self._params: Dict[int, object] = {}
        self._chunks: Dict[int, Optional[bytes]] = {}
        self._chunk_target: Optional[Tuple[int, int]] = None  # (height, format)
        self.backfilled: Dict[int, LightBlock] = {}

    # --- reactor sinks --------------------------------------------------------

    def install(self) -> None:
        self.reactor.on_snapshot = self._on_snapshot
        self.reactor.on_chunk = self._on_chunk
        self.reactor.on_light_block = self._on_light_block
        self.reactor.on_params = self._on_params

    def _on_snapshot(self, peer: str, s: abci.Snapshot) -> None:
        if not (0 < s.chunks <= MAX_SNAPSHOT_CHUNKS) or s.height <= 0:
            return  # hostile/garbage advertisement
        key = (s.height, s.format, s.hash, s.chunks)
        with self._cond:
            self._snapshots.setdefault(key, set()).add(peer)
            self._cond.notify_all()

    def _on_chunk(self, peer, height, format_, index, body) -> None:
        with self._cond:
            if self._chunk_target != (height, format_):
                return
            if body is not None and self._chunks.get(index) is None:
                self._chunks[index] = body
            self._cond.notify_all()

    def _on_light_block(self, peer, height, lb) -> None:
        with self._cond:
            if lb is not None and height not in self._light_blocks:
                # Basic integrity: the signed header must hash-match itself.
                if (
                    lb.signed_header.header is not None
                    and lb.signed_header.commit is not None
                    and lb.signed_header.commit.block_id.hash
                    == lb.signed_header.header.hash()
                    and lb.validator_set is not None
                    and lb.validator_set.hash()
                    == lb.signed_header.header.validators_hash
                ):
                    self._light_blocks[height] = lb
            self._cond.notify_all()

    def _on_params(self, peer, height, params) -> None:
        with self._cond:
            self._params.setdefault(height, params)
            self._cond.notify_all()

    # --- fetch helpers --------------------------------------------------------

    def _peers(self) -> List[str]:
        with self._mtx:
            out: Set[str] = set()
            for peers in self._snapshots.values():
                out |= peers
        return sorted(out)

    def _fetch_light_block(self, height: int) -> LightBlock:
        deadline = time.monotonic() + self.config.light_block_timeout
        peers = self._peers()
        i = 0
        while time.monotonic() < deadline:
            with self._cond:
                if height in self._light_blocks:
                    return self._light_blocks[height]
            if peers:
                self.reactor.request_light_block(peers[i % len(peers)], height)
                i += 1
            with self._cond:
                self._cond.wait(0.25)
        raise SyncAbortedError(f"no light block at height {height}")

    def _fetch_params(self, height: int):
        deadline = time.monotonic() + self.config.light_block_timeout
        peers = self._peers()
        i = 0
        while time.monotonic() < deadline:
            with self._cond:
                if height in self._params:
                    return self._params[height]
            if peers:
                self.reactor.request_params(peers[i % len(peers)], height)
                i += 1
            with self._cond:
                self._cond.wait(0.25)
        raise SyncAbortedError(f"no consensus params at height {height}")

    # --- the state provider ---------------------------------------------------

    def _verified_light_block(
        self, height: int, trusted: LightBlock
    ) -> LightBlock:
        """Walk trust from `trusted` to `height` via the light verifier
        (stateprovider.go uses an embedded light client the same way)."""
        lb = self._fetch_light_block(height)
        now = Timestamp.from_unix_ns(time.time_ns() + 10**9)
        light_verifier.verify(
            trusted.signed_header,
            trusted.validator_set,
            lb.signed_header,
            lb.validator_set,
            self.config.trust_period,
            now,
            self.config.max_clock_drift,
            Fraction(1, 3),
        )
        return lb

    def _build_state(self, snapshot: abci.Snapshot) -> Tuple[State, LightBlock]:
        """stateprovider.go State(): state at the snapshot height from
        three verified light blocks (h, h+1, h+2)."""
        cfg = self.config
        h = snapshot.height
        anchor = self._fetch_light_block(cfg.trust_height)
        if cfg.trust_hash and anchor.signed_header.header.hash() != cfg.trust_hash:
            raise SyncAbortedError(
                f"trust hash mismatch at height {cfg.trust_height}"
            )
        base = self._verified_light_block(h, anchor) if h != cfg.trust_height else anchor
        nxt = self._verified_light_block(h + 1, base)
        nxt2 = self._verified_light_block(h + 2, nxt)
        params = self._fetch_params(h + 1)

        state = State(
            version=Consensus(
                block=base.signed_header.header.version.block,
                app=base.signed_header.header.version.app,
            ),
            chain_id=self.genesis.chain_id,
            initial_height=self.genesis.initial_height,
            last_block_height=h,
            last_block_id=base.signed_header.commit.block_id,
            last_block_time=base.signed_header.header.time,
            next_validators=nxt2.validator_set,
            validators=nxt.validator_set,
            last_validators=base.validator_set,
            last_height_validators_changed=h + 1,
            consensus_params=params,
            last_height_consensus_params_changed=h + 1,
            last_results_hash=nxt.signed_header.header.last_results_hash,
            app_hash=nxt.signed_header.header.app_hash,
        )
        return state, base

    # --- chunk restore --------------------------------------------------------

    def _restore_chunks(self, snapshot: abci.Snapshot, peers: List[str]) -> bool:
        """4 concurrent fetchers + in-order apply (syncer.go:389-533).
        True = app fully restored; False = app rejected the snapshot and
        wiped its own state (safe to try another). Raises on timeout
        (app not yet mutated — chunks only land at the final apply)."""
        with self._cond:
            self._chunks = {i: None for i in range(snapshot.chunks)}
            self._chunk_target = (snapshot.height, snapshot.format)
        stop = threading.Event()
        next_req = {"i": 0}

        def fetcher(worker: int) -> None:
            # Runs until the applier stops it — chunks can be re-nulled by
            # APPLY_CHUNK_RETRY/RETRY_SNAPSHOT after all of them arrived,
            # so "nothing pending" only means idle, never done.
            while not stop.is_set():
                with self._cond:
                    pending = [i for i, c in self._chunks.items() if c is None]
                    if pending:
                        i = pending[next_req["i"] % len(pending)]
                        next_req["i"] += 1
                    else:
                        i = None
                if i is not None:
                    peer = peers[(worker + next_req["i"]) % len(peers)]
                    self.reactor.request_chunk(
                        peer, snapshot.height, snapshot.format, i
                    )
                with self._cond:
                    self._cond.wait(0.3)

        threads = [
            threading.Thread(target=fetcher, args=(w,), daemon=True)
            for w in range(min(self.config.chunk_fetchers, max(len(peers), 1)))
        ]
        for t in threads:
            t.start()
        try:
            deadline = time.monotonic() + self.config.chunk_timeout * snapshot.chunks
            applied = 0
            while applied < snapshot.chunks:
                if time.monotonic() > deadline:
                    raise SyncAbortedError("chunk fetch timed out")
                with self._cond:
                    body = self._chunks.get(applied)
                    if body is None:
                        self._cond.wait(0.25)
                        continue
                res = self.app.apply_snapshot_chunk(
                    abci.RequestApplySnapshotChunk(index=applied, chunk=body)
                )
                if res.result == abci.APPLY_CHUNK_ACCEPT:
                    applied += 1
                elif res.result == abci.APPLY_CHUNK_RETRY:
                    with self._cond:
                        self._chunks[applied] = None
                elif res.result == abci.APPLY_CHUNK_RETRY_SNAPSHOT:
                    with self._cond:
                        for i in self._chunks:
                            self._chunks[i] = None
                    applied = 0
                else:
                    return False  # rejected/aborted; app wiped its state
                for i in res.refetch_chunks:
                    with self._cond:
                        self._chunks[i] = None
            return True
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=1)
            with self._cond:
                self._chunk_target = None

    # --- backfill -------------------------------------------------------------

    def _backfill(self, base: LightBlock) -> None:
        """Verify headers backwards from the snapshot height so evidence
        within the window can be validated (reactor.go Backfill:416)."""
        base_height = base.signed_header.header.height
        stop_at = max(base_height - self.config.backfill_blocks, 1)
        trusted = base
        for height in range(base_height - 1, stop_at - 1, -1):
            lb = self._fetch_light_block(height)
            light_verifier.verify_backwards(
                lb.signed_header.header, trusted.signed_header.header
            )
            self.backfilled[height] = lb
            self.state_store._save_validators(height, height, lb.validator_set)
            trusted = lb

    # --- the main entry -------------------------------------------------------

    def sync(self, timeout: float = 60.0) -> State:
        """Discover, restore, bootstrap; returns the bootstrapped state."""
        self.install()
        deadline = time.monotonic() + timeout
        self.reactor.request_snapshots()
        time.sleep(self.config.discovery_time)

        tried: Set[_SnapKey] = set()
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            with self._mtx:
                candidates = sorted(
                    (k for k in self._snapshots if k not in tried),
                    key=lambda k: (-k[0], k[1]),
                )
                peers_by_key = {k: sorted(self._snapshots[k]) for k in candidates}
            if not candidates:
                self.reactor.request_snapshots()
                time.sleep(0.5)
                continue
            key = candidates[0]
            tried.add(key)
            snapshot = abci.Snapshot(
                height=key[0], format=key[1], chunks=key[3], hash=key[2]
            )
            try:
                state, base_lb = self._build_state(snapshot)
                res = self.app.offer_snapshot(
                    abci.RequestOfferSnapshot(
                        snapshot=snapshot, app_hash=state.app_hash
                    )
                )
                if res.result != abci.OFFER_SNAPSHOT_ACCEPT:
                    raise SyncAbortedError(f"snapshot offer result {res.result}")
                restored = self._restore_chunks(snapshot, peers_by_key[key])
            except (SyncAbortedError, light_verifier.InvalidHeaderError) as e:
                last_err = e
                continue
            if not restored:
                # The app rejected the assembled payload (bad hash / bad
                # content) and wiped its state — another snapshot is safe.
                last_err = SyncAbortedError("snapshot rejected by app")
                continue
            # The app now holds restored state: any failure past this
            # point is fatal (retrying onto mutated state is unsound).
            try:
                self._verify_app(state)
                self.state_store.bootstrap(state)
                self.block_store.save_seen_commit(base_lb.signed_header.commit)
                if self.config.backfill_blocks > 0:
                    self._backfill(base_lb)
            except Exception as e:
                raise StateSyncFatalError(
                    f"post-restore state sync failure at height "
                    f"{snapshot.height}: {e}"
                ) from e
            return state
        raise NoSnapshotError(f"state sync failed: {last_err}")

    def _verify_app(self, state: State) -> None:
        """syncer.go verifyApp:535: Info must report the restored height
        and the trusted app hash."""
        info = self.app.info(abci.RequestInfo())
        if info.last_block_app_hash != state.app_hash:
            raise SyncAbortedError(
                f"restored app hash {info.last_block_app_hash.hex()} != "
                f"trusted {state.app_hash.hex()}"
            )
        if info.last_block_height != state.last_block_height:
            raise SyncAbortedError(
                f"restored app height {info.last_block_height} != "
                f"{state.last_block_height}"
            )
