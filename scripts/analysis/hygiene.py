"""Hygiene checkers (TPH): exception, thread, and logging discipline.

- TPH001 — bare ``except:``: catches ``SystemExit``/``KeyboardInterrupt``
  too, which is how a verify daemon ends up unkillable. Name the
  exception types.
- TPH002 — ``except <T>: pass`` with no rationale comment: a silent
  swallow is sometimes right (best-effort close paths), but then it
  must say WHY on the same line or inside the handler. A comment
  anywhere in the handler counts as the rationale.
- TPH003 — ``threading.Thread(...)`` that is neither ``daemon=True``
  nor ever ``.join()``-ed in the same file: such a thread blocks
  interpreter shutdown forever if its loop doesn't exit — the exact
  hang the scheduler's accumulator avoids by being a joined daemon.
- TPH004 — eager interpolation into a ``libs/log`` logger call:
  f-strings / ``%`` / ``.format()`` passed as the message build the
  string even when the level is filtered, and bypass the structured
  ``key=value`` fields the log format wants. Pass fields as kwargs.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from scripts.analysis.core import Checker, Finding, Module, dotted_name

_LOGGER_METHODS = {"debug", "info", "warn", "warning", "error", "critical",
                   "exception"}


def _handler_has_comment(module: Module, handler: ast.ExceptHandler) -> bool:
    end = handler.end_lineno or handler.lineno
    for line in range(handler.lineno, end + 1):
        if module.comment_on(line):
            return True
    return False


def _is_pass_only(handler: ast.ExceptHandler) -> bool:
    return len(handler.body) == 1 and isinstance(handler.body[0], ast.Pass)


class HygieneChecker(Checker):
    name = "hygiene"
    codes = {
        "TPH001": "bare except: catches SystemExit/KeyboardInterrupt",
        "TPH002": "silent except-pass without a rationale comment",
        "TPH003": "non-daemon thread that is never joined",
        "TPH004": "eager string interpolation into a libs/log logger",
    }

    def check_module(self, module: Module) -> Iterator[Finding]:
        yield from self._check_excepts(module)
        yield from self._check_threads(module)
        yield from self._check_logging(module)

    # --- exceptions ----------------------------------------------------------

    def _check_excepts(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(
                    module.rel,
                    node.lineno,
                    "TPH001",
                    "bare 'except:' also catches SystemExit/"
                    "KeyboardInterrupt; name the exception types",
                )
                continue
            if _is_pass_only(node) and not _handler_has_comment(module, node):
                if isinstance(node.type, ast.Tuple):
                    caught = "(%s)" % ", ".join(
                        dotted_name(e) or "?" for e in node.type.elts
                    )
                else:
                    caught = dotted_name(node.type) or "exception"
                yield Finding(
                    module.rel,
                    node.lineno,
                    "TPH002",
                    f"'except {caught}: pass' swallows errors silently; "
                    "log it, handle it, or add a rationale comment",
                )

    # --- threads -------------------------------------------------------------

    def _thread_ctor_daemon(self, call: ast.Call) -> Optional[bool]:
        """True/False for an explicit daemon= kwarg, None if absent."""
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return None

    def _check_threads(self, module: Module) -> Iterator[Finding]:
        # Names that get .join()ed or .daemon = True anywhere in the file;
        # a file-level over-approximation is the right precision here —
        # the goal is catching threads NOBODY ever reaps.
        joined: Set[str] = set()
        daemoned: Set[str] = set()
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                base = dotted_name(node.func.value)
                if base:
                    joined.add(base.rsplit(".", 1)[-1])
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        base = dotted_name(t.value)
                        if base:
                            daemoned.add(base.rsplit(".", 1)[-1])
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)):
                continue
            callee = dotted_name(node.func) or ""
            if callee.rsplit(".", 1)[-1] != "Thread":
                continue
            daemon = self._thread_ctor_daemon(node)
            if daemon is True:
                continue
            target = self._assigned_name(module, node)
            if target and (target in joined or target in daemoned):
                continue
            yield Finding(
                module.rel,
                node.lineno,
                "TPH003",
                "thread is not daemon=True and is never joined; it will "
                "block interpreter shutdown",
            )

    def _assigned_name(self, module: Module, call: ast.Call) -> Optional[str]:
        """X for ``X = Thread(...)`` / ``self.X = Thread(...)``."""
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        return t.id
                    if isinstance(t, ast.Attribute):
                        return t.attr
        return None

    # --- logging -------------------------------------------------------------

    def _check_logging(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOGGER_METHODS
                and node.args
            ):
                continue
            recv = dotted_name(node.func.value) or ""
            leaf = recv.rsplit(".", 1)[-1].lower()
            if "log" not in leaf:
                continue
            msg = node.args[0]
            bad = None
            if isinstance(msg, ast.JoinedStr):
                bad = "f-string"
            elif isinstance(msg, ast.BinOp) and isinstance(msg.op, ast.Mod):
                bad = "%-format"
            elif (
                isinstance(msg, ast.Call)
                and isinstance(msg.func, ast.Attribute)
                and msg.func.attr == "format"
            ):
                bad = ".format() call"
            if bad:
                yield Finding(
                    module.rel,
                    msg.lineno,
                    "TPH004",
                    f"{bad} interpolated into logger .{node.func.attr}(); "
                    "pass a constant message with key=value fields",
                )
