"""Trusted light-block store (light/store/store.go + store/db).

Persists verified LightBlocks keyed by height over the KV abstraction;
also usable fully in-memory via MemDB.
"""

from __future__ import annotations

import threading
from typing import Optional

from tendermint_tpu.storage.kv import KVStore, MemDB, ordered_key, prefix_end
from tendermint_tpu.types.light import LightBlock

PREFIX_LIGHT_BLOCK = 11


def _lb_key(height: int) -> bytes:
    return ordered_key(PREFIX_LIGHT_BLOCK, height)


class LightStore:
    """light/store.Store over a KVStore (light/store/db/db.go)."""

    def __init__(self, db: Optional[KVStore] = None):
        self._db = db or MemDB()
        self._lock = threading.Lock()

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("lightBlock.Height <= 0")
        with self._lock:
            self._db.set(_lb_key(lb.height), lb.to_proto_bytes())

    def delete_light_block(self, height: int) -> None:
        with self._lock:
            self._db.delete(_lb_key(height))

    def light_block(self, height: int) -> Optional[LightBlock]:
        raw = self._db.get(_lb_key(height))
        return LightBlock.from_proto_bytes(raw) if raw is not None else None

    def latest_light_block(self) -> Optional[LightBlock]:
        for _, v in self._db.reverse_iterator(
            _lb_key(0), prefix_end(bytes([PREFIX_LIGHT_BLOCK]))
        ):
            return LightBlock.from_proto_bytes(v)
        return None

    def first_light_block(self) -> Optional[LightBlock]:
        for _, v in self._db.iterator(
            _lb_key(0), prefix_end(bytes([PREFIX_LIGHT_BLOCK]))
        ):
            return LightBlock.from_proto_bytes(v)
        return None

    def light_block_before(self, height: int) -> Optional[LightBlock]:
        """Highest stored block with height < `height` (db.go
        LightBlockBefore)."""
        for _, v in self._db.reverse_iterator(_lb_key(0), _lb_key(height)):
            return LightBlock.from_proto_bytes(v)
        return None

    def heights(self) -> list:
        """All stored heights, ascending. lightd uses the before/after
        delta of this to memoize which pivots proved a verification."""
        return [
            int.from_bytes(k[1:9], "big")
            for k, _ in self._db.iterator(
                _lb_key(0), prefix_end(bytes([PREFIX_LIGHT_BLOCK]))
            )
        ]

    def prune(self, size: int) -> None:
        """Keep only the newest `size` blocks (db.go Prune)."""
        heights = [
            int.from_bytes(k[1:9], "big")
            for k, _ in self._db.iterator(
                _lb_key(0), prefix_end(bytes([PREFIX_LIGHT_BLOCK]))
            )
        ]
        for h in heights[: max(0, len(heights) - size)]:
            self.delete_light_block(h)

    def size(self) -> int:
        return sum(
            1
            for _ in self._db.iterator(
                _lb_key(0), prefix_end(bytes([PREFIX_LIGHT_BLOCK]))
            )
        )
