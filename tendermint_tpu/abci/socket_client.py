"""ABCI socket client: drive an out-of-process app.

The counterpart of abci/client/socket_client.go:417 with synchronous
call semantics (our callers — executor, mempool, syncers — are
synchronous; the reference's async pipelining exists to feed its own
async callers). One TCP connection, one in-flight request at a time
behind a mutex, bounded per-call timeout, auto-reconnect on the next
call after a connection failure.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

from tendermint_tpu.abci import codec
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import AbciClient


class ABCIConnectionError(ConnectionError):
    pass


class SocketClient(AbciClient):
    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._addr = (host, port)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._mtx = threading.Lock()
        self._running = False

    # --- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            self._connect()
            self._running = True

    def stop(self) -> None:
        with self._mtx:
            self._running = False
            self._close()

    def is_running(self) -> bool:
        return self._running

    def _connect(self) -> None:
        if self._sock is not None:
            return
        s = socket.create_connection(self._addr, timeout=self._timeout)
        s.settimeout(self._timeout)
        self._sock = s

    def _close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # --- request plumbing -------------------------------------------------------

    def _call(self, type_: str, body) -> dict:
        with self._mtx:
            try:
                self._connect()
                self._sock.sendall(codec.encode_frame("req", type_, body))
                raw = codec.read_frame(self._sock)
            except (OSError, ValueError) as exc:
                self._close()
                raise ABCIConnectionError(f"abci {type_}: {exc}") from exc
            if raw is None:
                self._close()
                raise ABCIConnectionError(f"abci {type_}: connection closed")
        kind, rtype, rbody = codec.decode_frame(raw)
        if kind == "exc":
            raise RuntimeError(f"abci {type_} failed: {rbody.get('error')}")
        if rtype != type_:
            self._close()
            raise ABCIConnectionError(
                f"abci response type {rtype!r} != request {type_!r}"
            )
        return rbody

    def _request(self, type_: str, req):
        _, res_cls = codec.METHODS[type_]
        body = codec.encode_obj(req) if req is not None else None
        return codec.decode_obj(res_cls, self._call(type_, body))

    # --- AbciClient -------------------------------------------------------------

    def echo(self, msg: str) -> str:
        return self._call("echo", {"message": msg}).get("message", "")

    def flush(self) -> None:
        self._call("flush", None)

    def info(self, req):
        return self._request("info", req)

    def query(self, req):
        return self._request("query", req)

    def check_tx(self, req):
        return self._request("check_tx", req)

    def init_chain(self, req):
        return self._request("init_chain", req)

    def prepare_proposal(self, req):
        return self._request("prepare_proposal", req)

    def process_proposal(self, req):
        return self._request("process_proposal", req)

    def extend_vote(self, req):
        return self._request("extend_vote", req)

    def verify_vote_extension(self, req):
        return self._request("verify_vote_extension", req)

    def finalize_block(self, req):
        return self._request("finalize_block", req)

    def commit(self):
        return self._request("commit", None)

    def list_snapshots(self, req):
        return self._request("list_snapshots", req)

    def offer_snapshot(self, req):
        return self._request("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._request("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._request("apply_snapshot_chunk", req)
