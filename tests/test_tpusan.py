"""tpusan happens-before + schedule-explorer tests.

The hb detector is exact on the schedule it observes: a race is
reported iff two conflicting accesses are not ordered by any chain of
sync edges (lock release->acquire, Thread.start/join, Event/Condition,
queue hand-off). The fixture pair below is the calibration standard —
the racy twin MUST be flagged with both stacks, the guarded twin MUST
stay silent — and the explorer makes the verdict a pure function of
the seed, which the byte-identical replay test pins.

These tests install hb mode themselves, so they run (and must pass)
in a plain tier-1 run with TENDERMINT_TPU_SANITIZE unset.
"""

import threading
import time

import pytest

from tendermint_tpu.libs import sanitizer as san


# --- fixture twins -----------------------------------------------------------


@san.instrument_attrs
class RacyCounter:
    """The seeded race: ``n`` is mutated with no lock and polled from
    another thread. tpusan must flag the read/write pair."""

    def __init__(self):
        self.n = 0

    def bump_many(self, k):
        for _ in range(k):
            self.n += 1


@san.instrument_attrs
class GuardedCounter:
    """The clean twin: same shape, every access under ``_mtx``."""

    def __init__(self):
        self._mtx = threading.Lock()
        self.n = 0  # guarded-by: _mtx

    def bump_many(self, k):
        for _ in range(k):
            with self._mtx:
                self.n += 1

    def value(self):
        with self._mtx:
            return self.n


@pytest.fixture()
def hb():
    """Enable hb mode for one test (or reuse a global env install),
    always restoring the pre-test state."""
    was_installed = san.installed()
    was_hb = san.hb_enabled()
    san.install(mode="hb")
    san.reset()
    try:
        yield san
    finally:
        san.reset()
        if not was_installed:
            san.uninstall()
        elif not was_hb:
            san._disable_hb()


# --- the detector ------------------------------------------------------------


def test_hb_detects_seeded_fixture_race(hb):
    box = RacyCounter()
    t = threading.Thread(target=box.bump_many, args=(200,), daemon=True)
    t.start()
    # unsynchronized poll: start() orders parent->child only, so these
    # reads have NO happens-before path from the child's writes
    deadline = time.monotonic() + 5
    while box.n < 200 and time.monotonic() < deadline:
        time.sleep(0.001)
    t.join(timeout=5)

    races = hb.report()["races"]
    assert any(
        r["cls"] == "RacyCounter" and r["attr"] == "n" for r in races
    ), races
    text = hb.race_report()
    assert "DATA RACE: RacyCounter.n" in text
    # both access stacks are in the report, pointing at real code
    assert "first (" in text and "second (" in text
    assert "bump_many" in text  # the writer frame
    assert "test_hb_detects_seeded_fixture_race" in text  # the reader frame


def test_guarded_twin_is_silent(hb):
    box = GuardedCounter()
    t = threading.Thread(target=box.bump_many, args=(200,), daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while box.value() < 200 and time.monotonic() < deadline:
        time.sleep(0.001)
    t.join(timeout=5)
    assert box.value() == 200
    assert hb.race_report() == ""


def test_join_edge_orders_post_join_reads(hb):
    """A raw read AFTER join is ordered (the child's final clock merges
    into the joiner) — tpusan must not cry wolf on the join idiom."""
    box = RacyCounter()
    t = threading.Thread(target=box.bump_many, args=(50,), daemon=True)
    t.start()
    t.join(timeout=5)
    assert box.n == 50
    assert hb.race_report() == ""


def test_lock_edge_orders_handoff(hb):
    """Release->acquire on the same lock is an edge: a value written
    under the lock then read under the lock is never a race."""
    box = GuardedCounter()
    done = threading.Event()

    def writer():
        box.bump_many(10)
        done.set()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    assert done.wait(timeout=5)
    assert box.value() == 10
    t.join(timeout=5)
    assert hb.race_report() == ""


# --- the explorer ------------------------------------------------------------


def _explore_racy_round(seed):
    san.reset()
    with san.explore_scope(seed):
        box = RacyCounter()
        ts = [
            threading.Thread(target=box.bump_many, args=(25,), daemon=True)
            for _ in range(2)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
    return san.race_report()


def test_same_seed_replays_byte_identical(hb):
    """The replay contract: one seed, one schedule, one report. A race
    found in CI under explore:<seed> reproduces exactly from the seed."""
    for seed in (0, 42, 123):
        first = _explore_racy_round(seed)
        assert "DATA RACE: RacyCounter.n" in first
        for _ in range(2):
            assert _explore_racy_round(seed) == first


def test_explorer_serializes_guarded_twin_clean(hb):
    for seed in (0, 7):
        san.reset()
        with san.explore_scope(seed):
            box = GuardedCounter()
            ts = [
                threading.Thread(
                    target=box.bump_many, args=(25,), daemon=True
                )
                for _ in range(2)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=10)
        assert box.value() == 50
        assert san.race_report() == ""


# --- regression pins for the production race fixes ---------------------------


def _mini_scheduler():
    from tendermint_tpu.crypto.scheduler import VerifyScheduler

    return VerifyScheduler(
        lambda pks, msgs, sigs: [True] * len(pks),
        max_batch=4,
        max_delay=0.002,
        continuous=True,
        pipeline_depth=2,
    )


def test_raw_counter_poll_is_the_bug_hb_catches(hb):
    """The pre-fix pattern in tests/bench — polling a raw scheduler
    counter while the dispatcher runs — is a real race and hb says so.
    (The suites now poll via stats(); this pins WHY.)"""
    s = _mini_scheduler()
    s.start()
    try:
        handles = [s.submit(b"p%d" % i, b"m", b"s") for i in range(8)]
        deadline = time.monotonic() + 5
        while s.dispatch_handoffs < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert s.wait_many(handles, timeout=5) == [True] * 8
    finally:
        s.stop()
    races = hb.report()["races"]
    assert any(
        r["cls"] == "VerifyScheduler" and r["attr"] == "dispatch_handoffs"
        for r in races
    ), races


def test_scheduler_stats_poll_is_race_free(hb):
    """The fix: the same poll through the locked stats() snapshot has a
    release->acquire edge from every counter write. Failed before
    stats() existed."""
    s = _mini_scheduler()
    s.start()
    try:
        handles = [s.submit(b"p%d" % i, b"m", b"s") for i in range(8)]
        deadline = time.monotonic() + 5
        while (
            s.stats()["dispatch_handoffs"] < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.001)
        assert s.wait_many(handles, timeout=5) == [True] * 8
    finally:
        s.stop()
    assert hb.race_report() == "", hb.race_report()


def test_brownout_snapshot_is_race_free(hb):
    """The verifyd observe path: a load thread drives the ladder while
    the main thread reads through snapshot(). Pre-fix, reading .level
    and .transitions raw was unordered against observe()'s writes."""
    from tendermint_tpu.verifyd.server import BrownoutController

    b = BrownoutController(escalate_after=0.01, cooldown_fn=None)
    stop = threading.Event()

    def load():
        t = 0.0
        while not stop.is_set():
            t += 0.02
            b.observe(True, now=t)

    th = threading.Thread(target=load, daemon=True)
    th.start()
    deadline = time.monotonic() + 5
    snap = b.snapshot()
    while snap["level"] == 0 and time.monotonic() < deadline:
        time.sleep(0.001)
        snap = b.snapshot()
    stop.set()
    th.join(timeout=5)
    assert snap["level"] >= 1
    assert sum(snap["transitions"].values()) >= 1
    races = hb.report()["races"]
    assert not [r for r in races if r["cls"] == "BrownoutController"], races


def test_mesh_settlement_is_race_free(hb):
    """Concurrent plan settlement: on_success/on_failure from worker
    threads while another thread reads snapshot(). Pre-fix the
    settlement loop iterated plan.attempts outside _mtx.

    Uses a fresh MeshManager: hb only sees locks created after
    install, and the module singleton's _mtx predates this test's
    install (the env-mode CI stage installs before any import, so
    there the singleton IS covered)."""
    from tendermint_tpu.parallel import mesh

    mgr = mesh.MeshManager()
    mgr.configure(2)
    stop = threading.Event()

    def settle():
        while not stop.is_set():
            plan = mgr.plan()
            if plan is None:
                return
            mgr.on_success(plan)

    def observe():
        while not stop.is_set():
            mgr.snapshot()

    ts = [
        threading.Thread(target=settle, daemon=True),
        threading.Thread(target=settle, daemon=True),
        threading.Thread(target=observe, daemon=True),
    ]
    for t in ts:
        t.start()
    time.sleep(0.05)
    stop.set()
    for t in ts:
        t.join(timeout=5)
    races = hb.report()["races"]
    assert not [r for r in races if r["cls"] == "MeshManager"], races


def test_continuous_batching_clean_across_25_schedules(hb):
    """The acceptance bar: the full submit -> coalesce -> dispatch ->
    resolve cycle of the continuous scheduler is race-free under 25
    distinct explored interleavings."""
    for seed in range(25):
        san.reset()
        with san.explore_scope(seed):
            s = _mini_scheduler()
            s.start()
            try:
                handles = [
                    s.submit(b"p%d" % i, b"m", b"s") for i in range(6)
                ]
                assert s.wait_many(handles, timeout=10) == [True] * 6
            finally:
                s.stop()
        assert san.race_report() == "", (seed, san.race_report())
