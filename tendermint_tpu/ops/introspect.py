"""Device-tier introspection: memory accounting + continuous profiling.

PR 15 made the *fleet* observable (traces, latency attribution, flight
recorder); this module makes the *device tier* observable. Two units,
one process-wide singleton each:

``DeviceMemAccountant``
    Tracks device-resident bytes by **owner** — ``resident_tables``
    (the installed (8,4,32,K) tensor, exact nbytes, hooked from
    ops/resident.py install/drop), ``resident_tables/<tenant>``
    (pro-rata share from the store's pin table), ``shm_slabs`` (live
    slab-ring segment bytes, hooked from verifyd/shm.py register/
    unregister on both ends), and ``exec_cache`` (compiled-executable
    cache entries, counted not sized — XLA does not expose executable
    HBM footprints, so the entry count + compile counter is the honest
    signal). Mirrored into ``tendermint_ops_device_bytes{owner}`` /
    ``tendermint_ops_compile_events_total{engine}`` when metrics are
    bound, and snapshotted by :func:`memstats` for ``/debug/memstats``,
    ``verifyd stats``, and every flight-recorder dump.

``KernelProfiler``
    A continuous low-overhead profiler fed from the tracer's third
    sink slot (:func:`tendermint_tpu.libs.tracing.Tracer.
    set_profile_sink`): per-(engine, batch-bucket) rolling windows of
    kernel wall time (``dispatch_chunk`` spans) and compile time
    (``kernel_compile`` spans), exported as p50/p95/p99 digests in the
    ``profile`` fragment bench/child.py attaches to every section.
    Buckets are power-of-two lane counts only, capped with an
    ``other`` overflow (:func:`bucket_label`), so the metric-label
    cardinality is bounded by construction — tpulint TPM004 audits
    that every ``bucket=`` label site routes through that helper.

Env knob::

    TENDERMINT_TPU_PROFILE   on (default) | off

Everything here fails safe: accounting hooks never raise into the op
that triggered them, and with the profiler off the tracer sink slot
stays None so the hot path pays nothing.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from tendermint_tpu.libs.sanitizer import instrument_attrs

# Power-of-two lane buckets only: 1, 2, 4, ... up to this cap; larger
# batches collapse into "other". 2^14 covers the largest bench lane
# counts (BENCH_MULTICHIP_LANES=8192) with headroom, for at most
# 15 + 1 label values per engine.
_BUCKET_CAP = 1 << 14
_WINDOW = 512  # rolling samples kept per (engine, bucket) series


def bucket_label(lanes: Any) -> str:
    """The ONE bounded batch-bucket labeler: rounds a lane count up to
    the next power of two, capped at ``other``. Every ``bucket=`` metric
    label and profiler series key must come from here (tpulint TPM004
    enforces the metric-label half), so per-bucket cardinality can
    never exceed 16 values per engine."""
    try:
        n = int(lanes)
    except (TypeError, ValueError):
        return "other"
    if n <= 0:
        return "other"
    b = 1
    while b < n:
        b <<= 1
    if b > _BUCKET_CAP:
        return "other"
    return str(b)


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class _Series:
    """One rolling timing window. Not thread-safe on its own; the
    profiler's lock guards every touch."""

    __slots__ = ("samples", "count", "total_s")

    def __init__(self) -> None:
        self.samples: deque = deque(maxlen=_WINDOW)
        self.count = 0
        self.total_s = 0.0

    def add(self, seconds: float) -> None:
        self.samples.append(seconds)
        self.count += 1
        self.total_s += seconds

    def digest(self) -> Dict[str, float]:
        vals = sorted(self.samples)
        return {
            "count": self.count,
            "total_ms": round(self.total_s * 1e3, 3),
            "p50_ms": round(_percentile(vals, 0.50) * 1e3, 4),
            "p95_ms": round(_percentile(vals, 0.95) * 1e3, 4),
            "p99_ms": round(_percentile(vals, 0.99) * 1e3, 4),
        }


@instrument_attrs
class KernelProfiler:
    """Rolling per-(engine, bucket) kernel wall + compile digests.

    Installed as the tracer's profile sink (a third slot beside the
    metrics observer and the flight sink); the sink call is the whole
    hot-path cost: one dict lookup + deque append under a lock, only
    for ``dispatch_chunk`` / ``kernel_compile`` spans. The bench
    harness keeps it on by default and proves the overhead ≤5% in CI.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._kernel: Dict[Tuple[str, str], _Series] = {}  # guarded-by: _lock
        self._compile: Dict[Tuple[str, str], _Series] = {}  # guarded-by: _lock
        self._enabled = _env_on()  # guarded-by: none(racy bool read)
        self._metrics = None  # guarded-by: none(racy hot-path read)

    # --- wiring --------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, mode: Optional[str]) -> None:
        """``on``/``off`` (anything else falls back to the env knob)."""
        if mode == "on":
            self._enabled = True
        elif mode == "off":
            self._enabled = False
        else:
            self._enabled = _env_on()
        _sync_tracer_sink()

    def bind_metrics(self, metrics) -> None:
        self._metrics = metrics

    # --- the tracer sink ------------------------------------------------------

    def sink(self, name: str, args: Dict[str, Any], seconds: float) -> None:
        """(name, args, seconds) for every completed span — same shape
        as the metrics observer. Anything that is not a dispatch or
        compile span returns in two compares."""
        if name not in ("dispatch_chunk", "kernel_compile"):
            return
        engine = str(args.get("engine", "unknown"))
        bucket = bucket_label(args.get("lanes"))
        key = (engine, bucket)
        with self._lock:
            table = (
                self._kernel if name == "dispatch_chunk" else self._compile
            )
            series = table.get(key)
            if series is None:
                series = table[key] = _Series()
            series.add(seconds)
        metrics = self._metrics
        if metrics is not None and name == "dispatch_chunk":
            try:
                metrics.kernel_bucket_seconds.labels(
                    engine=engine, bucket=bucket
                ).observe(seconds)
            except Exception:
                pass  # a broken metrics binding must not fail the dispatch

    # --- export --------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``profile`` fragment: per-series digests keyed
        ``<engine>/b<bucket>``."""
        with self._lock:
            kernel = {k: s.digest() for k, s in self._kernel.items()}
            comp = {k: s.digest() for k, s in self._compile.items()}
        return {
            "enabled": self._enabled,
            "kernel": {
                "%s/b%s" % key: d for key, d in sorted(kernel.items())
            },
            "compile": {
                "%s/b%s" % key: d for key, d in sorted(comp.items())
            },
        }

    def clear(self) -> None:
        with self._lock:
            self._kernel.clear()
            self._compile.clear()


@instrument_attrs
class DeviceMemAccountant:
    """Process-wide device-resident byte ledger, by owner string.

    Owners are *set*, not incremented, by the subsystems that know the
    exact size (resident table install, shm segment register), so the
    ledger can never drift from the real allocation the way a +=/-=
    pair interleaved with an exception could. Compile events and
    exec-cache entries ride along because they are the same question
    ("what is sitting on the device and why") asked of XLA.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._bytes: Dict[str, int] = {}  # guarded-by: _lock
        self._compiles: Dict[str, int] = {}  # guarded-by: _lock
        self._exec_entries: Dict[str, int] = {}  # guarded-by: _lock
        self._metrics = None  # guarded-by: none(racy hot-path read)

    def bind_metrics(self, metrics) -> None:
        """Last binder wins (device_policy.bind_metrics convention);
        re-mirrors the current ledger so a late binding starts true."""
        self._metrics = metrics
        with self._lock:
            snap = dict(self._bytes)
            compiles = dict(self._compiles)
        for owner, n in snap.items():
            self._mirror(owner, n)
        if metrics is not None:
            for engine, c in compiles.items():
                try:
                    metrics.compile_events.labels(engine=engine).inc(0)
                except Exception:
                    pass  # pre-binding counts are cosmetic; never fail bind

    def _mirror(self, owner: str, nbytes: int) -> None:
        metrics = self._metrics
        if metrics is None:
            return
        try:
            metrics.device_bytes.labels(owner=owner).set(nbytes)
        except Exception:
            pass  # accounting must never fail the op that allocated

    # --- byte ledger ----------------------------------------------------------

    def set_bytes(self, owner: str, nbytes: int) -> None:
        """Absolute-set the owner's ledger entry (0 removes it from the
        snapshot but keeps the gauge at 0 so scrapes see the release)."""
        nbytes = max(0, int(nbytes))
        with self._lock:
            if nbytes == 0:
                self._bytes.pop(owner, None)
            else:
                self._bytes[owner] = nbytes
        self._mirror(owner, nbytes)

    def add_bytes(self, owner: str, delta: int) -> None:
        """Delta accounting for owners with many live allocations
        (shm slab segments attach/retire independently)."""
        with self._lock:
            n = max(0, self._bytes.get(owner, 0) + int(delta))
            if n == 0:
                self._bytes.pop(owner, None)
            else:
                self._bytes[owner] = n
        self._mirror(owner, n)

    def bytes_for(self, owner: str) -> int:
        with self._lock:
            return self._bytes.get(owner, 0)

    def set_tenant_bytes(self, total: int, pins: Dict[str, int]) -> None:
        """Pro-rata ``resident_tables/<tenant>`` owners from the pin
        table: pinned columns are the tenant's declared stake in the
        shared tensor. Tenants that lost all pins are zeroed."""
        total = max(0, int(total))
        pinned = sum(pins.values())
        with self._lock:
            stale = [
                o
                for o in self._bytes
                if o.startswith("resident_tables/")
                and o.split("/", 1)[1] not in pins
            ]
        for owner in stale:
            self.set_bytes(owner, 0)
        for tenant, count in pins.items():
            share = total * count // pinned if pinned else 0
            self.set_bytes("resident_tables/%s" % tenant, share)

    # --- compile ledger -------------------------------------------------------

    def note_compile(self, engine: str, entries: Optional[int] = None) -> None:
        """One XLA (re)compile on ``engine``; ``entries`` is the
        caller's current compiled-executable cache size when known."""
        engine = str(engine)
        with self._lock:
            self._compiles[engine] = self._compiles.get(engine, 0) + 1
            if entries is not None:
                self._exec_entries[engine] = int(entries)
        metrics = self._metrics
        if metrics is not None:
            try:
                metrics.compile_events.labels(engine=engine).inc()
            except Exception:
                pass  # accounting must never fail the compiling op

    def set_exec_entries(self, engine: str, entries: int) -> None:
        with self._lock:
            self._exec_entries[str(engine)] = int(entries)

    # --- export --------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "device_bytes": dict(sorted(self._bytes.items())),
                "device_bytes_total": sum(self._bytes.values()),
                "compile_events": dict(sorted(self._compiles.items())),
                "exec_cache_entries": dict(sorted(self._exec_entries.items())),
            }

    def clear(self) -> None:
        """Test hook: forget everything (gauges are left behind — the
        registry is per-test anyway)."""
        with self._lock:
            self._bytes.clear()
            self._compiles.clear()
            self._exec_entries.clear()


def _env_on() -> bool:
    return os.environ.get("TENDERMINT_TPU_PROFILE", "on").lower() not in (
        "off",
        "0",
        "false",
    )


accountant = DeviceMemAccountant()
profiler = KernelProfiler()


def _sync_tracer_sink() -> None:
    """Install (or remove) the profiler as the tracer's profile sink so
    a disabled profiler costs the hot path nothing — the tracer's span
    gate returns NOP_SPAN when every sink slot is None."""
    from tendermint_tpu.libs import tracing

    tracing.tracer.set_profile_sink(
        profiler.sink if profiler.enabled else None
    )


def install() -> None:
    """Wire the profiler into the process tracer. Idempotent; called
    from node assembly, verifyd serve, and bench children."""
    _sync_tracer_sink()


def bind_metrics(metrics) -> None:
    accountant.bind_metrics(metrics)
    profiler.bind_metrics(metrics)


def set_bytes(owner: str, nbytes: int) -> None:
    accountant.set_bytes(owner, nbytes)


def add_bytes(owner: str, delta: int) -> None:
    accountant.add_bytes(owner, delta)


def note_compile(engine: str, entries: Optional[int] = None) -> None:
    accountant.note_compile(engine, entries)


def traced_first_call(fn: Callable, engine: str, kernel: str, lanes: int):
    """Wrap a freshly jitted callable so its FIRST invocation — the one
    that traces and compiles — runs under a ``kernel_compile`` span
    (feeding the profiler's compile digests) and lands one
    ``note_compile`` tick. Steady-state calls pay one bool check.
    Same pattern as pallas_verify._trace_first_call; this is the XLA-
    graph engines' version."""
    state = {"first": True}

    def wrapper(*args, **kwargs):
        if state["first"]:
            state["first"] = False
            from tendermint_tpu.libs import tracing

            note_compile(engine)
            with tracing.tracer.span(
                "kernel_compile", engine=engine, kernel=kernel, lanes=lanes
            ):
                return fn(*args, **kwargs)
        return fn(*args, **kwargs)

    return wrapper


def _exec_cache_entries() -> Dict[str, int]:
    """Compiled-executable cache entries per engine, read from the
    factories' lru_cache stats — only for engine modules that are
    already imported (reading must never be the thing that pulls jax
    into a process that wasn't using it)."""
    import sys

    out: Dict[str, int] = {}
    ed = sys.modules.get("tendermint_tpu.ops.ed25519_batch")
    if ed is not None:
        try:
            out["ed25519"] = (
                ed._compiled_kernel.cache_info().currsize
                + ed._compiled_kernel_tables.cache_info().currsize
                + ed._compiled_kernel_resident.cache_info().currsize
            )
        except Exception:
            pass  # cache introspection is best-effort; report what we can
    sr = sys.modules.get("tendermint_tpu.ops.sr25519_batch")
    if sr is not None:
        try:
            out["sr25519"] = sr._compiled_kernel_sr.cache_info().currsize
        except Exception:
            pass  # cache introspection is best-effort; report what we can
    pl = sys.modules.get("tendermint_tpu.ops.pallas_verify")
    if pl is not None:
        try:
            out["pallas"] = (
                pl.compiled_verify.cache_info().currsize
                + pl.compiled_verify_tables.cache_info().currsize
            )
        except Exception:
            pass  # cache introspection is best-effort; report what we can
    return out


# --- federation fleet roll-up -------------------------------------------------
# A federated process (verifyd serve --shards, or a node routing to a
# fleet) installs a provider returning per-shard ledger rows; memstats
# then carries a "fleet" section — per-shard device bytes under the
# SAME owner labels as the local ledger, plus the owner-wise aggregate,
# so partitioned vs replicated table placement is visible at a glance
# (each shard's resident_tables entry disjoint => sum grows linearly).

_fleet_mtx = threading.Lock()
_fleet_provider: Optional[Callable[[], Dict[str, Dict[str, Any]]]] = None
_shard_id = -1


def set_shard_identity(shard_id: int) -> None:
    """Stamp this process's federation shard id into memstats (-1 =
    standalone, omitted from the snapshot)."""
    global _shard_id
    with _fleet_mtx:
        _shard_id = int(shard_id)


def shard_identity() -> int:
    with _fleet_mtx:
        return _shard_id


def set_fleet_provider(
    fn: Optional[Callable[[], Dict[str, Dict[str, Any]]]]
) -> None:
    """Install (or clear, with None) the fleet roll-up source: a
    callable returning ``{shard_label: {"device_bytes": {owner: n},
    ...}}`` rows. Must be cheap or internally rate-limited — memstats
    is polled by /debug/memstats and the flight recorder."""
    global _fleet_provider
    with _fleet_mtx:
        _fleet_provider = fn


def fleet_rollup() -> Optional[Dict[str, Any]]:
    """The fleet section, or None when unfederated/unavailable: the
    provider's per-shard rows plus the owner-wise byte aggregate."""
    with _fleet_mtx:
        provider = _fleet_provider
    if provider is None:
        return None
    try:
        rows = provider() or {}
    except Exception:
        return None
    if not isinstance(rows, dict) or not rows:
        return None
    agg: Dict[str, int] = {}
    for row in rows.values():
        if not isinstance(row, dict):
            continue
        owners = row.get("device_bytes")
        if not isinstance(owners, dict):
            continue
        for owner, n in owners.items():
            try:
                agg[owner] = agg.get(owner, 0) + int(n)
            except (TypeError, ValueError):
                continue
    return {
        "shards": rows,
        "aggregate_bytes": dict(sorted(agg.items())),
        "aggregate_total": sum(agg.values()),
    }


def memstats() -> Dict[str, Any]:
    """The full device-tier snapshot: the accountant's ledger, the
    resident store's own counters (so byte claims are cross-checkable
    against uploads), and the profiler digests. This is the payload of
    ``GET /debug/memstats``, the ``verifyd stats`` memstats field, and
    the flight-recorder ``memstats`` section. Federated processes grow
    a ``fleet`` section (per-shard rows + owner-wise aggregate) and a
    ``shard_id`` stamp."""
    out = accountant.snapshot()
    sid = shard_identity()
    if sid >= 0:
        out["shard_id"] = sid
    fleet = fleet_rollup()
    if fleet is not None:
        out["fleet"] = fleet
    live = _exec_cache_entries()
    if live:
        merged = dict(out.get("exec_cache_entries", {}))
        merged.update(live)
        out["exec_cache_entries"] = dict(sorted(merged.items()))
    try:
        from tendermint_tpu.ops import resident

        out["resident"] = resident.stats()
    except Exception:
        out["resident"] = {}
    out["profile"] = profiler.snapshot()
    return out


def memstats_json(limit_bytes: Optional[int] = None) -> str:
    """Serialized memstats, optionally size-bounded: when the compact
    JSON exceeds ``limit_bytes`` the profiler digests are dropped
    first, then the snapshot collapses to totals — callers with a hard
    budget (the flight recorder's atomic dump) always get *something*
    that fits."""
    doc = memstats()
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    if limit_bytes is None or len(blob) <= limit_bytes:
        return blob
    doc.pop("profile", None)
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    if len(blob) <= limit_bytes:
        return blob
    slim = {
        "device_bytes_total": doc.get("device_bytes_total", 0),
        "truncated": True,
    }
    return json.dumps(slim, sort_keys=True, separators=(",", ":"))
