"""verifyd client: pooled, retrying, deadline-propagating.

``VerifydClient.verify`` is the wire analog of
``ops.verify_batch(pks, msgs, sigs) -> List[bool]``, so it drops into
every seam that takes a verify_fn: the shared ``VerifyScheduler``
(crypto/batch.get_shared_scheduler), ``Ed25519BatchVerifier`` (and
through it ``types/validation.verify_commit``), and ``light/verifier``.

Failure semantics (fail AVAILABLE, not open): connection loss retries
with exponential backoff across a small channel pool. An admission
rejection (RESOURCE_EXHAUSTED) is a *shed*, not a death sentence: the
client retries it with deadline-jittered exponential backoff against
the REMAINING deadline, up to a bounded ``shed_retries`` budget —
sheds are transient by design (the brownout ladder recovers), so a
beat of patience usually beats burning host CPU. Only once the budget
(or the deadline) is exhausted — or the server is unreachable / the
deadline expired server-side — does the call degrade to the local
host oracle (``verify_zip215`` / sr25519 host verify) when
``fallback`` is enabled; verdicts stay sound because the host oracle
is the same ZIP-215 ground truth the device kernels are tested
against. With ``fallback=False`` the caller sees
``VerifydRejectedError`` / ``VerifydUnavailableError`` instead.

Selection: ``TENDERMINT_TPU_VERIFY_REMOTE=<host:port>`` env or the
``[ops] verify_remote`` config key (plumbed via node assembly into
``set_remote_addr``). ``remote_backend()`` returns the process-wide
client's verify_fn, or None when no remote is configured. The tenant
namespace rides every request: ``set_remote_tenant`` (config
``[ops] verify_tenant``) labels this node's traffic server-side.

Workload classes ride a thread-local set by ``classify(klass)`` at the
call sites that know the work's nature (consensus commit verification,
blocksync, light-client header checks) — outermost wins, so the light
package's "light" labeling is not overridden by validation internals.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, List, Optional, Sequence

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.grpc import GrpcChannel, GrpcError, H2ProtocolError
from tendermint_tpu.libs.metrics import VerifydMetrics
from tendermint_tpu.verifyd import protocol
from tendermint_tpu.verifyd import shm as shm_transport
from tendermint_tpu.verifyd.protocol import (
    ALGO_ED25519,
    ALGO_SR25519,
    CLASS_BLOCKSYNC,
    CLASS_CONSENSUS,
    CLASS_LIGHT,
    CLASS_RPC,
    DEFAULT_TENANT,
    KIND_COMMIT,
    KIND_HEADER,
    KIND_RAW,
    STATUS_NAMES,
    STATUS_OK,
    STATS_PATH,
    VERIFY_PATH,
    VerifyRequest,
)

REMOTE_ENV = "TENDERMINT_TPU_VERIFY_REMOTE"

# which request kind a class implies when the caller sets none
_CLASS_KIND = {
    CLASS_CONSENSUS: KIND_COMMIT,
    CLASS_BLOCKSYNC: KIND_COMMIT,
    CLASS_LIGHT: KIND_HEADER,
    CLASS_RPC: KIND_RAW,
}


class VerifydUnavailableError(ConnectionError):
    """Server unreachable after retries (and fallback disabled)."""


class VerifydRejectedError(RuntimeError):
    """Server answered non-OK (admission shed, expired deadline, ...)."""

    def __init__(self, status: int, message: str = ""):
        self.status = status
        super().__init__(
            f"verifyd {STATUS_NAMES.get(status, status)}: {message}"
        )


# --- workload classification (thread-local, outermost wins) ----------------

_tls = threading.local()


@contextmanager
def classify(klass: int):
    """Tag verification work on this thread with a priority class. The
    OUTERMOST classification wins: light/verifier's "light" stays in
    force through the validation internals it calls."""
    if getattr(_tls, "klass", None) is not None:
        yield
        return
    _tls.klass = klass
    try:
        yield
    finally:
        _tls.klass = None


def current_class() -> Optional[int]:
    return getattr(_tls, "klass", None)


# --- the client -------------------------------------------------------------


def _host_verify(algo: int, pks, msgs, sigs) -> List[bool]:
    if algo == ALGO_SR25519:
        from tendermint_tpu.crypto.sr25519 import verify as sr_verify

        return [sr_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    from tendermint_tpu.crypto.ed25519_ref import verify_zip215

    return [verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]


class VerifydClient:
    """Pooled blocking client for one verifyd server.

    A small pool of HTTP/2 channels (each carries one call at a time)
    lets concurrent caller threads overlap their wire round-trips —
    which is exactly what gives the server cross-client batches.
    """

    def __init__(
        self,
        addr: str,
        pool_size: int = 4,
        timeout: float = 10.0,
        retries: int = 3,
        backoff: float = 0.05,
        fallback: bool = True,
        tenant: str = DEFAULT_TENANT,
        shed_retries: int = 2,
        shed_backoff: float = 0.02,
        shm: Optional[str] = None,
        metrics: Optional[VerifydMetrics] = None,
        slo_ms: int = 0,
        shard_id: int = -1,
    ):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"verifyd address must be host:port, got {addr!r}")
        self.addr = addr
        self._host = host
        self._port = int(port)
        self.timeout = timeout
        self.retries = retries
        self.backoff = backoff
        self.fallback = fallback
        self.tenant = tenant or DEFAULT_TENANT
        # declared p99 target for this tenant's traffic (protocol field
        # 8, zero = none): the server holds the tenant's attributed
        # latency budget to it (tightest declaration wins server-side)
        self.slo_ms = max(0, int(slo_ms))
        # federation routing identity: the shard this client believes
        # it is talking to (-1 = unfederated: fields 9/10 stay off the
        # wire) and the routing epoch of the shard map that picked it.
        # The FederationClient bumps route_epoch on membership changes
        # so the server can count stale-map misroutes honestly.
        self.shard_id = int(shard_id)
        self.route_epoch = 0
        # RESOURCE_EXHAUSTED retry budget: sheds are transient (the
        # server's brownout ladder recovers), so wait-and-retry against
        # the remaining deadline before surrendering to the fallback
        self.shed_retries = max(0, shed_retries)
        self.shed_backoff = shed_backoff
        self._mtx = threading.Lock()
        self._pool: List[GrpcChannel] = []
        self._free: List[GrpcChannel] = []
        self._pool_size = max(1, pool_size)
        self._available = threading.Condition(self._mtx)
        # observability
        self.metrics = metrics or VerifydMetrics.nop()
        self.calls = 0
        self.transport_retries = 0
        self.fallback_calls = 0
        self.shed_retries_used = 0
        self.rejected = {}  # status -> count
        # end-to-end latency attribution: cumulative per-stage seconds
        # from server stage vectors (metrics-free view for bench/tests)
        self.stage_totals: dict = {}
        self.stage_calls = 0
        # zero-copy ingress: negotiated lazily when the server shares
        # this host and advertises an endpoint (TENDERMINT_TPU_SHM /
        # [ops] verify_shm / the shm param; off restores pure TCP)
        if shm is not None and shm not in ("auto", "on", "off"):
            raise ValueError(f"bad shm mode {shm!r}")
        self._shm_param = shm
        self._shm_local = shm_transport.is_local(host)
        self._shm_mtx = threading.Lock()
        self._shm: Optional[shm_transport.ShmClientTransport] = None  # guarded-by: _shm_mtx
        self._shm_next_retry = 0.0  # guarded-by: _shm_mtx
        self.shm_calls = 0  # guarded-by: _shm_mtx
        self.shm_fallbacks = 0  # guarded-by: _shm_mtx
        self.shm_lanes = 0  # guarded-by: _shm_mtx
        self.shm_bytes_avoided = 0  # guarded-by: _shm_mtx

    def shm_mode(self) -> str:
        """Effective transport mode: constructor param beats the
        process-wide config/env resolution (re-read per call so
        ``set_shm_mode`` applies to cached clients too)."""
        return self._shm_param or shm_transport.shm_mode()

    @property
    def transport(self) -> str:
        """The negotiated transport right now: ``shm`` once a slab-ring
        session is live, else ``tcp``."""
        with self._shm_mtx:
            t = self._shm
        return "shm" if (t is not None and t.alive) else "tcp"

    def stats(self) -> dict:
        """Counter snapshot (CLI banner, bench, tests)."""
        with self._shm_mtx:
            shm_stats = {
                "shm_calls": self.shm_calls,
                "shm_fallbacks": self.shm_fallbacks,
                "shm_lanes": self.shm_lanes,
                "shm_bytes_avoided": self.shm_bytes_avoided,
            }
        return {
            "transport": self.transport,
            "calls": self.calls,
            "transport_retries": self.transport_retries,
            "fallback_calls": self.fallback_calls,
            "shed_retries_used": self.shed_retries_used,
            "stage_totals": dict(self.stage_totals),
            "stage_calls": self.stage_calls,
            **shm_stats,
        }

    def _acquire(self) -> GrpcChannel:
        with self._available:
            while True:
                if self._free:
                    return self._free.pop()
                if len(self._pool) < self._pool_size:
                    ch = GrpcChannel(
                        self._host, self._port, timeout=self.timeout
                    )
                    self._pool.append(ch)
                    return ch
                self._available.wait(timeout=self.timeout)

    def _release(self, ch: GrpcChannel, broken: bool = False) -> None:
        with self._available:
            if broken:
                self._pool.remove(ch)
                try:
                    ch.close()
                except OSError:
                    pass  # already-dead channel; discard is the point
            else:
                self._free.append(ch)
            self._available.notify()

    def close(self) -> None:
        with self._shm_mtx:
            shm, self._shm = self._shm, None
        if shm is not None:
            shm.close()
        with self._available:
            for ch in self._pool:
                try:
                    ch.close()
                except OSError:
                    pass  # best-effort teardown of a possibly-dead channel
            self._pool.clear()
            self._free.clear()
            self._available.notify_all()

    # --- shm negotiation -----------------------------------------------------

    def _maybe_shm(self) -> Optional[shm_transport.ShmClientTransport]:
        mode = self.shm_mode()
        if mode == "off" or not self._shm_local:
            return None
        with self._shm_mtx:
            t = self._shm
            if t is not None:
                if t.alive:
                    return t
                self._shm = None  # dead session (server restart): renegotiate
            now = time.monotonic()
            if now < self._shm_next_retry:
                return None
            self._shm_next_retry = now + 1.0
            ep = shm_transport.read_endpoint(self._port)
            if ep is None:
                # no advert = the server runs TCP-only (or isn't up):
                # that's negotiation working, not a fallback — unless
                # the caller demanded shm outright
                if mode == "on":
                    self.shm_fallbacks += 1
                return None
            try:
                new = shm_transport.ShmClientTransport(
                    ep["socket"], ep["token"]
                )
            except shm_transport.ShmError:
                self.shm_fallbacks += 1
                return None
            self._shm = new
            return new

    def _call_transport(
        self, req: VerifyRequest, timeout: float
    ) -> protocol.VerifyResponse:
        """One unary exchange over the best negotiated transport: slab
        ring when live, TCP otherwise. ShmBusy (ring full) pushes just
        this request onto TCP — that is the backpressure path admission
        control meters; any other shm failure drops the session and
        renegotiates later."""
        t = self._maybe_shm()
        if t is not None:
            try:
                resp = t.call(req, timeout=timeout)
            except shm_transport.ShmBusy:
                with self._shm_mtx:
                    self.shm_fallbacks += 1
            except shm_transport.ShmError:
                with self._shm_mtx:
                    self.shm_fallbacks += 1
                    if self._shm is t:
                        self._shm = None
                t.close()
            else:
                with self._shm_mtx:
                    self.shm_calls += 1
                    self.shm_lanes += len(req)
                    self.shm_bytes_avoided += protocol.encoded_request_size(
                        req
                    )
                self.calls += 1
                return resp
        if len(req) <= protocol.MAX_LANES:
            return self.call(req, timeout=timeout)
        # the TCP codec caps a request at MAX_LANES; shm super-batches
        # that fell back split here and merge their verdicts
        verdicts: List[bool] = []
        depth = 0
        stage_acc: dict = {}
        for start in range(0, len(req), protocol.MAX_LANES):
            end = start + protocol.MAX_LANES
            sub = VerifyRequest(
                kind=req.kind,
                klass=req.klass,
                deadline_ms=req.deadline_ms,
                algo=req.algo,
                pks=list(req.pks[start:end]),
                msgs=list(req.msgs[start:end]),
                sigs=list(req.sigs[start:end]),
                tenant=req.tenant,
                trace=req.trace,  # every split rides the same trace
                slo_ms=req.slo_ms,
                shard_id=req.shard_id,
                route_epoch=req.route_epoch,
            )
            resp = self.call(sub, timeout=timeout)
            if resp.status != STATUS_OK:
                return resp
            verdicts.extend(resp.verdicts)
            depth = max(depth, resp.queue_depth)
            for stage, v in protocol.unpack_stages(resp.stages).items():
                stage_acc[stage] = stage_acc.get(stage, 0.0) + v
        return protocol.VerifyResponse(
            status=STATUS_OK, verdicts=verdicts, queue_depth=depth,
            stages=protocol.pack_stages(stage_acc) if stage_acc else b"",
        )

    # --- calls --------------------------------------------------------------

    def call(
        self, req: VerifyRequest, timeout: Optional[float] = None
    ) -> protocol.VerifyResponse:
        """Send one request, retrying with exponential backoff on
        transport failure; raises VerifydUnavailableError when every
        attempt failed. Server-side non-OK statuses return normally —
        the caller decides whether to fall back or surface them."""
        payload = protocol.encode_request(req)
        timeout = self.timeout if timeout is None else timeout
        delay = self.backoff
        last_exc: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            ch = self._acquire()
            try:
                raw = ch.unary(VERIFY_PATH, payload, timeout=timeout)
            except GrpcError as exc:
                # the server answered (wrong path, handler crash): not a
                # transport problem, retrying the same call won't help
                self._release(ch)
                raise VerifydUnavailableError(
                    f"verifyd {self.addr} errored: {exc}"
                ) from exc
            except (OSError, H2ProtocolError) as exc:
                self._release(ch, broken=True)
                last_exc = exc
                if attempt < self.retries:
                    self.transport_retries += 1
                    time.sleep(delay)
                    delay *= 2
                    continue
                raise VerifydUnavailableError(
                    f"verifyd {self.addr} unreachable: {exc}"
                ) from exc
            else:
                self._release(ch)
                self.calls += 1
                return protocol.decode_response(raw)
        raise VerifydUnavailableError(
            f"verifyd {self.addr} unreachable: {last_exc}"
        )

    def server_stats(self, timeout: float = 2.0) -> dict:
        """One STATS_PATH round-trip: the server's gossip snapshot
        (wire counters, tenant SLO view, brownout level, pinned
        resident-table slice). Raises ``VerifydUnavailableError`` when
        the server is unreachable or answers garbage — the federation's
        health refresh treats that as a dead-shard signal."""
        ch = self._acquire()
        try:
            raw = ch.unary(STATS_PATH, b"", timeout=timeout)
        except GrpcError as exc:
            self._release(ch)
            raise VerifydUnavailableError(
                f"verifyd {self.addr} stats errored: {exc}"
            ) from exc
        except (OSError, H2ProtocolError) as exc:
            self._release(ch, broken=True)
            raise VerifydUnavailableError(
                f"verifyd {self.addr} stats unreachable: {exc}"
            ) from exc
        self._release(ch)
        try:
            snap = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise VerifydUnavailableError(
                f"verifyd {self.addr} stats malformed: {exc}"
            ) from exc
        if not isinstance(snap, dict):
            raise VerifydUnavailableError(
                f"verifyd {self.addr} stats malformed: not an object"
            )
        return snap

    def verify(
        self,
        pks: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
        *,
        algo: int = ALGO_ED25519,
        klass: Optional[int] = None,
        kind: Optional[int] = None,
        deadline: Optional[float] = None,
    ) -> List[bool]:
        """Remote batch verify with local host fallback. The class
        defaults to the thread's ``classify`` context (else rpc); the
        deadline defaults to the client timeout and propagates on the
        wire so the server can shed or flush-early accordingly."""
        if not pks:
            return []
        if klass is None:
            klass = current_class()
            if klass is None:
                klass = CLASS_RPC
        if kind is None:
            kind = _CLASS_KIND.get(klass, KIND_RAW)
        if deadline is None:
            deadline = self.timeout
        t0 = time.monotonic()
        with tracing.span(
            "verifyd_call", lanes=len(pks), klass=klass, algo=algo
        ) as sp:
            # propagate this span's context on the wire (protocol field
            # 7) so the server's enqueue/dispatch/chunk spans link under
            # it in the merged fleet timeline; empty when tracing is off
            ctx = tracing.current_context()
            trace_bytes = ctx.to_bytes() if ctx is not None else b""
            delay = self.shed_backoff
            sheds = 0
            while True:
                # the remaining deadline shrinks across shed retries so
                # the retried request carries an honest wire deadline
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    resp = protocol.VerifyResponse(
                        status=protocol.STATUS_DEADLINE_EXCEEDED,
                        message="deadline spent across shed retries",
                    )
                    break
                req = VerifyRequest(
                    kind=kind,
                    klass=klass,
                    deadline_ms=max(1, int(remaining * 1000)),
                    algo=algo,
                    pks=list(pks),
                    msgs=list(msgs),
                    sigs=list(sigs),
                    tenant=self.tenant,
                    trace=trace_bytes,
                    slo_ms=self.slo_ms,
                    shard_id=self.shard_id,
                    route_epoch=self.route_epoch,
                )
                try:
                    # transport grace past the verify deadline: the
                    # server answers DEADLINE_EXCEEDED at exactly
                    # `deadline`; the wire timeout must not race that
                    resp = self._call_transport(req, timeout=remaining + 0.5)
                except VerifydUnavailableError:
                    if not self.fallback:
                        raise
                    sp.set(outcome="fallback_unavailable", sheds=sheds)
                    self.fallback_calls += 1
                    return _host_verify(algo, pks, msgs, sigs)
                if (
                    resp.status == protocol.STATUS_RESOURCE_EXHAUSTED
                    and sheds < self.shed_retries
                ):
                    # shed: back off (jittered exponential, bounded by
                    # the remaining deadline) and try again — the
                    # brownout that shed us is designed to recover
                    sheds += 1
                    self.shed_retries_used += 1
                    remaining = deadline - (time.monotonic() - t0)
                    pause = min(
                        delay * (0.5 + random.random() * 0.5),
                        max(0.0, remaining),
                    )
                    delay *= 2
                    if pause > 0:
                        time.sleep(pause)
                    continue
                break
            if resp.status != STATUS_OK or len(resp.verdicts) != len(pks):
                self.rejected[resp.status] = (
                    self.rejected.get(resp.status, 0) + 1
                )
                if not self.fallback:
                    raise VerifydRejectedError(resp.status, resp.message)
                sp.set(
                    outcome=STATUS_NAMES.get(resp.status, "bad"),
                    sheds=sheds,
                )
                self.fallback_calls += 1
                return _host_verify(algo, pks, msgs, sigs)
            sp.set(outcome="ok", sheds=sheds)
            self._note_stages(resp, ctx, time.monotonic() - t0)
            return list(resp.verdicts)

    def _note_stages(
        self,
        resp: protocol.VerifyResponse,
        ctx: Optional[tracing.TraceContext],
        wall_s: float,
    ) -> None:
        """End-to-end latency attribution: fold the server's stage-time
        vector into the ``e2e_stage_seconds{stage}`` histograms, with
        the request's trace id attached as an OpenMetrics exemplar so a
        latency outlier links straight into the merged fleet timeline.
        The unattributed remainder (client wall minus stage sum) is the
        transport overhead and rides the ``transport`` pseudo-stage."""
        if not resp.stages:
            return
        stages = protocol.unpack_stages(resp.stages)
        exem = {"trace_id": ctx.trace_id} if ctx is not None else None
        attributed = 0.0
        for stage, v in stages.items():
            attributed += v
            self.metrics.e2e_stage_seconds.labels(stage=stage).observe(
                v, exemplar=exem
            )
            # tpuflow: sanitized=keys come from zip(STAGE_NAMES, ...) in
            # unpack_stages — a host constant list, so cardinality is
            # bounded even though the stage VALUES are wire data
            self.stage_totals[stage] = self.stage_totals.get(stage, 0.0) + v
        overhead = max(0.0, wall_s - attributed)
        self.metrics.e2e_stage_seconds.labels(stage="transport").observe(
            overhead, exemplar=exem
        )
        self.stage_totals["transport"] = (
            self.stage_totals.get("transport", 0.0) + overhead
        )
        self.stage_calls += 1

    @property
    def verify_fn(self) -> Callable[..., List[bool]]:
        """(pks, msgs, sigs) -> List[bool]; plugs into VerifyScheduler,
        Ed25519BatchVerifier, and any other verify_fn seam."""
        return self.verify


# --- process-wide remote backend -------------------------------------------

_remote_mtx = threading.Lock()
_remote_addr: str = ""  # config override; env consulted when empty
_remote_tenant: str = DEFAULT_TENANT  # config override ([ops] verify_tenant)
_remote_client: Optional[VerifydClient] = None
_remote_client_key: tuple = ("", DEFAULT_TENANT)


def set_remote_addr(addr: str) -> None:
    """Config-driven override of the remote verifier address (node
    assembly calls this from ``[ops] verify_remote``). Empty string
    clears the override; the env var still applies."""
    global _remote_addr
    with _remote_mtx:
        _remote_addr = addr or ""


def set_remote_tenant(tenant: str) -> None:
    """Tenant/chain namespace this node's remote traffic rides under
    (node assembly plumbs ``[ops] verify_tenant``; empty = default)."""
    global _remote_tenant
    with _remote_mtx:
        _remote_tenant = tenant or DEFAULT_TENANT


def reset_remote() -> None:
    """Drop the overrides AND the cached client (tests)."""
    global _remote_addr, _remote_tenant, _remote_client, _remote_client_key
    with _remote_mtx:
        _remote_addr = ""
        _remote_tenant = DEFAULT_TENANT
        if _remote_client is not None:
            _remote_client.close()
        _remote_client = None
        _remote_client_key = ("", DEFAULT_TENANT)


def remote_backend() -> Optional[Callable[..., List[bool]]]:
    """The configured remote's verify_fn, or None. The client is cached
    process-wide and rebuilt when the address or tenant changes."""
    global _remote_client, _remote_client_key
    with _remote_mtx:
        addr = _remote_addr or os.environ.get(REMOTE_ENV, "")
        if not addr:
            return None
        key = (addr, _remote_tenant)
        if _remote_client is None or _remote_client_key != key:
            if _remote_client is not None:
                _remote_client.close()
            _remote_client = VerifydClient(addr, tenant=_remote_tenant)
            _remote_client_key = key
        return _remote_client.verify


def remote_transport() -> Optional[str]:
    """Negotiated transport of the process-wide remote client
    (``"shm"`` | ``"tcp"``), or None when no remote is configured.
    Probes shm negotiation eagerly so a start-up banner reports the
    transport the first verify call will actually ride."""
    if remote_backend() is None:
        return None
    with _remote_mtx:
        client = _remote_client
    if client is None:
        return None
    client._maybe_shm()
    return client.transport
