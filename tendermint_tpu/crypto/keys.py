"""Key interfaces and implementations.

Mirrors the reference crypto layer's contracts (crypto/crypto.go:38-76):
``PubKey`` (address, bytes, verify), ``PrivKey`` (sign, pubkey), and
20-byte addresses. Ed25519 addresses are SHA256(pubkey)[:20]
(crypto/crypto.go:27 AddressHash); secp256k1 uses RIPEMD160(SHA256(pub))
(crypto/secp256k1/secp256k1.go).

Ed25519 verification uses ZIP-215 semantics via the batch engine's host
oracle (crypto/ed25519/ed25519.go:24-31); signing follows RFC 8032.
"""

from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod
from typing import Optional

from tendermint_tpu.crypto import ed25519_ref

ADDRESS_LEN = 20

ED25519_KEY_TYPE = "ed25519"
SECP256K1_KEY_TYPE = "secp256k1"
SR25519_KEY_TYPE = "sr25519"

ED25519_PUBKEY_SIZE = 32
ED25519_PRIVKEY_SIZE = 64
ED25519_SIG_SIZE = 64


def address_hash(data: bytes) -> bytes:
    """crypto.AddressHash: first 20 bytes of SHA-256."""
    return hashlib.sha256(data).digest()[:ADDRESS_LEN]


class PubKey(ABC):
    @abstractmethod
    def address(self) -> bytes: ...

    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @property
    @abstractmethod
    def type(self) -> str: ...

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PubKey)
            and self.type == other.type
            and self.bytes() == other.bytes()
        )

    def __hash__(self) -> int:
        return hash((self.type, self.bytes()))

    def __repr__(self) -> str:
        return f"PubKey{{{self.type}:{self.bytes().hex()[:16]}…}}"


class PrivKey(ABC):
    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abstractmethod
    def pub_key(self) -> PubKey: ...

    @property
    @abstractmethod
    def type(self) -> str: ...


# --- Ed25519 ----------------------------------------------------------------

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _LibPriv,
    )

    _HAVE_LIB = True
except Exception:  # pragma: no cover
    _HAVE_LIB = False


class Ed25519PubKey(PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != ED25519_PUBKEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be 32 bytes, got {len(data)}")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return address_hash(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != ED25519_SIG_SIZE:
            return False
        return ed25519_ref.verify_zip215(self._bytes, msg, sig)

    @property
    def type(self) -> str:
        return ED25519_KEY_TYPE


class Ed25519PrivKey(PrivKey):
    """64-byte layout: seed || pubkey (crypto/ed25519/ed25519.go:76-82)."""

    __slots__ = ("_bytes", "_lib")

    def __init__(self, data: bytes):
        if len(data) == 32:  # bare seed
            data, _ = ed25519_ref.keypair_from_seed(data)
        if len(data) != ED25519_PRIVKEY_SIZE:
            raise ValueError(f"ed25519 privkey must be 64 bytes, got {len(data)}")
        self._bytes = bytes(data)
        self._lib = (
            _LibPriv.from_private_bytes(self._bytes[:32]) if _HAVE_LIB else None
        )

    @classmethod
    def generate(cls) -> "Ed25519PrivKey":
        priv, _ = ed25519_ref.generate_keypair()
        return cls(priv)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Ed25519PrivKey":
        priv, _ = ed25519_ref.keypair_from_seed(seed)
        return cls(priv)

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        if self._lib is not None:
            return self._lib.sign(msg)
        return ed25519_ref.sign(self._bytes, msg)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(self._bytes[32:])

    @property
    def type(self) -> str:
        return ED25519_KEY_TYPE


# --- secp256k1 --------------------------------------------------------------

try:
    from cryptography.hazmat.primitives.asymmetric import ec as _ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature as _decode_dss,
        encode_dss_signature as _encode_dss,
    )
    from cryptography.hazmat.primitives import hashes as _hashes
    from cryptography.exceptions import InvalidSignature as _InvalidSig

    _HAVE_SECP = True
except Exception:  # pragma: no cover
    _HAVE_SECP = False

SECP256K1_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def _ripemd160_sha256(data: bytes) -> bytes:
    return hashlib.new("ripemd160", hashlib.sha256(data).digest()).digest()


class Secp256k1PubKey(PubKey):
    """33-byte compressed SEC1 pubkey; 64-byte r||s signatures with low-s
    requirement (crypto/secp256k1/secp256k1.go:38-217)."""

    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != 33:
            raise ValueError(f"secp256k1 pubkey must be 33 bytes, got {len(data)}")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return _ripemd160_sha256(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if not _HAVE_SECP or len(sig) != 64:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if s > SECP256K1_N // 2:  # reject malleable high-s (reference does too)
            return False
        if r == 0 or s == 0:
            return False
        try:
            pub = _ec.EllipticCurvePublicKey.from_encoded_point(
                _ec.SECP256K1(), self._bytes
            )
            pub.verify(_encode_dss(r, s), msg, _ec.ECDSA(_hashes.SHA256()))
            return True
        except (_InvalidSig, ValueError):
            return False

    @property
    def type(self) -> str:
        return SECP256K1_KEY_TYPE


class Secp256k1PrivKey(PrivKey):
    __slots__ = ("_bytes", "_lib")

    def __init__(self, data: bytes):
        if len(data) != 32:
            raise ValueError("secp256k1 privkey must be 32 bytes")
        if not _HAVE_SECP:  # pragma: no cover
            raise RuntimeError("secp256k1 backend unavailable")
        self._bytes = bytes(data)
        self._lib = _ec.derive_private_key(
            int.from_bytes(data, "big"), _ec.SECP256K1()
        )

    @classmethod
    def generate(cls) -> "Secp256k1PrivKey":
        key = _ec.generate_private_key(_ec.SECP256K1())
        raw = key.private_numbers().private_value.to_bytes(32, "big")
        return cls(raw)

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        der = self._lib.sign(msg, _ec.ECDSA(_hashes.SHA256()))
        r, s = _decode_dss(der)
        if s > SECP256K1_N // 2:  # normalize to low-s
            s = SECP256K1_N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> Secp256k1PubKey:
        from cryptography.hazmat.primitives import serialization

        raw = self._lib.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
        )
        return Secp256k1PubKey(raw)

    @property
    def type(self) -> str:
        return SECP256K1_KEY_TYPE


# --- proto encoding of public keys (crypto/encoding/codec.go) ---------------

from tendermint_tpu.encoding.proto import Reader, encode_bytes_field, tag  # noqa: E402


def pubkey_to_proto(pub: PubKey) -> bytes:
    """tendermint.crypto.PublicKey: oneof {ed25519=1, secp256k1=2, sr25519=3}."""
    if pub.type == ED25519_KEY_TYPE:
        return encode_bytes_field(1, pub.bytes())
    if pub.type == SECP256K1_KEY_TYPE:
        return encode_bytes_field(2, pub.bytes())
    if pub.type == SR25519_KEY_TYPE:
        return encode_bytes_field(3, pub.bytes())
    raise ValueError(f"unknown key type {pub.type}")


def pubkey_from_proto(data: bytes) -> PubKey:
    r = Reader(data)
    for field, wire in r.fields():
        if field == 1 and wire == 2:
            return Ed25519PubKey(r.read_bytes())
        if field == 2 and wire == 2:
            return Secp256k1PubKey(r.read_bytes())
        if field == 3 and wire == 2:
            from tendermint_tpu.crypto.sr25519 import Sr25519PubKey

            return Sr25519PubKey(r.read_bytes())
        r.skip(wire)
    raise ValueError("empty PublicKey proto")


def pubkey_from_type_and_bytes(key_type: str, data: bytes) -> PubKey:
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PubKey(data)
    if key_type == SECP256K1_KEY_TYPE:
        return Secp256k1PubKey(data)
    if key_type == SR25519_KEY_TYPE:
        from tendermint_tpu.crypto.sr25519 import Sr25519PubKey

        return Sr25519PubKey(data)
    raise ValueError(f"unknown key type {key_type}")


def privkey_from_type_and_bytes(key_type: str, data: bytes) -> PrivKey:
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PrivKey(data)
    if key_type == SECP256K1_KEY_TYPE:
        return Secp256k1PrivKey(data)
    if key_type == SR25519_KEY_TYPE:
        from tendermint_tpu.crypto.sr25519 import Sr25519PrivKey

        return Sr25519PrivKey(data)
    raise ValueError(f"unknown key type {key_type}")
