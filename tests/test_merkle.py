"""RFC-6962 merkle tree tests against independent recursion + known answers."""

import hashlib

from tendermint_tpu.crypto import merkle


def _ref_hash(items):
    """Independent recursive RFC-6962 implementation."""
    if len(items) == 0:
        return hashlib.sha256(b"").digest()
    if len(items) == 1:
        return hashlib.sha256(b"\x00" + items[0]).digest()
    k = merkle.get_split_point(len(items))
    return hashlib.sha256(
        b"\x01" + _ref_hash(items[:k]) + _ref_hash(items[k:])
    ).digest()


def test_empty_tree():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    assert (
        merkle.hash_from_byte_slices([]).hex()
        == "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"
    )


def test_single_leaf():
    # RFC 6962 §2.1: MTH({d0}) = SHA-256(0x00 || d0)
    assert (
        merkle.hash_from_byte_slices([b""]).hex()
        == "6e340b9cffb37a989ca544e6bb780a2c78901d3fb33738768511a30617afa01d"
    )


def test_matches_reference_recursion():
    for n in [1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17, 33, 100]:
        items = [bytes([i]) * (i % 5) for i in range(n)]
        assert merkle.hash_from_byte_slices(items) == _ref_hash(items)


def test_split_point():
    # crypto/merkle/tree.go getSplitPoint: largest power of two < n
    assert merkle.get_split_point(2) == 1
    assert merkle.get_split_point(3) == 2
    assert merkle.get_split_point(4) == 2
    assert merkle.get_split_point(5) == 4
    assert merkle.get_split_point(8) == 4
    assert merkle.get_split_point(9) == 8


def test_proofs():
    for n in [1, 2, 3, 5, 8, 13]:
        items = [b"item%d" % i for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, proof in enumerate(proofs):
            assert proof.verify(root, items[i])
            assert not proof.verify(root, b"wrong")
            if n > 1:
                other = (i + 1) % n
                assert not proof.verify(root, items[other])


def test_proof_tamper_rejected():
    items = [b"a", b"b", b"c", b"d"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    p = proofs[2]
    p.aunts[0] = b"\x00" * 32
    assert not p.verify(root, items[2])
