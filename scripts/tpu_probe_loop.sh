#!/bin/bash
# Probe the axon TPU tunnel until it answers; log timestamps.
for i in $(seq 1 60); do
  if timeout 90 python -u -c "import jax; print(jax.devices())" >/tmp/tpu_probe.log 2>&1; then
    echo "$(date +%T) TPU BACK after attempt $i" >> /tmp/tpu_probe.log
    exit 0
  fi
  echo "$(date +%T) attempt $i failed" >> /tmp/tpu_probe.log
  sleep 120
done
exit 1
