"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The build machine exposes one real TPU chip through the experimental
``axon`` platform; tests instead run on CPU with 8 virtual devices so
multi-chip sharding paths (shard_map over a Mesh) are exercised without
real hardware, per the reference test strategy of substituting in-memory
fakes for the real transport (SURVEY.md section 4).

This must run before anything imports jax and initializes a backend.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(autouse=True)
def _fresh_verify_caches(monkeypatch):
    """Pin the verify caches to a known state per test.

    The result cache defaults ON in production; under pytest the suite
    reuses identical (pk, msg, sig) triples across tests, so a default-on
    cache would short-circuit device paths other tests assert on
    (fallback counters, kernel dispatch warnings). Tests that exercise
    the caches opt back in with monkeypatch (tests/test_precompute.py).
    """
    from tendermint_tpu.ops import precompute

    monkeypatch.setenv(precompute._RESULT_ENV, "0")
    precompute.reset()
    yield
