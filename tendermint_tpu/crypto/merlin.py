"""Merlin transcripts over STROBE-128 (Keccak-f[1600]).

Schnorrkel (sr25519) signatures are defined over Merlin transcripts, so a
compatible implementation needs the exact STROBE-128 duplex construction
Merlin pins down: rate 166, protocol tag "STROBEv1.0.2", and Merlin's
framing (``meta-AD(label || LE32(len))`` then ``AD``/``PRF`` of the data).

Reference behavior: crypto/sr25519/pubkey.go:49-61 builds a signing
transcript per message via curve25519-voi's sr25519, which implements the
same Merlin construction (w3f schnorrkel). This is a from-scratch host-side
implementation — transcript hashing is inherently sequential and stays on
CPU; only the curve math batches onto the device (SURVEY §7 "Hard parts").
"""

from __future__ import annotations

# --- Keccak-f[1600] permutation -------------------------------------------

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

_ROTATION = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_MASK = (1 << 64) - 1


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (64 - n))) & _MASK


def keccak_f1600(state: bytearray) -> None:
    """In-place Keccak-f[1600] over a 200-byte state (little-endian lanes)."""
    lanes = [
        int.from_bytes(state[8 * i : 8 * i + 8], "little") for i in range(25)
    ]
    # lanes[x + 5*y] layout
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [
            lanes[x] ^ lanes[x + 5] ^ lanes[x + 10] ^ lanes[x + 15] ^ lanes[x + 20]
            for x in range(5)
        ]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                lanes[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(
                    lanes[x + 5 * y], _ROTATION[x][y]
                )
        # chi
        for x in range(5):
            for y in range(5):
                lanes[x + 5 * y] = b[x + 5 * y] ^ (
                    (~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y] & _MASK
                )
        # iota
        lanes[0] ^= rc
    for i in range(25):
        state[8 * i : 8 * i + 8] = lanes[i].to_bytes(8, "little")


# --- STROBE-128 ------------------------------------------------------------

_STROBE_R = 166  # 200 - 128/4 - 2

_FLAG_I = 1
_FLAG_A = 1 << 1
_FLAG_C = 1 << 2
_FLAG_T = 1 << 3
_FLAG_M = 1 << 4
_FLAG_K = 1 << 5


class Strobe128:
    """Minimal STROBE-128 duplex: exactly the subset Merlin uses
    (meta-AD, AD, PRF, KEY)."""

    __slots__ = ("state", "pos", "pos_begin", "cur_flags")

    def __init__(self, protocol_label: bytes):
        st = bytearray(200)
        st[0:6] = bytes((1, _STROBE_R + 2, 1, 0, 1, 96))
        st[6:18] = b"STROBEv1.0.2"
        keccak_f1600(st)
        self.state = st
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def clone(self) -> "Strobe128":
        c = Strobe128.__new__(Strobe128)
        c.state = bytearray(self.state)
        c.pos = self.pos
        c.pos_begin = self.pos_begin
        c.cur_flags = self.cur_flags
        return c

    # internal duplex ops

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_STROBE_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] = byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        for i in range(n):
            out[i] = self.state[self.pos]
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if self.cur_flags != flags:
                raise ValueError("strobe: op continuation changed flags")
            return
        if flags & _FLAG_T:
            raise ValueError("strobe: transport ops unsupported")
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes((old_begin, flags)))
        force_f = bool(flags & (_FLAG_C | _FLAG_K))
        if force_f and self.pos != 0:
            self._run_f()

    # public ops (Merlin's subset)

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        self._overwrite(data)


# --- Merlin transcript ------------------------------------------------------


def _le32(n: int) -> bytes:
    return n.to_bytes(4, "little")


class MerlinTranscript:
    """Merlin v1.0 transcript: labeled absorb / challenge over Strobe128."""

    __slots__ = ("strobe",)

    def __init__(self, label: bytes):
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def clone(self) -> "MerlinTranscript":
        c = MerlinTranscript.__new__(MerlinTranscript)
        c.strobe = self.strobe.clone()
        return c

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(_le32(len(message)), True)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, value: int) -> None:
        self.append_message(label, value.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(_le32(n), True)
        return self.strobe.prf(n, False)

    # Transcript-based RNG (merlin::TranscriptRngBuilder). Used for signing
    # nonces: rekey with the secret nonce seed, then with external entropy.

    def build_rng(self) -> "TranscriptRngBuilder":
        return TranscriptRngBuilder(self.strobe.clone())


class TranscriptRngBuilder:
    __slots__ = ("strobe",)

    def __init__(self, strobe: Strobe128):
        self.strobe = strobe

    def rekey_with_witness_bytes(
        self, label: bytes, witness: bytes
    ) -> "TranscriptRngBuilder":
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(_le32(len(witness)), True)
        self.strobe.key(witness, False)
        return self

    def finalize(self, entropy32: bytes) -> "TranscriptRng":
        if len(entropy32) != 32:
            raise ValueError("transcript rng entropy must be 32 bytes")
        self.strobe.meta_ad(b"rng", False)
        self.strobe.key(entropy32, False)
        return TranscriptRng(self.strobe)


class TranscriptRng:
    __slots__ = ("strobe",)

    def __init__(self, strobe: Strobe128):
        self.strobe = strobe

    def fill_bytes(self, n: int) -> bytes:
        self.strobe.meta_ad(_le32(n), False)
        return self.strobe.prf(n, False)
