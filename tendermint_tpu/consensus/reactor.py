"""Consensus reactor: targeted per-peer gossip of proposals, parts, votes.

Mirrors internal/consensus/reactor.go's channel layout — State(0x20),
Data(0x21), Vote(0x22), VoteSetBits(0x23) (reactor.go:78-81) — and its
gossip discipline: one gossip routine per peer consults that peer's
PeerState and sends only what the peer is missing (gossipDataRoutine
reactor.go:501, gossipVotesRoutine reactor.go:736), with block-part +
commit catch-up for peers on older heights (gossipDataForCatchup
reactor.go:437). Peers announce state via NewRoundStep, HasVote, and
periodic VoteSetBits; everything a peer sends also updates its
PeerState, so re-sends converge to zero once a peer is caught up.

Wire format per message: 1 tag byte + payload (struct-packed fields,
proto payloads for types).
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Dict, Optional

from tendermint_tpu.consensus.peer_state import PeerState
from tendermint_tpu.consensus.state import Broadcaster, ConsensusState
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.p2p.router import Channel, Envelope, Router
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
)
from tendermint_tpu.types.block import Proposal, Vote
from tendermint_tpu.types.part_set import Part

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

TAG_NEW_ROUND_STEP = 1
TAG_PROPOSAL = 2
TAG_BLOCK_PART = 3
TAG_VOTE = 4
TAG_HAS_VOTE = 5
TAG_VOTE_SET_BITS = 6

# How long gossip routines sleep when a peer needs nothing
# (peerGossipSleepDuration reactor.go:119 is 100ms; smaller here because
# test networks run sub-second rounds).
GOSSIP_SLEEP = 0.02
# Votes sent per gossip iteration when a peer is behind on votes.
VOTES_PER_ITER = 8
# Interval between VoteSetBits announcements of our own vote bitmaps.
BITS_INTERVAL = 0.5
# Upper bound on wire-supplied validator indices / bit-array sizes; a
# peer claiming more validators than this is lying (the reference bounds
# set size via MaxTotalVotingPower, validator_set.go:18-25).
MAX_WIRE_VALIDATORS = 65536


def encode_new_round_step(
    height: int, round_: int, step: int, last_commit_round: int
) -> bytes:
    return bytes([TAG_NEW_ROUND_STEP]) + struct.pack(
        ">qiii", height, round_, step, last_commit_round
    )


def encode_proposal(p: Proposal) -> bytes:
    return bytes([TAG_PROPOSAL]) + p.to_proto_bytes()


def encode_block_part(height: int, round_: int, part: Part) -> bytes:
    return (
        bytes([TAG_BLOCK_PART])
        + struct.pack(">qi", height, round_)
        + part.to_proto_bytes()
    )


def encode_vote(v: Vote) -> bytes:
    return bytes([TAG_VOTE]) + v.to_proto_bytes()


def encode_has_vote(height: int, round_: int, type_: int, index: int) -> bytes:
    return bytes([TAG_HAS_VOTE]) + struct.pack(">qibi", height, round_, type_, index)


def encode_vote_set_bits(
    height: int, round_: int, type_: int, bits: BitArray
) -> bytes:
    return (
        bytes([TAG_VOTE_SET_BITS])
        + struct.pack(">qibi", height, round_, type_, bits.size())
        + bytes(bits._elems)
    )


def decode_vote_set_bits(payload: bytes):
    """Returns (height, round, type, bits) or None for malformed/hostile
    input (oversized nbits would allocate unboundedly; a short payload
    would leave the BitArray's backing storage inconsistent)."""
    height, round_, type_, nbits = struct.unpack_from(">qibi", payload)
    if nbits < 0 or nbits > MAX_WIRE_VALIDATORS:
        return None
    ba = BitArray(nbits)
    body = payload[struct.calcsize(">qibi") :]
    if len(body) != len(ba._elems):
        return None
    ba._elems[:] = body
    return height, round_, type_, ba


class ConsensusReactor(Broadcaster):
    def __init__(self, cs: ConsensusState, router: Router):
        self.cs = cs
        self.router = router
        self.state_ch = router.open_channel(STATE_CHANNEL)
        self.data_ch = router.open_channel(DATA_CHANNEL)
        self.vote_ch = router.open_channel(VOTE_CHANNEL)
        self.vote_bits_ch = router.open_channel(VOTE_SET_BITS_CHANNEL)
        cs.broadcaster = self
        self._stop_flag = threading.Event()
        self._threads = []
        self._peers: Dict[str, PeerState] = {}
        self._gossip_threads: Dict[str, threading.Thread] = {}
        self._peers_mtx = threading.Lock()

    def start(self) -> None:
        self._stop_flag.clear()
        for ch, handler in (
            (self.state_ch, self._handle_state),
            (self.data_ch, self._handle_data),
            (self.vote_ch, self._handle_vote),
            (self.vote_bits_ch, self._handle_vote_bits),
        ):
            t = threading.Thread(
                target=self._recv_loop, args=(ch, handler), daemon=True
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._peer_lifecycle_loop, daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._announce_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop_flag.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        with self._peers_mtx:
            gossipers = list(self._gossip_threads.values())
            self._gossip_threads.clear()
            self._peers.clear()
        for t in gossipers:
            t.join(timeout=2)

    # --- peer lifecycle -------------------------------------------------------

    def _peer_lifecycle_loop(self) -> None:
        """Track router connections; one gossip routine per live peer
        (the reference subscribes to PeerUpdates, reactor.go:392)."""
        while not self._stop_flag.is_set():
            try:
                connected = set(self.router.connected_peers())
                with self._peers_mtx:
                    for pid in connected:
                        if pid not in self._gossip_threads:
                            ps = self._peers.get(pid) or PeerState(pid)
                            self._peers[pid] = ps
                            t = threading.Thread(
                                target=self._gossip_routine,
                                args=(ps,),
                                daemon=True,
                                name=f"cs-gossip-{pid[:8]}",
                            )
                            self._gossip_threads[pid] = t
                            t.start()
                    for pid in list(self._gossip_threads):
                        if pid not in connected:
                            del self._gossip_threads[pid]
                            self._peers.pop(pid, None)
            except Exception:
                pass
            self._stop_flag.wait(0.1)

    def _peer(self, peer_id: str) -> PeerState:
        with self._peers_mtx:
            ps = self._peers.get(peer_id)
            if ps is None:
                ps = PeerState(peer_id)
                self._peers[peer_id] = ps
            return ps

    # --- outbound (Broadcaster) ----------------------------------------------

    def broadcast_proposal(self, proposal: Proposal) -> None:
        self.data_ch.broadcast(encode_proposal(proposal))

    def broadcast_block_part(self, height: int, round_: int, part: Part) -> None:
        self.data_ch.broadcast(encode_block_part(height, round_, part))

    def broadcast_vote(self, vote: Vote) -> None:
        # The SM announces HasVote separately when the vote lands in a set.
        self.vote_ch.broadcast(encode_vote(vote))

    def broadcast_has_vote(
        self, height: int, round_: int, type_: int, index: int
    ) -> None:
        self.state_ch.broadcast(encode_has_vote(height, round_, type_, index))

    def broadcast_new_round_step(self, rs) -> None:
        lcr = rs.last_commit.round if rs.last_commit is not None else -1
        self.state_ch.broadcast(
            encode_new_round_step(rs.height, rs.round, int(rs.step), lcr)
        )

    # --- periodic announcements ----------------------------------------------

    def _announce_loop(self) -> None:
        """Broadcast NewRoundStep + our vote bitmaps periodically so late
        joiners and message-drop victims re-converge (the role of the
        reference's VoteSetMaj23/VoteSetBits query cycle, reactor.go:808)."""
        while not self._stop_flag.is_set():
            try:
                rs = self.cs.rs
                if rs.votes is not None:
                    self.broadcast_new_round_step(rs)
                    for type_, vs in (
                        (SIGNED_MSG_TYPE_PREVOTE, rs.votes.prevotes(rs.round)),
                        (SIGNED_MSG_TYPE_PRECOMMIT, rs.votes.precommits(rs.round)),
                    ):
                        if vs is not None:
                            self.vote_bits_ch.broadcast(
                                encode_vote_set_bits(
                                    rs.height, rs.round, type_, vs.bit_array()
                                )
                            )
            except Exception:
                pass
            self._stop_flag.wait(BITS_INTERVAL)

    # --- per-peer gossip ------------------------------------------------------

    def _gossip_routine(self, ps: PeerState) -> None:
        """reactor.go gossipDataRoutine+gossipVotesRoutine merged: each
        iteration sends the peer at most one part and a few votes."""
        while not self._stop_flag.is_set():
            with self._peers_mtx:
                if self._gossip_threads.get(ps.peer_id) is not threading.current_thread():
                    return  # unsubscribed
            try:
                sent = self._gossip_once(ps)
            except Exception:
                sent = False
            if not sent:
                self._stop_flag.wait(GOSSIP_SLEEP)

    def _gossip_once(self, ps: PeerState) -> bool:
        rs = self.cs.rs
        p_height, p_round, p_step, p_lcr = ps.snapshot()
        if p_height == 0:
            return False  # no NewRoundStep from the peer yet

        if p_height == rs.height:
            return self._gossip_same_height(ps, rs, p_round)
        if p_height < rs.height:
            return self._gossip_catchup(ps, p_height, p_round, p_lcr)
        return False  # peer ahead: blocksync pulls us forward, not gossip

    def _gossip_same_height(self, ps: PeerState, rs, p_round: int) -> bool:
        sent = False
        # Proposal + parts for the peer's current round (reactor.go:501).
        if p_round == rs.round and rs.proposal is not None and not ps.has_proposal:
            self.data_ch.send(
                Envelope(
                    DATA_CHANNEL,
                    encode_proposal(rs.proposal),
                    to_peer=ps.peer_id,
                )
            )
            ps.set_has_proposal(rs.height, rs.round)
            sent = True
        parts = rs.proposal_block_parts
        if p_round == rs.round and parts is not None:
            ps.init_parts(rs.height, rs.round, parts.header())
            idx = ps.pick_missing_part(parts.parts_bit_array)
            if idx is not None:
                part = parts.get_part(idx)
                if part is not None:
                    self.data_ch.send(
                        Envelope(
                            DATA_CHANNEL,
                            encode_block_part(rs.height, rs.round, part),
                            to_peer=ps.peer_id,
                        )
                    )
                    ps.set_has_part(rs.height, rs.round, idx)
                    sent = True
        # Votes: peer's round first, then our round, then POL round
        # (gossipVotesForHeight reactor.go:640-700).
        if rs.votes is not None:
            rounds = []
            for r in (p_round, rs.round, rs.valid_round):
                if r >= 0 and r not in rounds:
                    rounds.append(r)
            for r in rounds:
                for type_, vs in (
                    (SIGNED_MSG_TYPE_PREVOTE, rs.votes.prevotes(r)),
                    (SIGNED_MSG_TYPE_PRECOMMIT, rs.votes.precommits(r)),
                ):
                    if vs is None:
                        continue
                    if self._send_missing_votes(ps, vs, rs.height, r, type_):
                        sent = True
        return sent

    def _send_missing_votes(self, ps, vote_set, height, round_, type_) -> bool:
        ours = vote_set.bit_array()
        sent = False
        for _ in range(VOTES_PER_ITER):
            idx = ps.pick_missing_vote(height, round_, type_, ours)
            if idx is None:
                break
            vote = vote_set.get_by_index(idx)
            if vote is None:
                break
            self.vote_ch.send(
                Envelope(VOTE_CHANNEL, encode_vote(vote), to_peer=ps.peer_id)
            )
            ps.set_has_vote(height, round_, type_, idx, ours.size())
            sent = True
        return sent

    def _gossip_catchup(self, ps: PeerState, p_height, p_round, p_lcr) -> bool:
        """Peer is on an older height: serve the decided block's parts and
        its commit from the store (gossipDataForCatchup reactor.go:437)."""
        store = self.cs.block_store
        if p_height < store.base():
            return False
        meta = store.load_block_meta(p_height)
        # With vote extensions enabled the peer REQUIRES extensions on
        # every non-nil precommit, so when an extended commit is stored
        # it is the ONLY source served — its round/absence bookkeeping
        # can legitimately differ from the canonical commit (written by
        # the h+1 proposer), and mixing indices between the two would
        # serve wrong-round or unsigned votes that the peer rejects
        # while we mark them sent.
        ext_commit = store.load_block_extended_commit(p_height)
        commit = None
        if ext_commit is None:
            commit = store.load_block_commit(p_height)
            if commit is None:
                # The canonical commit for p_height is only stored once
                # block p_height+1 lands; until then the seen commit
                # covers it (reference serves rs.LastCommit to height-1
                # peers, reactor.go:736).
                seen = store.load_seen_commit()
                if seen is not None and seen.height == p_height:
                    commit = seen
        if meta is None:
            return False
        n_parts = meta.block_id.part_set_header.total
        if ext_commit is not None:
            n_sigs = ext_commit.size()
        else:
            n_sigs = commit.size() if commit is not None else 0
        ps.ensure_catchup(p_height, n_parts, n_sigs)
        sent = False
        # One part per iteration, preferring whatever the peer lacks.
        theirs = ps.parts if ps.parts is not None else BitArray(0)
        for i in range(n_parts):
            if ps.catchup_parts.get_index(i) or theirs.get_index(i):
                continue
            part = store.load_block_part(p_height, i)
            if part is None:
                break
            self.data_ch.send(
                Envelope(
                    DATA_CHANNEL,
                    encode_block_part(p_height, p_round, part),
                    to_peer=ps.peer_id,
                )
            )
            ps.catchup_parts.set_index(i, True)
            sent = True
            break
        # Commit precommits let the lagging peer finish its round
        # (reactor.go:736 LastCommit case). One source drives the whole
        # loop: the extended commit when stored, the canonical/seen
        # commit otherwise.
        if ext_commit is not None or commit is not None:
            budget = VOTES_PER_ITER
            for i in range(n_sigs):
                if budget == 0:
                    break
                if ps.catchup_commit.get_index(i):
                    continue
                if ext_commit is not None:
                    if not ext_commit.extended_signatures[i].commit_sig.signature:
                        ps.catchup_commit.set_index(i, True)
                        continue
                    vote = ext_commit.get_extended_vote(i)
                else:
                    if not commit.signatures[i].signature:
                        ps.catchup_commit.set_index(i, True)
                        continue
                    vote = commit.get_vote(i)
                self.vote_ch.send(
                    Envelope(VOTE_CHANNEL, encode_vote(vote), to_peer=ps.peer_id)
                )
                ps.catchup_commit.set_index(i, True)
                ps.set_has_vote(vote.height, vote.round, vote.type, i, n_sigs)
                sent = True
                budget -= 1
        return sent

    # --- inbound --------------------------------------------------------------

    def _recv_loop(self, ch: Channel, handler) -> None:
        while not self._stop_flag.is_set():
            env = ch.receive(timeout=0.2)
            if env is None:
                continue
            try:
                handler(env)
            except Exception:
                pass  # peer input must not kill the reactor

    def _handle_state(self, env: Envelope) -> None:
        if not env.message:
            return
        tag = env.message[0]
        if tag == TAG_NEW_ROUND_STEP:
            height, round_, step, lcr = struct.unpack_from(">qiii", env.message, 1)
            self._peer(env.from_peer).apply_new_round_step(height, round_, step, lcr)
        elif tag == TAG_HAS_VOTE:
            height, round_, type_, index = struct.unpack_from(">qibi", env.message, 1)
            if 0 <= index < MAX_WIRE_VALIDATORS:
                self._peer(env.from_peer).set_has_vote(height, round_, type_, index)

    def _handle_data(self, env: Envelope) -> None:
        if not env.message:
            return
        tag = env.message[0]
        if tag == TAG_PROPOSAL:
            proposal = Proposal.from_proto_bytes(env.message[1:])
            ps = self._peer(env.from_peer)
            ps.set_has_proposal(proposal.height, proposal.round)
            self.cs.add_proposal_from_peer(proposal, env.from_peer)
        elif tag == TAG_BLOCK_PART:
            height, round_ = struct.unpack_from(">qi", env.message, 1)
            part = Part.from_proto_bytes(env.message[13:])
            self._peer(env.from_peer).set_has_part(height, round_, part.index)
            self.cs.add_block_part_from_peer(height, round_, part, env.from_peer)

    def _handle_vote(self, env: Envelope) -> None:
        if not env.message or env.message[0] != TAG_VOTE:
            return
        vote = Vote.from_proto_bytes(env.message[1:])
        if not (0 <= vote.validator_index < MAX_WIRE_VALIDATORS):
            return
        self._peer(env.from_peer).set_has_vote(
            vote.height, vote.round, vote.type, vote.validator_index
        )
        self.cs.add_vote_from_peer(vote, env.from_peer)

    def _handle_vote_bits(self, env: Envelope) -> None:
        if not env.message or env.message[0] != TAG_VOTE_SET_BITS:
            return
        decoded = decode_vote_set_bits(env.message[1:])
        if decoded is None:
            return
        height, round_, type_, bits = decoded
        self._peer(env.from_peer).apply_vote_set_bits(height, round_, type_, bits)
