"""FilePV tests (privval/file_test.go analog): persistence, HRS guard,
timestamp-only re-sign, extension signing."""

import pytest

from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Timestamp,
)
from tendermint_tpu.privval import DoubleSignError, FilePV
from tendermint_tpu.types import BlockID, Proposal, Vote
from tests.helpers import CHAIN_ID, make_block_id

TS = Timestamp.from_unix_ns(1_700_000_000_000_000_000)
TS2 = Timestamp.from_unix_ns(1_700_000_001_000_000_000)


@pytest.fixture()
def pv(tmp_path):
    return FilePV.generate(str(tmp_path / "key.json"), str(tmp_path / "state.json"))


def _vote(pv, type_=SIGNED_MSG_TYPE_PREVOTE, height=1, round_=0, bid=None, ts=TS,
          extension=b""):
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=bid if bid is not None else make_block_id(),
        timestamp=ts,
        validator_address=pv.get_pub_key().address(),
        validator_index=0,
        extension=extension,
    )


class TestFilePV:
    def test_sign_and_verify(self, pv):
        v = _vote(pv)
        pv.sign_vote(CHAIN_ID, v)
        v.verify(CHAIN_ID, pv.get_pub_key())

    def test_persistence_roundtrip(self, pv, tmp_path):
        v = _vote(pv)
        pv.sign_vote(CHAIN_ID, v)
        reloaded = FilePV.load(str(tmp_path / "key.json"), str(tmp_path / "state.json"))
        assert reloaded.get_pub_key() == pv.get_pub_key()
        assert reloaded.last_sign_state.height == 1
        assert reloaded.last_sign_state.step == 2
        assert reloaded.last_sign_state.signature == v.signature

    def test_height_regression_rejected(self, pv):
        pv.sign_vote(CHAIN_ID, _vote(pv, height=5))
        with pytest.raises(DoubleSignError, match="height regression"):
            pv.sign_vote(CHAIN_ID, _vote(pv, height=4))

    def test_round_regression_rejected(self, pv):
        pv.sign_vote(CHAIN_ID, _vote(pv, height=5, round_=3))
        with pytest.raises(DoubleSignError, match="round regression"):
            pv.sign_vote(CHAIN_ID, _vote(pv, height=5, round_=2))

    def test_step_regression_rejected(self, pv):
        pv.sign_vote(CHAIN_ID, _vote(pv, type_=SIGNED_MSG_TYPE_PRECOMMIT))
        with pytest.raises(DoubleSignError, match="step regression"):
            pv.sign_vote(CHAIN_ID, _vote(pv, type_=SIGNED_MSG_TYPE_PREVOTE))

    def test_same_vote_reuses_signature(self, pv):
        v1 = _vote(pv)
        pv.sign_vote(CHAIN_ID, v1)
        v2 = _vote(pv)
        pv.sign_vote(CHAIN_ID, v2)
        assert v2.signature == v1.signature

    def test_timestamp_only_diff_reuses_signature(self, pv):
        v1 = _vote(pv, ts=TS)
        pv.sign_vote(CHAIN_ID, v1)
        v2 = _vote(pv, ts=TS2)
        pv.sign_vote(CHAIN_ID, v2)
        assert v2.signature == v1.signature
        assert v2.timestamp == TS  # reverted to the signed timestamp
        v2.verify(CHAIN_ID, pv.get_pub_key())

    def test_conflicting_block_rejected(self, pv):
        pv.sign_vote(CHAIN_ID, _vote(pv, bid=make_block_id(b"a")))
        with pytest.raises(DoubleSignError, match="conflicting data"):
            pv.sign_vote(CHAIN_ID, _vote(pv, bid=make_block_id(b"b")))

    def test_precommit_extension_signed(self, pv):
        v = _vote(pv, type_=SIGNED_MSG_TYPE_PRECOMMIT, extension=b"price:9")
        pv.sign_vote(CHAIN_ID, v)
        assert v.extension_signature
        v.verify_vote_and_extension(CHAIN_ID, pv.get_pub_key())

    def test_extension_on_prevote_rejected(self, pv):
        v = _vote(pv, type_=SIGNED_MSG_TYPE_PREVOTE, extension=b"x")
        with pytest.raises(ValueError, match="extension"):
            pv.sign_vote(CHAIN_ID, v)

    def test_nil_precommit_no_extension_signature(self, pv):
        v = _vote(pv, type_=SIGNED_MSG_TYPE_PRECOMMIT, bid=BlockID())
        pv.sign_vote(CHAIN_ID, v)
        assert v.extension_signature == b""

    def test_sign_proposal_and_hrs(self, pv):
        p = Proposal(
            height=3, round=0, pol_round=-1, block_id=make_block_id(), timestamp=TS
        )
        pv.sign_proposal(CHAIN_ID, p)
        assert p.signature
        # proposal step (1) precedes votes at same HRS: prevote allowed after
        pv.sign_vote(CHAIN_ID, _vote(pv, height=3))
        with pytest.raises(DoubleSignError):
            pv.sign_proposal(
                CHAIN_ID,
                Proposal(height=3, round=0, pol_round=-1,
                         block_id=make_block_id(b"other"), timestamp=TS),
            )

    def test_load_or_generate(self, tmp_path):
        key, state = str(tmp_path / "k.json"), str(tmp_path / "s.json")
        pv1 = FilePV.load_or_generate(key, state)
        pv2 = FilePV.load_or_generate(key, state)
        assert pv1.get_pub_key() == pv2.get_pub_key()
