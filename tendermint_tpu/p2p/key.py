"""Node identity (types/node_key.go, types/node_id.go).

NodeID = hex(first 20 bytes of SHA256(pubkey)) — the address of the
node's ed25519 identity key, used to authenticate transport handshakes.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tendermint_tpu.crypto.keys import Ed25519PrivKey, PubKey

NodeID = str  # 40 hex chars


def node_id_from_pubkey(pub: PubKey) -> NodeID:
    return pub.address().hex()


def validate_node_id(node_id: NodeID) -> None:
    if len(node_id) != 40:
        raise ValueError(f"invalid node ID length {len(node_id)}")
    int(node_id, 16)  # raises on non-hex


@dataclass
class NodeKey:
    """types/node_key.go: persistent p2p identity."""

    priv_key: Ed25519PrivKey

    @property
    def node_id(self) -> NodeID:
        return node_id_from_pubkey(self.priv_key.pub_key())

    @property
    def pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(Ed25519PrivKey.generate())

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            with open(path) as f:
                doc = json.load(f)
            return cls(Ed25519PrivKey(bytes.fromhex(doc["priv_key"])))
        nk = cls.generate()
        with open(path, "w") as f:
            json.dump({"priv_key": nk.priv_key.bytes().hex()}, f)
        return nk
