"""State sync: bootstrap a fresh node from an application snapshot
(reference: internal/statesync/ — reactor, syncer, state provider)."""

from tendermint_tpu.statesync.reactor import StateSyncReactor
from tendermint_tpu.statesync.syncer import StateSyncer, StateSyncConfig

__all__ = ["StateSyncReactor", "StateSyncer", "StateSyncConfig"]
