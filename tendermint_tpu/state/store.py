"""StateStore: persists State, per-height validator sets, per-height
consensus params, and FinalizeBlock responses (internal/state/store.go).

Validator sets are stored sparsely: a full set only at heights where the
set changed (and every `VALSET_CHECKPOINT_INTERVAL`), other heights store
a back-pointer — the reference's ValidatorsInfo scheme.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from tendermint_tpu.encoding.proto import (
    Reader,
    encode_bytes_field,
    encode_message_field,
    encode_varint_field,
)
from tendermint_tpu.state.state import State
from tendermint_tpu.storage.kv import KVStore, ordered_key, prefix_end
from tendermint_tpu.types.block import BlockID, Consensus, _decode_time, _encode_time_field
from tendermint_tpu.types.params import (
    ConsensusParams,
    consensus_params_from_proto_bytes,
    consensus_params_to_proto_bytes,
)
from tendermint_tpu.types.validator_set import ValidatorSet

VALSET_CHECKPOINT_INTERVAL = 100000  # internal/state/store.go valSetCheckpointInterval

PREFIX_VALIDATORS = 5
PREFIX_CONSENSUS_PARAMS = 6
PREFIX_ABCI_RESPONSES = 7
PREFIX_STATE = 8


def _validators_key(height: int) -> bytes:
    return ordered_key(PREFIX_VALIDATORS, height)


def _params_key(height: int) -> bytes:
    return ordered_key(PREFIX_CONSENSUS_PARAMS, height)


def _abci_responses_key(height: int) -> bytes:
    return ordered_key(PREFIX_ABCI_RESPONSES, height)


def _state_key() -> bytes:
    return bytes([PREFIX_STATE])


def _encode_state(s: State) -> bytes:
    """tendermint.state.State layout (proto/tendermint/state/types.proto):
    chain_id=2, initial_height=14, last_block_height=3, last_block_id=4,
    last_block_time=5, next_validators=6, validators=7, last_validators=8,
    last_height_validators_changed=9, consensus_params=10,
    last_height_consensus_params_changed=11, last_results_hash=12,
    app_hash=13; version.consensus packed in 1."""
    out = encode_message_field(
        1, encode_message_field(1, s.version.to_proto_bytes(), always=True), always=True
    )
    out += encode_bytes_field(2, s.chain_id.encode())
    out += encode_varint_field(3, s.last_block_height)
    out += encode_message_field(4, s.last_block_id.to_proto_bytes(), always=True)
    out += _encode_time_field(5, s.last_block_time)
    if s.next_validators is not None and not s.next_validators.is_nil_or_empty():
        out += encode_message_field(6, s.next_validators.to_proto_bytes(), always=True)
    if s.validators is not None and not s.validators.is_nil_or_empty():
        out += encode_message_field(7, s.validators.to_proto_bytes(), always=True)
    if s.last_validators is not None and not s.last_validators.is_nil_or_empty():
        out += encode_message_field(8, s.last_validators.to_proto_bytes(), always=True)
    out += encode_varint_field(9, s.last_height_validators_changed)
    out += encode_message_field(
        10, consensus_params_to_proto_bytes(s.consensus_params), always=True
    )
    out += encode_varint_field(11, s.last_height_consensus_params_changed)
    out += encode_bytes_field(12, s.last_results_hash)
    out += encode_bytes_field(13, s.app_hash)
    out += encode_varint_field(14, s.initial_height)
    return out


def _decode_state(data: bytes) -> State:
    s = State()
    r = Reader(data)
    for f, w in r.fields():
        if f == 1 and w == 2:
            vr = Reader(r.read_bytes())
            for vf, vw in vr.fields():
                if vf == 1 and vw == 2:
                    s.version = Consensus.from_proto_bytes(vr.read_bytes())
                else:
                    vr.skip(vw)
        elif f == 2 and w == 2:
            s.chain_id = r.read_bytes().decode()
        elif f == 3 and w == 0:
            s.last_block_height = r.read_svarint()
        elif f == 4 and w == 2:
            s.last_block_id = BlockID.from_proto_bytes(r.read_bytes())
        elif f == 5 and w == 2:
            s.last_block_time = _decode_time(r.read_bytes())
        elif f == 6 and w == 2:
            s.next_validators = ValidatorSet.from_proto_bytes(r.read_bytes())
        elif f == 7 and w == 2:
            s.validators = ValidatorSet.from_proto_bytes(r.read_bytes())
        elif f == 8 and w == 2:
            s.last_validators = ValidatorSet.from_proto_bytes(r.read_bytes())
        elif f == 9 and w == 0:
            s.last_height_validators_changed = r.read_svarint()
        elif f == 10 and w == 2:
            s.consensus_params = consensus_params_from_proto_bytes(r.read_bytes())
        elif f == 11 and w == 0:
            s.last_height_consensus_params_changed = r.read_svarint()
        elif f == 12 and w == 2:
            s.last_results_hash = r.read_bytes()
        elif f == 13 and w == 2:
            s.app_hash = r.read_bytes()
        elif f == 14 and w == 0:
            s.initial_height = r.read_svarint()
        else:
            r.skip(w)
    if s.last_validators is None:
        s.last_validators = ValidatorSet()
    return s


@dataclass
class ValidatorsInfo:
    """Sparse valset record: full set or back-pointer
    (internal/state/store.go ValidatorsInfo)."""

    last_height_changed: int
    validator_set: Optional[ValidatorSet] = None

    def encode(self) -> bytes:
        out = encode_varint_field(1, self.last_height_changed)
        if self.validator_set is not None:
            out += encode_message_field(
                2, self.validator_set.to_proto_bytes(), always=True
            )
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorsInfo":
        r = Reader(data)
        height = 0
        vset = None
        for f, w in r.fields():
            if f == 1 and w == 0:
                height = r.read_svarint()
            elif f == 2 and w == 2:
                vset = ValidatorSet.from_proto_bytes(r.read_bytes())
            else:
                r.skip(w)
        return cls(height, vset)


class StateStore:
    def __init__(self, db: KVStore):
        self._db = db

    # --- state ---------------------------------------------------------------

    def load(self) -> Optional[State]:
        raw = self._db.get(_state_key())
        return _decode_state(raw) if raw is not None else None

    def save(self, state: State) -> None:
        """store.go Save: state + next-height valset + params."""
        next_height = state.last_block_height + 1
        if state.last_block_height == 0:  # genesis bootstrap
            next_height = state.initial_height
            self._save_validators(
                next_height, next_height, state.validators
            )
        self._save_validators(
            next_height + 1, state.last_height_validators_changed, state.next_validators
        )
        self._save_params(
            next_height,
            state.last_height_consensus_params_changed,
            state.consensus_params,
        )
        self._db.set(_state_key(), _encode_state(state))

    def bootstrap(self, state: State) -> None:
        """store.go Bootstrap (statesync entry): full valset records at
        h (last), h+1 (current), h+2 (next) so commit verification and
        ABCI CommitInfo construction work from the restored height."""
        height = state.last_block_height + 1
        if state.last_validators is not None \
                and not state.last_validators.is_nil_or_empty():
            self._save_validators(height - 1, height - 1, state.last_validators)
        self._save_validators(height, height, state.validators)
        self._save_validators(
            height + 1, height + 1, state.next_validators
        )
        self._save_params(
            height, state.last_height_consensus_params_changed, state.consensus_params
        )
        self._db.set(_state_key(), _encode_state(state))

    # --- validator sets ------------------------------------------------------

    def _save_validators(
        self, height: int, last_height_changed: int, vset: Optional[ValidatorSet]
    ) -> None:
        if vset is None:
            return
        if last_height_changed > height:
            raise ValueError("lastHeightChanged cannot be greater than ValidatorsInfo height")
        # Persist the full set at change heights and checkpoints; pointer otherwise.
        if height == last_height_changed or height % VALSET_CHECKPOINT_INTERVAL == 0:
            info = ValidatorsInfo(last_height_changed, vset)
        else:
            info = ValidatorsInfo(last_height_changed)
        self._db.set(_validators_key(height), info.encode())

    def load_validators(self, height: int) -> ValidatorSet:
        """store.go LoadValidators with pointer-chase + priority replay."""
        raw = self._db.get(_validators_key(height))
        if raw is None:
            raise LookupError(f"no validator set at height {height}")
        info = ValidatorsInfo.decode(raw)
        if info.validator_set is not None:
            return info.validator_set
        raw2 = self._db.get(_validators_key(info.last_height_changed))
        if raw2 is None:
            raise LookupError(
                f"missing checkpoint validator set at height {info.last_height_changed}"
            )
        info2 = ValidatorsInfo.decode(raw2)
        if info2.validator_set is None:
            raise LookupError(
                f"validator pointer at {height} led to another pointer at "
                f"{info.last_height_changed}"
            )
        vset = info2.validator_set.copy()
        # Replay proposer rotation to the requested height (store.go:105-120).
        vset.increment_proposer_priority(height - info.last_height_changed)
        return vset

    # --- consensus params ----------------------------------------------------

    def _save_params(
        self, height: int, last_height_changed: int, params: ConsensusParams
    ) -> None:
        if height == last_height_changed:
            payload = encode_varint_field(1, last_height_changed) + encode_message_field(
                2, consensus_params_to_proto_bytes(params), always=True
            )
        else:
            payload = encode_varint_field(1, last_height_changed)
        self._db.set(_params_key(height), payload)

    def load_consensus_params(self, height: int) -> ConsensusParams:
        raw = self._db.get(_params_key(height))
        if raw is None:
            raise LookupError(f"no consensus params at height {height}")
        last_height_changed, params = self._decode_params_info(raw)
        if params is not None:
            return params
        raw2 = self._db.get(_params_key(last_height_changed))
        if raw2 is None:
            raise LookupError(
                f"missing consensus params at change height {last_height_changed}"
            )
        _, params2 = self._decode_params_info(raw2)
        if params2 is None:
            raise LookupError("consensus params pointer led to another pointer")
        return params2

    @staticmethod
    def _decode_params_info(raw: bytes) -> Tuple[int, Optional[ConsensusParams]]:
        r = Reader(raw)
        height = 0
        params = None
        for f, w in r.fields():
            if f == 1 and w == 0:
                height = r.read_svarint()
            elif f == 2 and w == 2:
                params = consensus_params_from_proto_bytes(r.read_bytes())
            else:
                r.skip(w)
        return height, params

    # --- ABCI responses -------------------------------------------------------

    def save_finalize_block_response(self, height: int, response_bytes: bytes) -> None:
        """Raw proto bytes of the FinalizeBlock response, for replay/indexing
        (store.go SaveFinalizeBlockResponses)."""
        self._db.set(_abci_responses_key(height), response_bytes)

    def load_finalize_block_response(self, height: int) -> Optional[bytes]:
        return self._db.get(_abci_responses_key(height))

    def load_decoded_finalize_block_response(self, height: int):
        """The stored FinalizeBlock response as an abci object, or None —
        the public seam replay/reindex/RPC share (store.go
        LoadFinalizeBlockResponses)."""
        raw = self.load_finalize_block_response(height)
        if raw is None:
            return None
        from tendermint_tpu.state.execution import (
            _unmarshal_finalize_response,
        )

        return _unmarshal_finalize_response(raw)

    def prune_states(self, retain_height: int) -> None:
        """store.go PruneStates: drop valsets/params/responses below height."""
        for prefix, keyfn in (
            (PREFIX_VALIDATORS, _validators_key),
            (PREFIX_CONSENSUS_PARAMS, _params_key),
            (PREFIX_ABCI_RESPONSES, _abci_responses_key),
        ):
            batch = self._db.new_batch()
            for k, _ in self._db.iterator(
                ordered_key(prefix, 0), keyfn(retain_height)
            ):
                batch.delete(k)
            batch.write()
