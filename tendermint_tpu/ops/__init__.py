"""Device kernels: batched Ed25519 verification on TPU via JAX/XLA.

This package is the TPU-native replacement for the reference's native
crypto dependency (curve25519-voi; SURVEY.md §2.9): GF(2^255-19) limb
arithmetic shaped for the TPU VPU, complete Edwards point ops, and a
vmap-free hand-batched ZIP-215 verifier, shardable over device meshes
(see tendermint_tpu.parallel).
"""

from tendermint_tpu.ops.ed25519_batch import (  # noqa: F401
    prepare_batch,
    verify_batch,
    verify_kernel,
)
