"""Interpret-mode parity: the Pallas kernel vs the ZIP-215 oracle.

The Pallas verifier (ops/pallas_verify.py) restates the field32/curve32
math with kernel-local ops; these tests pin it to the pure-Python
ZIP-215 oracle (crypto/ed25519_ref.py) on the same edge vectors
test_ops_ed25519.py uses for the XLA graph, running the kernel in
interpret mode so no TPU is needed (reference test model: substitute a
fake backend, SURVEY.md section 4; semantics from
crypto/ed25519/ed25519.go:24-31).

Interpret mode traces the kernel body as ordinary JAX ops, so one
compile of the 8-lane block is shared by every test in this module.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.ops import ed25519_batch, pallas_verify


def keypair(i):
    return ref.keypair_from_seed(bytes([i + 1]) * 32)


def pallas_verify_batch(pks, msgs, sigs):
    """verify_batch semantics routed through the interpret-mode kernel."""
    n = len(pks)
    pad = ((n + 7) // 8) * 8
    inputs, host_ok = ed25519_batch.prepare_batch(pks, msgs, sigs, pad_to=pad)
    fn = pallas_verify.compiled_verify(pad, block=8, interpret=True)
    out = fn(
        jnp.asarray(inputs["pk"]),
        jnp.asarray(inputs["r"]),
        jnp.asarray(inputs["s"]),
        jnp.asarray(inputs["k"]),
    )
    return list(np.logical_and(np.asarray(out)[:n], host_ok))


@pytest.fixture(scope="module")
def batch8():
    pks, msgs, sigs = [], [], []
    for i in range(8):
        priv, pub = keypair(i)
        msg = b"vote %d" % i
        pks.append(pub)
        msgs.append(msg)
        sigs.append(ref.sign(priv, msg))
    return pks, msgs, sigs


def test_pallas_valid_batch(batch8):
    pks, msgs, sigs = batch8
    assert pallas_verify_batch(pks, msgs, sigs) == [True] * 8


def test_pallas_flags_bad_entries(batch8):
    pks, msgs, sigs = (list(x) for x in batch8)
    sigs[1] = sigs[1][:32] + bytes(32)  # wrong s
    msgs[3] = b"tampered"  # wrong msg
    sigs[5] = bytes(32) + sigs[5][32:]  # R replaced (y=0 IS on curve)
    pks[6] = keypair(7)[1]  # wrong key
    got = pallas_verify_batch(pks, msgs, sigs)
    assert got == [True, False, True, False, True, False, False, True]


def test_pallas_zip215_edge_cases(batch8):
    pks, msgs, sigs = (list(x) for x in batch8)
    # identity pubkey: R = [s]B verifies for any msg (small-order accepted)
    ident = (1).to_bytes(32, "little")
    s = 12345
    rb = ref.pt_compress(ref.pt_mul(s, ref.B_POINT))
    sig215 = rb + s.to_bytes(32, "little")
    assert ref.verify_zip215_slow(ident, b"x", sig215)
    pks[0], msgs[0], sigs[0] = ident, b"x", sig215
    # non-canonical encoding of the same point
    pks[1], msgs[1], sigs[1] = (ref.P + 1).to_bytes(32, "little"), b"x", sig215
    # s >= L must be rejected even though the curve equation would hold
    pks[2], msgs[2], sigs[2] = ident, b"x", rb + (s + ref.L).to_bytes(32, "little")
    got = pallas_verify_batch(pks, msgs, sigs)
    assert got == [True, True, False, True, True, True, True, True]


def test_pallas_off_curve_and_mutations(batch8):
    pks, msgs, sigs = (list(x) for x in batch8)
    rng = np.random.RandomState(7)
    pks[0] = bytes([2] + [0] * 31)  # y=2: off-curve, must reject
    for i in range(1, 8):
        mode = i % 4
        if mode == 0:
            continue  # leave valid
        b = bytearray(sigs[i])
        if mode == 1:
            b[rng.randint(32)] ^= 1 << rng.randint(8)  # corrupt R
        elif mode == 2:
            b[32 + rng.randint(31)] ^= 1 << rng.randint(8)  # corrupt s
        else:
            pk = bytearray(pks[i])
            pk[rng.randint(32)] ^= 1 << rng.randint(8)
            pks[i] = bytes(pk)
        sigs[i] = bytes(b)
    want = [ref.verify_zip215(pk, m, s) for pk, m, s in zip(pks, msgs, sigs)]
    got = pallas_verify_batch(pks, msgs, sigs)
    assert got == want


def pallas_verify_batch_tables(pks, msgs, sigs):
    """Table-input kernel: host-built precompute columns, no in-kernel
    table construction. Mirrors _run_chunk_tables' pallas branch."""
    from tendermint_tpu.ops import precompute

    n = len(pks)
    pad = ((n + 7) // 8) * 8
    tabs, oks = zip(*(precompute.build_table(pk) for pk in pks))
    inputs, host_ok = ed25519_batch._prep_table_chunk(
        pks, msgs, sigs, list(tabs), list(oks), pad_to=pad
    )
    fn = pallas_verify.compiled_verify_tables(pad, block=8, interpret=True)
    out = fn(
        jnp.asarray(inputs["tab"]),
        jnp.asarray(inputs["ok"]),
        jnp.asarray(inputs["r"]),
        jnp.asarray(inputs["s"]),
        jnp.asarray(inputs["k"]),
    )
    return list(np.logical_and(np.asarray(out)[:n], host_ok))


@pytest.mark.slow  # interpret-mode XLA compile of this kernel runs ~8 min
def test_pallas_table_path_parity(batch8):
    pks, msgs, sigs = (list(x) for x in batch8)
    pks[0] = bytes([2] + [0] * 31)  # off-curve: identity table, ok=False
    sigs[1] = sigs[1][:33] + bytes([sigs[1][33] ^ 1]) + sigs[1][34:]
    msgs[2] = b"tampered"
    pks[3] = (ref.P + 1).to_bytes(32, "little")  # non-canonical encoding
    want = [ref.verify_zip215(pk, m, s) for pk, m, s in zip(pks, msgs, sigs)]
    assert pallas_verify_batch_tables(pks, msgs, sigs) == want


def test_dispatch_prefers_pallas_on_tpu(monkeypatch):
    """active_impl routes TPU platforms to the Pallas kernel, CPU to XLA."""
    monkeypatch.delenv(ed25519_batch._IMPL_ENV, raising=False)
    monkeypatch.setattr(ed25519_batch, "_PALLAS_BROKEN", False)
    monkeypatch.setattr(ed25519_batch, "_platform", lambda b: "tpu")
    assert ed25519_batch.active_impl() == "pallas"
    monkeypatch.setattr(ed25519_batch, "_platform", lambda b: "cpu")
    assert ed25519_batch.active_impl() == "xla"
    monkeypatch.setenv(ed25519_batch._IMPL_ENV, "pallas")
    assert ed25519_batch.active_impl() == "pallas"
    monkeypatch.setattr(ed25519_batch, "_PALLAS_BROKEN", True)
    assert ed25519_batch.active_impl() == "xla"


def test_dispatch_falls_back_when_pallas_fails(monkeypatch, batch8):
    """A Pallas failure degrades to the XLA graph instead of erroring."""
    pks, msgs, sigs = batch8
    monkeypatch.setattr(ed25519_batch, "_PALLAS_BROKEN", False)
    monkeypatch.setenv(ed25519_batch._IMPL_ENV, "pallas")

    def boom(n, block=256, interpret=False):
        raise RuntimeError("mosaic unavailable")

    monkeypatch.setattr(pallas_verify, "compiled_verify", boom)
    with pytest.warns(UserWarning, match="falling back"):
        assert ed25519_batch.verify_batch(pks, msgs, sigs) == [True] * 8
    assert ed25519_batch._PALLAS_BROKEN
    monkeypatch.setattr(ed25519_batch, "_PALLAS_BROKEN", False)
