"""tpulint framework tests: every checker family must flag its target
pattern (fixture) and stay quiet on the clean twin, and the repo itself
must pass ``python -m scripts.analysis`` with the committed baseline."""

import textwrap

import pytest

from scripts.analysis import checker_registry
from scripts.analysis.core import (
    Finding,
    Module,
    Project,
    Runner,
    diff_baseline,
    load_baseline,
    write_baseline,
)
from scripts.analysis.hygiene import HygieneChecker
from scripts.analysis.jaxpurity import JaxPurityChecker
from scripts.analysis.locks import LockDisciplineChecker
from scripts.analysis.metrics_checks import MetricsChecker
from scripts.analysis.taint import TaintChecker
from scripts.analysis.wire import WireCompatChecker


def run_on(checker, sources):
    """sources: {rel_path: code}. Returns list of finding codes+lines."""
    modules = [
        Module(rel, textwrap.dedent(src), rel=rel)
        for rel, src in sources.items()
    ]
    return Runner([checker]).run(modules)


def codes(findings):
    return [f.code for f in findings]


# --- lock discipline ---------------------------------------------------------


LOCKED_DIRTY = """
    import threading

    class Box:
        def __init__(self):
            self._mtx = threading.Lock()
            self._items = []  # guarded-by: _mtx

        def bad(self):
            return len(self._items)

        def good(self):
            with self._mtx:
                return len(self._items)
"""

LOCKED_CLEAN = """
    import threading

    class Box:
        def __init__(self):
            self._mtx = threading.Lock()
            self._items = []  # guarded-by: _mtx

        def good(self):
            with self._mtx:
                return len(self._items)
"""


class TestLockDiscipline:
    def test_flags_unlocked_access(self):
        found = run_on(LockDisciplineChecker(), {"m.py": LOCKED_DIRTY})
        assert codes(found) == ["TPL001"]
        assert "_items" in found[0].message

    def test_clean_twin_passes(self):
        assert run_on(LockDisciplineChecker(), {"m.py": LOCKED_CLEAN}) == []

    def test_condition_wraps_lock_alias(self):
        src = """
            import threading

            class Q:
                def __init__(self):
                    self._mtx = threading.Lock()
                    self._wake = threading.Condition(self._mtx)
                    self._pending = []  # guarded-by: _mtx

                def drain(self):
                    with self._wake:
                        return list(self._pending)
        """
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_none_annotation_documents_lock_free(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._mtx = threading.Lock()
                    self.hits = 0  # guarded-by: none(single-writer stats)

                def bump(self):
                    self.hits += 1
        """
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_unknown_lock_name(self):
        src = """
            class S:
                def __init__(self):
                    self.x = 0  # guarded-by: _nope
        """
        found = run_on(LockDisciplineChecker(), {"m.py": src})
        assert codes(found) == ["TPL002"]

    def test_orphan_annotation(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._mtx = threading.Lock()
                    # guarded-by: _mtx
                    pass
        """
        found = run_on(LockDisciplineChecker(), {"m.py": src})
        assert codes(found) == ["TPL003"]

    def test_module_global_guarded_by_module_lock_accepted(self):
        """The ops singleton pattern: a top-level global annotated with
        a module-scope lock is a legitimate annotation, not an orphan."""
        src = """
            import threading

            _lock = threading.Lock()
            _selected = {}  # guarded-by: _lock
            _count = 0  # guarded-by: none(single-writer stats)
        """
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_module_global_guarded_by_unknown_lock_is_orphan(self):
        src = """
            _selected = {}  # guarded-by: _lock
        """
        found = run_on(LockDisciplineChecker(), {"m.py": src})
        assert codes(found) == ["TPL003"]

    def test_locked_suffix_methods_assume_lock_held(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._mtx = threading.Lock()
                    self._v = 0  # guarded-by: _mtx

                def _bump_locked(self):
                    self._v += 1
        """
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_nested_def_resets_held_locks(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._mtx = threading.Lock()
                    self._v = 0  # guarded-by: _mtx

                def spawn(self):
                    with self._mtx:
                        def cb():
                            return self._v  # escapes the critical section
                        return cb
        """
        found = run_on(LockDisciplineChecker(), {"m.py": src})
        assert codes(found) == ["TPL001"]

    def test_base_class_lock_is_inherited(self):
        src = """
            import threading

            class Base:
                def __init__(self):
                    self._lock = threading.Lock()

            class Child(Base):
                def __init__(self):
                    super().__init__()
                    self._v = {}  # guarded-by: _lock

                def get(self):
                    with self._lock:
                        return dict(self._v)
        """
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []


# --- instrumented-class coverage (TPL005) ------------------------------------


TPL005_DIRTY = """
    import threading
    from tendermint_tpu.libs.sanitizer import instrument_attrs

    @instrument_attrs
    class Pool:
        def __init__(self):
            self._mtx = threading.Lock()
            self.depth = 0

        def grow(self):
            with self._mtx:
                self.depth += 1

        def shrink(self):
            with self._mtx:
                self.depth -= 1
"""

TPL005_CLEAN = """
    import threading
    from tendermint_tpu.libs.sanitizer import instrument_attrs

    @instrument_attrs
    class Pool:
        def __init__(self):
            self._mtx = threading.Lock()
            self.depth = 0  # guarded-by: _mtx

        def grow(self):
            with self._mtx:
                self.depth += 1

        def shrink(self):
            with self._mtx:
                self.depth -= 1
"""


class TestInstrumentedCoverage:
    def test_flags_unannotated_multi_writer_attr(self):
        found = run_on(LockDisciplineChecker(), {"m.py": TPL005_DIRTY})
        assert codes(found) == ["TPL005"]
        assert "Pool.depth" in found[0].message
        assert "grow" in found[0].message and "shrink" in found[0].message

    def test_annotated_twin_passes(self):
        assert run_on(LockDisciplineChecker(), {"m.py": TPL005_CLEAN}) == []

    def test_single_writer_method_is_not_shared(self):
        src = TPL005_DIRTY.replace(
            "        def shrink(self):\n"
            "            with self._mtx:\n"
            "                self.depth -= 1\n",
            "",
        )
        assert "shrink" not in src  # the replace actually fired
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_decorator_exclude_suppresses(self):
        src = TPL005_DIRTY.replace(
            "@instrument_attrs",
            '@instrument_attrs(exclude=("depth",))',
        )
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_none_reason_annotation_suppresses(self):
        src = TPL005_DIRTY.replace(
            "self.depth = 0",
            "self.depth = 0  # guarded-by: none(stats-grade, torn reads ok)",
        )
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_uninstrumented_class_is_out_of_scope(self):
        src = "\n".join(
            ln
            for ln in TPL005_DIRTY.splitlines()
            if "@instrument_attrs" not in ln
        )
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []

    def test_dotted_decorator_form_is_recognized(self):
        src = TPL005_DIRTY.replace(
            "@instrument_attrs", "@sanitizer.instrument_attrs"
        ).replace(
            "from tendermint_tpu.libs.sanitizer import instrument_attrs",
            "from tendermint_tpu.libs import sanitizer",
        )
        found = run_on(LockDisciplineChecker(), {"m.py": src})
        assert codes(found) == ["TPL005"]


# --- JAX purity --------------------------------------------------------------


JIT_DIRTY = """
    import time
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        t = time.monotonic()
        return x + t
"""

JIT_CLEAN = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        return x * 2
"""


class TestJaxPurity:
    def run(self, src):
        return run_on(
            JaxPurityChecker(), {"tendermint_tpu/ops/fix.py": src}
        )

    def test_flags_time_call_in_jitted_fn(self):
        found = self.run(JIT_DIRTY)
        assert codes(found) == ["TPJ001"]
        assert "time.monotonic" in found[0].message

    def test_clean_twin_passes(self):
        assert self.run(JIT_CLEAN) == []

    def test_reachability_through_helper(self):
        src = """
            import time
            import jax

            def helper(x):
                print(x)
                return x

            @jax.jit
            def kernel(x):
                return helper(x)
        """
        found = self.run(src)
        assert codes(found) == ["TPJ001"]
        assert "print" in found[0].message

    def test_unreachable_helper_may_do_io(self):
        src = """
            import jax

            def host_only(path):
                return open(path).read()

            @jax.jit
            def kernel(x):
                return x
        """
        assert self.run(src) == []

    def test_branch_on_traced_value(self):
        src = """
            import jax

            @jax.jit
            def kernel(x):
                if x > 0:
                    return x
                return -x
        """
        found = self.run(src)
        assert codes(found) == ["TPJ002"]

    def test_shape_branch_is_static(self):
        src = """
            import jax

            @jax.jit
            def kernel(x):
                if x.shape[0] > 8:
                    return x[:8]
                return x
        """
        assert self.run(src) == []

    def test_string_compare_is_host_config(self):
        src = """
            import jax

            @jax.jit
            def kernel(x, mode="a"):
                if mode == "a":
                    return x
                return -x
        """
        assert self.run(src) == []

    def test_dtype_discipline(self):
        src = """
            import jax.numpy as jnp

            def table():
                return jnp.zeros((4,), dtype=jnp.float64)
        """
        found = self.run(src)
        assert codes(found) == ["TPJ003"]

    def test_jit_call_entry_point(self):
        src = """
            import jax

            def run(x):
                print(x)
                return x

            compiled = jax.jit(run)
        """
        found = self.run(src)
        assert codes(found) == ["TPJ001"]

    def test_jit_factory_closure_is_entry_point(self):
        """jax.jit(factory(...)) — the autotuner's timing-kernel shape —
        must resolve through the factory to the returned closure."""
        src = """
            import time
            import jax

            def make_chain(n):
                def chain(a, b):
                    time.sleep(0)
                    return a + b
                return chain

            compiled = jax.jit(make_chain(8))
        """
        found = self.run(src)
        assert codes(found) == ["TPJ001"]
        assert "time.sleep" in found[0].message

    def test_jit_factory_clean_closure_passes(self):
        src = """
            import jax

            def make_chain(n):
                def chain(a, b):
                    return a + b
                return chain

            compiled = jax.jit(make_chain(8))
        """
        assert self.run(src) == []

    def test_bare_from_import_alias_flagged(self):
        """from time import perf_counter: the bare call is as impure as
        the dotted one."""
        src = """
            from time import perf_counter

            import jax

            @jax.jit
            def kernel(x):
                t = perf_counter()
                return x + t
        """
        found = self.run(src)
        assert codes(found) == ["TPJ001"]
        assert "time.perf_counter" in found[0].message

    def test_benign_from_import_alias_passes(self):
        src = """
            from functools import lru_cache

            import jax

            @jax.jit
            def kernel(x):
                return x
        """
        assert self.run(src) == []


# --- wire compat -------------------------------------------------------------


WIRE_DIRTY = """
    CLASS_CONSENSUS = 0
    CLASS_RPC = 3
    CLASS_NAMES = {CLASS_CONSENSUS: "consensus", CLASS_RPC: "rpc"}

    def _put_varint(out, v):
        out.append(v)

    def encode(req):
        out = []
        if req.klass:
            _put_varint(out, req.klass)
        return out

    def decode(data):
        klass = CLASS_RPC
        return klass
"""

WIRE_CLEAN = """
    CLASS_CONSENSUS = 0
    CLASS_RPC = 3
    CLASS_NAMES = {CLASS_CONSENSUS: "consensus", CLASS_RPC: "rpc"}

    def _put_varint(out, v):
        out.append(v)

    def encode(req):
        out = []
        _put_varint(out, req.klass + 1)
        return out

    def decode(r, req):
        req.klass = r.read_varint() - 1
        return req
"""


class TestWireCompat:
    def run(self, src):
        return run_on(
            WireCompatChecker(), {"tendermint_tpu/verifyd/protocol.py": src}
        )

    def test_flags_zero_omitted_meaningful_enum(self):
        found = self.run(WIRE_DIRTY)
        assert codes(found) == ["TPW001"]
        assert "CLASS_CONSENSUS" in found[0].message

    def test_shifted_twin_passes(self):
        assert self.run(WIRE_CLEAN) == []

    def test_asymmetric_shift(self):
        src = """
            CLASS_CONSENSUS = 0
            CLASS_NAMES = {CLASS_CONSENSUS: "consensus"}

            def encode(req, out):
                out.append(req.klass + 1)
        """
        found = self.run(src)
        assert codes(found) == ["TPW002"]
        assert "never decoded" in found[0].message

    def test_conditional_grpc_status(self):
        src = """
            def trailers(conn, status):
                hdrs = []
                if status:
                    hdrs.append(("grpc-status", str(status)))
                conn.send(hdrs)
        """
        found = run_on(
            WireCompatChecker(), {"tendermint_tpu/libs/grpc.py": src}
        )
        assert codes(found) == ["TPW003"]

    def test_unconditional_grpc_status_passes(self):
        src = """
            def trailers(conn, status):
                conn.send([("grpc-status", str(status))])
        """
        assert (
            run_on(WireCompatChecker(), {"tendermint_tpu/libs/grpc.py": src})
            == []
        )

    def test_non_wire_files_ignored(self):
        assert run_on(WireCompatChecker(), {"other.py": WIRE_DIRTY}) == []

    def test_default_omitted_string_without_reestablish(self):
        src = """
            DEFAULT_TENANT = "default"

            def encode_string_field(field, s):
                return b""

            def encode(req):
                out = b""
                if req.tenant and req.tenant != DEFAULT_TENANT:
                    out += encode_string_field(6, req.tenant)
                return out

            def decode(r, req):
                req.tenant = r.read_bytes().decode()
                return req
        """
        found = self.run(src)
        assert codes(found) == ["TPW004"]
        assert "tenant" in found[0].message
        assert "DEFAULT_TENANT" in found[0].message

    def test_default_omitted_string_with_or_normalization_passes(self):
        src = """
            DEFAULT_TENANT = "default"

            def encode_string_field(field, s):
                return b""

            def encode(req):
                out = b""
                if req.tenant and req.tenant != DEFAULT_TENANT:
                    out += encode_string_field(6, req.tenant)
                return out

            def decode(r, req):
                req.tenant = r.read_bytes().decode()
                req.tenant = req.tenant or DEFAULT_TENANT
                return req
        """
        assert self.run(src) == []

    def test_default_omitted_string_with_dataclass_default_passes(self):
        src = """
            DEFAULT_TENANT = "default"

            class VerifyRequest:
                tenant: str = DEFAULT_TENANT

            def encode_string_field(field, s):
                return b""

            def encode(req):
                out = b""
                if req.tenant != DEFAULT_TENANT:
                    out += encode_string_field(6, req.tenant)
                return out
        """
        assert self.run(src) == []

    def test_truthiness_omitted_bytes_without_reestablish(self):
        # the trace-context pattern: a bytes field omitted when falsy must
        # have a decode path that pins the empty default, otherwise an old
        # frame (field absent) decodes to None and re-encodes differently
        src = """
            def encode_bytes_field(field, b):
                return b""

            def encode(req):
                out = b""
                if req.trace:
                    out += encode_bytes_field(7, req.trace)
                return out

            def decode(r, req):
                req.trace = r.read_bytes()
                return req
        """
        found = self.run(src)
        assert codes(found) == ["TPW004"]
        assert "trace" in found[0].message
        assert "truthiness" in found[0].message

    def test_truthiness_omitted_bytes_with_or_empty_passes(self):
        # clean twin: post-parse `or b""` re-establishes the empty default,
        # so absent-field frames decode byte-identically on re-encode
        src = """
            def encode_bytes_field(field, b):
                return b""

            def encode(req):
                out = b""
                if req.trace:
                    out += encode_bytes_field(7, req.trace)
                return out

            def decode(r, req):
                req.trace = r.read_bytes()
                req.trace = req.trace or b""
                return req
        """
        assert self.run(src) == []

    def test_truthiness_omitted_string_with_dataclass_default_passes(self):
        # an empty-literal dataclass default also pins the decode default
        src = """
            class VerifyResponse:
                message: str = ""

            def encode_string_field(field, s):
                return b""

            def encode(resp):
                out = b""
                if resp.message:
                    out += encode_string_field(3, resp.message)
                return out
        """
        assert self.run(src) == []


# ISSUE 17: the slo_ms wire field — a zero-omitted PLAIN varint (no enum
# family) whose decode path must pin the integer zero, or an old frame
# without the field decodes differently from a new frame carrying an
# explicit 0.
SLO_VARINT_DIRTY = """
    def encode_varint_field(field, v):
        return b""

    def encode(req):
        out = b""
        if req.slo_ms:
            out += encode_varint_field(8, req.slo_ms)
        return out

    def decode(r, req):
        req.slo_ms = r.read_varint()
        return req
"""

SLO_VARINT_CLEAN = """
    def encode_varint_field(field, v):
        return b""

    def encode(req):
        out = b""
        if req.slo_ms:
            out += encode_varint_field(8, req.slo_ms)
        return out

    def decode(r, req):
        req.slo_ms = r.read_varint()
        req.slo_ms = req.slo_ms or 0
        return req
"""


class TestWireVarintZeroOmission:
    def run(self, src):
        return run_on(
            WireCompatChecker(), {"tendermint_tpu/verifyd/protocol.py": src}
        )

    def test_flags_varint_without_zero_reestablishment(self):
        found = self.run(SLO_VARINT_DIRTY)
        assert codes(found) == ["TPW004"]
        assert "slo_ms" in found[0].message
        assert "zero" in found[0].message

    def test_or_zero_twin_passes(self):
        assert self.run(SLO_VARINT_CLEAN) == []

    def test_zero_dataclass_default_passes(self):
        src = """
            class VerifyRequest:
                slo_ms: int = 0

            def encode_varint_field(field, v):
                return b""

            def encode(req):
                out = b""
                if req.slo_ms:
                    out += encode_varint_field(8, req.slo_ms)
                return out
        """
        assert self.run(src) == []

    def test_enum_family_fields_stay_tpw001_territory(self):
        # a field WITH an enum family is TPW001's beat even when emitted
        # via encode_varint_field: the dataclass AnnAssign default names
        # the 0-member, so the zero-omission round-trip is safe and the
        # varint leg must not double-report it
        src = """
            KIND_RAW = 0
            KIND_COMMIT = 1
            KIND_NAMES = {KIND_RAW: "raw", KIND_COMMIT: "commit"}

            class VerifyRequest:
                kind: int = KIND_RAW

            def encode_varint_field(field, v):
                return b""

            def encode(req):
                out = b""
                if req.kind:
                    out += encode_varint_field(1, req.kind)
                return out
        """
        assert self.run(src) == []

    def test_enum_family_via_field_emitter_still_catches_tpw001(self):
        # the dirty twin: same emit through encode_varint_field, but the
        # decode default is NOT the 0-member — the original consensus
        # priority bug, now visible through the field-level emitter
        src = """
            KIND_RAW = 0
            KIND_COMMIT = 1
            KIND_NAMES = {KIND_RAW: "raw", KIND_COMMIT: "commit"}

            class VerifyRequest:
                kind: int = KIND_COMMIT

            def encode_varint_field(field, v):
                return b""

            def encode(req):
                out = b""
                if req.kind:
                    out += encode_varint_field(1, req.kind)
                return out
        """
        found = self.run(src)
        assert codes(found) == ["TPW001"]
        assert "KIND_RAW" in found[0].message

    def test_route_epoch_without_reestablishment_flagged(self):
        # dirty twin of the federation routing-epoch field (protocol
        # field 10): zero-omitted on encode, so a pre-federation frame
        # (field absent) MUST decode back to exactly 0 — a decoder that
        # only assigns what it read re-encodes absent as present-zero
        src = """
            def encode_varint_field(field, v):
                return b""

            def encode(req):
                out = b""
                if req.route_epoch:
                    out += encode_varint_field(10, req.route_epoch)
                return out

            def decode(r, req):
                req.route_epoch = r.read_varint()
                return req
        """
        found = self.run(src)
        assert codes(found) == ["TPW004"]
        assert "route_epoch" in found[0].message

    def test_route_epoch_with_or_zero_passes(self):
        # clean twin: the real protocol.py shape — explicit `or 0`
        # re-establishment after the read
        src = """
            def encode_varint_field(field, v):
                return b""

            def encode(req):
                out = b""
                if req.route_epoch:
                    out += encode_varint_field(10, req.route_epoch)
                return out

            def decode(r, req):
                req.route_epoch = r.read_varint()
                req.route_epoch = req.route_epoch or 0
                return req
        """
        assert self.run(src) == []

    def test_shifted_shard_id_emit_stays_clean(self):
        # the federation shard-id field (protocol field 9) rides a +1
        # wire shift under a `>= 0` guard so shard 0 survives zero
        # omission; the shifted emit is not a raw-attr emit, so neither
        # the varint zero-omission leg nor the enum-default leg may
        # misread it as an unguarded field
        src = """
            class VerifyRequest:
                shard_id: int = -1

            def encode_varint_field(field, v):
                return b""

            def encode(req):
                out = b""
                if req.shard_id >= 0:
                    out += encode_varint_field(9, req.shard_id + 1)
                return out

            def decode(r, req):
                req.shard_id = r.read_varint() - 1
                return req
        """
        assert self.run(src) == []


SLAB_DIRTY = """
    SLAB_OFF_GEN = 0
    SLAB_OFF_KLASS = 8
    SLAB_OFF_LANES = 20
    SLAB_OFF_GEN2 = 92

    def pack_header(buf, base, gen, klass, lanes):
        struct.pack_into("<I", buf, base + SLAB_OFF_KLASS, klass + 1)
        struct.pack_into("<I", buf, base + SLAB_OFF_LANES, lanes)
        struct.pack_into("<I", buf, base + SLAB_OFF_GEN2, gen)
        struct.pack_into("<I", buf, base + SLAB_OFF_GEN, gen)

    def unpack_header(buf, base):
        (gen,) = struct.unpack_from("<I", buf, base + SLAB_OFF_GEN)
        (raw_klass,) = struct.unpack_from("<I", buf, base + SLAB_OFF_KLASS)
        (gen2,) = struct.unpack_from("<I", buf, base + SLAB_OFF_GEN2)
        return gen, raw_klass - 1, gen2
"""

SLAB_CLEAN = """
    SLAB_OFF_GEN = 0
    SLAB_OFF_KLASS = 8
    SLAB_OFF_LANES = 20
    SLAB_OFF_GEN2 = 92

    def pack_header(buf, base, gen, klass, lanes):
        struct.pack_into("<I", buf, base + SLAB_OFF_KLASS, klass + 1)
        struct.pack_into("<I", buf, base + SLAB_OFF_LANES, lanes)
        struct.pack_into("<I", buf, base + SLAB_OFF_GEN2, gen)
        struct.pack_into("<I", buf, base + SLAB_OFF_GEN, gen)

    def unpack_header(buf, base):
        (gen,) = struct.unpack_from("<I", buf, base + SLAB_OFF_GEN)
        (raw_klass,) = struct.unpack_from("<I", buf, base + SLAB_OFF_KLASS)
        (lanes,) = struct.unpack_from("<I", buf, base + SLAB_OFF_LANES)
        (gen2,) = struct.unpack_from("<I", buf, base + SLAB_OFF_GEN2)
        return gen, raw_klass - 1, lanes, gen2
"""


class TestSlabHeaderSymmetry:
    def run(self, src):
        return run_on(
            WireCompatChecker(), {"tendermint_tpu/verifyd/shm.py": src}
        )

    def test_field_unpacked_but_never_packed_flagged(self):
        found = self.run(SLAB_DIRTY)
        assert codes(found) == ["TPW005"]
        assert "SLAB_OFF_LANES" in found[0].message
        assert "unpack_header" in found[0].message

    def test_symmetric_codec_passes(self):
        assert self.run(SLAB_CLEAN) == []

    def test_missing_unpack_header_flagged(self):
        src = """
            SLAB_OFF_GEN = 0

            def pack_header(buf, base, gen):
                struct.pack_into("<I", buf, base + SLAB_OFF_GEN, gen)
        """
        found = self.run(src)
        assert codes(found) == ["TPW005"]
        assert "unpack_header" in found[0].message

    def test_undefined_offset_reference_flagged(self):
        src = """
            SLAB_OFF_GEN = 0

            def pack_header(buf, base, gen):
                struct.pack_into("<I", buf, base + SLAB_OFF_GEN, gen)
                struct.pack_into("<I", buf, base + SLAB_OFF_GENN, gen)

            def unpack_header(buf, base):
                return struct.unpack_from("<I", buf, base + SLAB_OFF_GEN)
        """
        found = self.run(src)
        assert codes(found) == ["TPW005"]
        assert "SLAB_OFF_GENN" in found[0].message

    def test_protocol_module_without_slab_codec_not_flagged(self):
        # the TCP codec module defines no SLAB_OFF_ layout: TPW005 is
        # inert there rather than demanding slab functions everywhere
        found = run_on(
            WireCompatChecker(),
            {"tendermint_tpu/verifyd/protocol.py": "KIND_RAW = 1\n"},
        )
        assert found == []

    def test_v4_routing_slot_unpacked_but_never_packed_flagged(self):
        # dirty twin of the slab-header v4 federation slots: a reader
        # that learns SLAB_OFF_ROUTE_EPOCH while the writer never
        # stamps it would ship uninitialized slab bytes as an epoch
        src = """
            SLAB_OFF_GEN = 0
            SLAB_OFF_SHARD_ID = 116
            SLAB_OFF_ROUTE_EPOCH = 120

            def pack_header(buf, base, gen, shard_id):
                struct.pack_into("<I", buf, base + SLAB_OFF_SHARD_ID, shard_id + 1)
                struct.pack_into("<I", buf, base + SLAB_OFF_GEN, gen)

            def unpack_header(buf, base):
                (gen,) = struct.unpack_from("<I", buf, base + SLAB_OFF_GEN)
                (raw,) = struct.unpack_from("<I", buf, base + SLAB_OFF_SHARD_ID)
                (epoch,) = struct.unpack_from("<I", buf, base + SLAB_OFF_ROUTE_EPOCH)
                return gen, raw - 1, epoch
        """
        found = self.run(src)
        assert codes(found) == ["TPW005"]
        assert "SLAB_OFF_ROUTE_EPOCH" in found[0].message

    def test_real_shm_module_is_clean(self):
        import pathlib

        src = (
            pathlib.Path(__file__).resolve().parents[1]
            / "tendermint_tpu"
            / "verifyd"
            / "shm.py"
        ).read_text()
        found = run_on(
            WireCompatChecker(), {"tendermint_tpu/verifyd/shm.py": src}
        )
        assert [f for f in found if f.code == "TPW005"] == []


# --- hygiene -----------------------------------------------------------------


class TestHygiene:
    def test_bare_except(self):
        src = """
            try:
                x = 1
            except:
                x = 2
        """
        found = run_on(HygieneChecker(), {"m.py": src})
        assert codes(found) == ["TPH001"]

    def test_silent_pass_without_comment(self):
        src = """
            try:
                x = 1
            except ValueError:
                pass
        """
        found = run_on(HygieneChecker(), {"m.py": src})
        assert codes(found) == ["TPH002"]

    def test_commented_pass_is_fine(self):
        src = """
            try:
                x = 1
            except ValueError:
                pass  # best-effort: unparsable input keeps the default
        """
        assert run_on(HygieneChecker(), {"m.py": src}) == []

    def test_non_daemon_unjoined_thread(self):
        src = """
            import threading
            t = threading.Thread(target=print)
            t.start()
        """
        found = run_on(HygieneChecker(), {"m.py": src})
        assert codes(found) == ["TPH003"]

    def test_daemon_thread_is_fine(self):
        src = """
            import threading
            t = threading.Thread(target=print, daemon=True)
            t.start()
        """
        assert run_on(HygieneChecker(), {"m.py": src}) == []

    def test_joined_thread_is_fine(self):
        src = """
            import threading
            t = threading.Thread(target=print)
            t.start()
            t.join()
        """
        assert run_on(HygieneChecker(), {"m.py": src}) == []

    def test_fstring_into_logger(self):
        src = """
            def f(logger, n):
                logger.info(f"flushed {n} lanes")
        """
        found = run_on(HygieneChecker(), {"m.py": src})
        assert codes(found) == ["TPH004"]

    def test_kv_logging_is_fine(self):
        src = """
            def f(logger, n):
                logger.info("flushed", lanes=n)
        """
        assert run_on(HygieneChecker(), {"m.py": src}) == []


# --- metrics -----------------------------------------------------------------


METRICS_REL = "tendermint_tpu/libs/metrics.py"


class TestMetricsChecks:
    def test_dead_instrument(self):
        metrics_src = """
            NAMESPACE = "tendermint"

            def _name(s, n):
                return f"tendermint_{s}_{n}"

            class M:
                def __init__(self, reg):
                    s = "demo"
                    self.used = reg.counter(_name(s, "used_total"), "h")
                    self.dead = reg.counter(_name(s, "dead_total"), "h")
        """
        user_src = """
            def f(m):
                m.used.inc()
        """
        found = run_on(
            MetricsChecker(),
            {METRICS_REL: metrics_src, "tendermint_tpu/ops/u.py": user_src},
        )
        assert codes(found) == ["TPM001"]
        assert "dead" in found[0].message

    def test_bad_name(self):
        metrics_src = """
            class M:
                def __init__(self, reg):
                    self.x = reg.counter("Bad-Name", "h")
        """
        user_src = """
            def f(m):
                m.x.inc()
        """
        found = run_on(
            MetricsChecker(),
            {METRICS_REL: metrics_src, "tendermint_tpu/ops/u.py": user_src},
        )
        assert codes(found) == ["TPM002"]

    EXEMPLAR_METRICS = """
        class M:
            def __init__(self, reg):
                self.lat = reg.histogram("tendermint_demo_lat", "h")
                self.hits = reg.counter("tendermint_demo_hits", "h")
    """

    def test_exemplar_on_histogram_passes(self):
        user_src = """
            def f(m, tid):
                m.hits.inc()
                m.lat.labels(stage="device").observe(
                    0.1, exemplar={"trace_id": tid}
                )
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "tendermint_tpu/ops/u.py": user_src,
            },
        )
        assert found == []

    def test_exemplar_on_undeclared_instrument(self):
        # the reverse of TPM001: call site survives a declaration rename
        user_src = """
            def f(m, tid):
                m.hits.inc()
                m.lat.labels(stage="x").observe(0.1, exemplar=None)
                m.lat_renamed.observe(0.1, exemplar={"trace_id": tid})
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "tendermint_tpu/ops/u.py": user_src,
            },
        )
        assert codes(found) == ["TPM003"]
        assert "lat_renamed" in found[0].message

    def test_exemplar_on_counter_flagged(self):
        user_src = """
            def f(m, tid):
                m.lat.observe(0.1)
                m.hits.observe(1.0, exemplar={"trace_id": tid})
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "tendermint_tpu/ops/u.py": user_src,
            },
        )
        assert codes(found) == ["TPM003"]
        assert "counter" in found[0].message

    def test_exemplar_on_local_alias_skipped(self):
        # a bare-name base is not statically resolvable; stay quiet
        user_src = """
            def f(m, tid):
                m.hits.inc()
                m.lat.observe(0.0)
                h = object()
                h.observe(0.1, exemplar={"trace_id": tid})
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "tendermint_tpu/ops/u.py": user_src,
            },
        )
        assert found == []

    def test_raw_bucket_label_flagged(self):
        # TPM004: a raw str(n) mints one label value per batch size
        user_src = """
            def f(m, n):
                m.hits.inc()
                m.lat.labels(engine="ed25519", bucket=str(n)).observe(0.1)
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "tendermint_tpu/ops/u.py": user_src,
            },
        )
        assert codes(found) == ["TPM004"]
        assert "bucket_label" in found[0].message

    def test_bucket_label_routed_passes(self):
        # direct call and a local name assigned from it are both blessed
        user_src = """
            from tendermint_tpu.ops.introspect import bucket_label

            def direct(m, n):
                m.hits.inc()
                m.lat.labels(bucket=bucket_label(n)).observe(0.1)

            def via_local(m, introspect, n):
                bucket = introspect.bucket_label(n)
                m.lat.labels(engine="sr25519", bucket=bucket).observe(0.2)
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "tendermint_tpu/ops/u.py": user_src,
            },
        )
        assert found == []

    def test_bucket_outside_package_ignored(self):
        # the cardinality rule is about the package's exposition; bench
        # helpers and scripts can label however they like
        user_src = """
            def f(m, n):
                m.hits.inc()
                m.lat.observe(0.1)
                m.lat.labels(bucket=str(n)).observe(0.1)
        """
        found = run_on(
            MetricsChecker(),
            {
                METRICS_REL: self.EXEMPLAR_METRICS,
                "bench/helper.py": user_src,
            },
        )
        assert found == []


# --- framework mechanics -----------------------------------------------------


class TestFramework:
    def test_inline_suppression(self):
        src = """
            try:
                x = 1
            except:  # tpulint: disable=TPH001
                x = 2
        """
        assert run_on(HygieneChecker(), {"m.py": src}) == []

    def test_render_shape(self):
        f = Finding("a/b.py", 12, "TPX001", "boom")
        assert f.render() == "a/b.py:12: TPX001 boom"
        assert f.baseline_key() == "a/b.py: TPX001 boom"

    def test_baseline_roundtrip(self, tmp_path):
        path = str(tmp_path / "baseline.txt")
        (tmp_path / "a.py").write_text("")  # keys for live files survive
        f1 = Finding("a.py", 1, "TPH002", "x")
        f2 = Finding("a.py", 9, "TPH002", "x")  # same key, twice
        write_baseline(path, [f1, f2])
        baseline = load_baseline(path, repo_root=str(tmp_path))
        new, stale = diff_baseline([f1, f2], baseline)
        assert new == [] and stale == []
        # a third identical finding is NEW (multiset semantics)
        f3 = Finding("a.py", 20, "TPH002", "x")
        new, stale = diff_baseline([f1, f2, f3], baseline)
        assert len(new) == 1 and stale == []
        # fixing one leaves a stale entry to prune
        new, stale = diff_baseline([f1], baseline)
        assert new == [] and stale == ["a.py: TPH002 x"]

    def test_line_drift_does_not_unbaseline(self, tmp_path):
        path = str(tmp_path / "baseline.txt")
        (tmp_path / "a.py").write_text("")
        write_baseline(path, [Finding("a.py", 10, "TPH002", "x")])
        moved = Finding("a.py", 999, "TPH002", "x")
        new, stale = diff_baseline(
            [moved], load_baseline(path, repo_root=str(tmp_path))
        )
        assert new == [] and stale == []

    def test_baseline_prunes_deleted_files(self, tmp_path):
        # an entry whose file is gone is dropped at load (reported via
        # the pruned list), not kept as permanent dead weight
        path = str(tmp_path / "baseline.txt")
        (tmp_path / "live.py").write_text("")
        write_baseline(path, [
            Finding("live.py", 1, "TPH002", "x"),
            Finding("deleted.py", 1, "TPH002", "y"),
        ])
        pruned = []
        baseline = load_baseline(
            path, repo_root=str(tmp_path), pruned=pruned
        )
        assert pruned == ["deleted.py: TPH002 y"]
        assert list(baseline) == ["live.py: TPH002 x"]

    def test_registry_covers_all_families(self):
        reg = checker_registry()
        assert set(reg) == {
            "locks", "jaxpurity", "wire", "hygiene", "metrics", "taint",
        }

    def test_comment_in_string_is_not_an_annotation(self):
        src = '''
            class S:
                def __init__(self):
                    self.x = "text with # guarded-by: _mtx inside"
        '''
        assert run_on(LockDisciplineChecker(), {"m.py": src}) == []


# --- taint (tpuflow) ---------------------------------------------------------


# fixtures live at a surface path so their read_* calls count as sources
_SURF = "tendermint_tpu/verifyd/protocol.py"
# a non-surface module: sinks here still fire when taint FLOWS in, but
# its own read_*/unpack calls are trusted local data
_SINK = "tendermint_tpu/verifyd/server.py"


TAINT_ALLOC_DIRTY = """
    def decode(r):
        n = r.read_varint()
        return bytearray(n)
"""

TAINT_ALLOC_CLEAN = """
    def decode(r):
        n = r.read_varint()
        if n > 4096:
            raise ValueError("length too large")
        return bytearray(n)
"""

TAINT_BLOCK_DIRTY = """
    def handle(r, done):
        t = r.read_varint()
        done.wait(timeout=t)
"""

TAINT_BLOCK_CLEAN = """
    def handle(r, done):
        t = r.read_varint()
        t = min(t, 60)
        done.wait(timeout=t)
"""

TAINT_LOOP_DIRTY = """
    def drain(r):
        n = r.read_varint()
        out = []
        for _ in range(n):
            out.append(r.read_bytes())
        return out
"""

TAINT_LOOP_CLEAN = """
    def drain(r):
        n = r.read_varint()
        if n > 64:
            raise ValueError("too many entries")
        out = []
        for _ in range(n):
            out.append(r.read_bytes())
        return out
"""

TAINT_KEY_DIRTY = """
    def ingest(r):
        table = {}
        key = r.read_bytes()
        table[key] = 1
        return table
"""

TAINT_KEY_CLEAN = """
    def ingest(r):
        table = {}
        key = r.read_bytes()
        if len(table) < 100:
            table[key] = 1
        return table
"""

# the cross-module flow the checker exists for: a decode helper in a
# surface module returns wire data, a server module spends it on a
# blocking wait
TAINT_INTER_SURFACE = """
    def read_deadline(r):
        return r.read_varint()
"""

TAINT_INTER_SINK_DIRTY = """
    from tendermint_tpu.verifyd.protocol import read_deadline

    def serve(r, done):
        t = read_deadline(r)
        done.wait(timeout=t)
"""

TAINT_INTER_SINK_CLEAN = """
    from tendermint_tpu.verifyd.protocol import read_deadline

    def serve(r, done):
        t = read_deadline(r)
        if t > 600:
            raise ValueError("deadline too far out")
        done.wait(timeout=t)
"""

TAINT_ANNOT_USED = """
    def decode(r):
        n = r.read_varint()
        # tpuflow: sanitized=caller enforces the frame cap upstream
        return bytearray(n)
"""

TAINT_ANNOT_STALE = """
    def decode(r):
        n = 4
        # tpuflow: sanitized=nothing tainted reaches this line anymore
        return bytearray(n)
"""

TAINT_ANNOT_MALFORMED = """
    def decode(r):
        n = r.read_varint()
        if n > 4096:
            raise ValueError("length too large")
        # tpuflow: sanitized=
        return bytearray(n)
"""


class TestTaint:
    def test_tainted_alloc_size_flags(self):
        findings = run_on(TaintChecker(), {_SURF: TAINT_ALLOC_DIRTY})
        assert codes(findings) == ["TPT001"]

    def test_range_guard_clears_alloc_size(self):
        assert run_on(TaintChecker(), {_SURF: TAINT_ALLOC_CLEAN}) == []

    def test_tainted_blocking_bound_flags(self):
        findings = run_on(TaintChecker(), {_SURF: TAINT_BLOCK_DIRTY})
        assert codes(findings) == ["TPT002"]

    def test_min_clamp_clears_blocking_bound(self):
        assert run_on(TaintChecker(), {_SURF: TAINT_BLOCK_CLEAN}) == []

    def test_tainted_loop_bound_flags(self):
        findings = run_on(TaintChecker(), {_SURF: TAINT_LOOP_DIRTY})
        assert "TPT002" in codes(findings)

    def test_range_guard_clears_loop_bound(self):
        assert run_on(TaintChecker(), {_SURF: TAINT_LOOP_CLEAN}) == []

    def test_tainted_key_grows_mapping_flags(self):
        findings = run_on(TaintChecker(), {_SURF: TAINT_KEY_DIRTY})
        assert codes(findings) == ["TPT003"]

    def test_cardinality_cap_clears_mapping_growth(self):
        assert run_on(TaintChecker(), {_SURF: TAINT_KEY_CLEAN}) == []

    def test_interprocedural_taint_crosses_modules(self):
        findings = run_on(TaintChecker(), {
            _SURF: TAINT_INTER_SURFACE,
            _SINK: TAINT_INTER_SINK_DIRTY,
        })
        assert codes(findings) == ["TPT002"]
        assert findings[0].path == _SINK

    def test_interprocedural_guard_at_callsite_clears(self):
        assert run_on(TaintChecker(), {
            _SURF: TAINT_INTER_SURFACE,
            _SINK: TAINT_INTER_SINK_CLEAN,
        }) == []

    def test_sources_only_fire_in_surface_modules(self):
        # the same dirty code in a NON-surface module reads trusted
        # local bytes: no taint, no findings
        assert run_on(TaintChecker(), {_SINK: TAINT_ALLOC_DIRTY}) == []

    def test_annotation_suppresses_and_counts_as_used(self):
        assert run_on(TaintChecker(), {_SURF: TAINT_ANNOT_USED}) == []

    def test_stale_annotation_flags_tpt004(self):
        findings = run_on(TaintChecker(), {_SURF: TAINT_ANNOT_STALE})
        assert codes(findings) == ["TPT004"]
        assert "stale" in findings[0].message

    def test_malformed_annotation_flags_tpt004(self):
        findings = run_on(TaintChecker(), {_SURF: TAINT_ANNOT_MALFORMED})
        assert codes(findings) == ["TPT004"]
        assert "malformed" in findings[0].message


# --- the repo itself ---------------------------------------------------------


class TestRepoPasses:
    def test_repo_passes_with_baseline(self, capsys):
        from scripts.analysis.__main__ import main

        rc = main([])
        out = capsys.readouterr().out
        assert rc == 0, f"tpulint found new findings:\n{out}"

    def test_annotated_files_have_guards(self):
        # the ISSUE's seed files must actually carry annotations
        import os

        from scripts.analysis.core import REPO_ROOT

        seeded = [
            "tendermint_tpu/crypto/scheduler.py",
            "tendermint_tpu/verifyd/server.py",
            "tendermint_tpu/ops/device_policy.py",
            "tendermint_tpu/ops/precompute.py",
            "tendermint_tpu/libs/tracing.py",
            "tendermint_tpu/libs/metrics.py",
        ]
        for rel in seeded:
            with open(os.path.join(REPO_ROOT, rel)) as fh:
                assert "guarded-by:" in fh.read(), f"{rel} lost its annotations"
