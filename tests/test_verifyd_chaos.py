"""Serving-tier chaos battery for verifyd.

Injects the failure modes a long-lived verification daemon actually
meets — device faults mid-dispatch, clients dying mid-frame, slow
readers, tenant floods, a kill/restart under load — and pins the
degradation contract:

- consensus-class verification NEVER silently drops: its worst case is
  the host oracle (brownout ladder rung 5), not a loss;
- every rejected request gets an explicit wire status, never silence;
- one tenant's flood cannot destroy another tenant's latency: the
  victim's p99 stays within 3x its unloaded p99 (floored, so a fast
  machine doesn't make the bound vacuous);
- continuous batching demonstrably overlaps admission with the
  in-flight kernel (trace-span containment proves it).
"""

import os
import socket
import threading
import time

import pytest

from tendermint_tpu.crypto.ed25519_ref import verify_zip215
from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.grpc import PREFACE
from tendermint_tpu.ops.fault_injection import DeviceFault
from tendermint_tpu.verifyd import protocol, shm
from tendermint_tpu.verifyd.client import (
    VerifydClient,
    VerifydRejectedError,
    VerifydUnavailableError,
)
from tendermint_tpu.verifyd.server import (
    LEVEL_HOST_CONSENSUS,
    LEVEL_NORMAL,
    LEVEL_SHED_BLOCKSYNC,
    LEVEL_SHED_LIGHT,
    LEVEL_SHED_RPC,
    LEVEL_SHRINK_SHARES,
    BrownoutController,
    VerifydServer,
    level_sheds_class,
)


def host_verify(pks, msgs, sigs):
    return [verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]


def make_lanes(n, seed=0, bad=()):
    priv = Ed25519PrivKey.from_seed(bytes([seed] * 32))
    pk = priv.pub_key().bytes()
    msgs = [b"chaos-%d-%d" % (seed, i) for i in range(n)]
    sigs = [
        bytes(64) if i in bad else priv.sign(m) for i, m in enumerate(msgs)
    ]
    return [pk] * n, msgs, sigs


# --- ladder semantics (unit) -------------------------------------------------


def test_ladder_shed_order_and_consensus_immunity():
    """rpc sheds first, light second, blocksync last; consensus at NO
    rung — the declared degradation order, mechanically."""
    first_shed = {}
    for klass in (
        protocol.CLASS_RPC,
        protocol.CLASS_LIGHT,
        protocol.CLASS_BLOCKSYNC,
    ):
        for level in range(LEVEL_HOST_CONSENSUS + 1):
            if level_sheds_class(level, klass):
                first_shed[klass] = level
                break
    assert first_shed[protocol.CLASS_RPC] == LEVEL_SHED_RPC
    assert first_shed[protocol.CLASS_LIGHT] == LEVEL_SHED_LIGHT
    assert first_shed[protocol.CLASS_BLOCKSYNC] == LEVEL_SHED_BLOCKSYNC
    assert (
        first_shed[protocol.CLASS_RPC]
        < first_shed[protocol.CLASS_LIGHT]
        < first_shed[protocol.CLASS_BLOCKSYNC]
    )
    for level in range(LEVEL_HOST_CONSENSUS + 1):
        assert not level_sheds_class(level, protocol.CLASS_CONSENSUS)


def test_brownout_escalates_on_sustained_pressure_and_recovers():
    """Synthetic clock: pressure sustained past escalate_after climbs
    exactly one rung per window; calm descends one per recover_after."""
    b = BrownoutController(
        escalate_after=0.1, recover_after=0.2, cooldown_fn=None
    )
    assert b.observe(True, now=0.0) == (LEVEL_NORMAL, 0)
    assert b.observe(True, now=0.05) == (LEVEL_NORMAL, 0)  # not sustained yet
    assert b.observe(True, now=0.11) == (LEVEL_SHED_RPC, 1)
    assert b.observe(True, now=0.15) == (LEVEL_SHED_RPC, 0)  # clock restarted
    assert b.observe(True, now=0.22) == (LEVEL_SHED_LIGHT, 1)
    # one blip of calm does not recover...
    assert b.observe(False, now=0.3) == (LEVEL_SHED_LIGHT, 0)
    # ...sustained calm walks back down one rung per window
    assert b.observe(False, now=0.51) == (LEVEL_SHED_RPC, -1)
    assert b.observe(False, now=0.72) == (LEVEL_NORMAL, -1)
    assert b.observe(False, now=1.0) == (LEVEL_NORMAL, 0)
    assert b.transitions == {"up": 2, "down": 2}


def test_brownout_never_escalates_past_top_rung():
    b = BrownoutController(escalate_after=0.01, cooldown_fn=None)
    t = 0.0
    for _ in range(20):
        t += 0.02
        b.observe(True, now=t)
    assert b.level == LEVEL_HOST_CONSENSUS


def test_device_cooldown_pins_host_consensus():
    cooling = [False]
    b = BrownoutController(cooldown_fn=lambda: cooling[0])
    assert b.effective() == LEVEL_NORMAL
    cooling[0] = True
    assert b.effective() == LEVEL_HOST_CONSENSUS  # load-independent pin
    assert b.level == LEVEL_NORMAL  # the organic level is untouched
    cooling[0] = False
    assert b.effective() == LEVEL_NORMAL


# --- ladder rungs over the wire ----------------------------------------------


def _client(addr, **kw):
    kw.setdefault("fallback", False)
    kw.setdefault("shed_retries", 0)
    return VerifydClient(addr, **kw)


def test_forced_rungs_shed_classes_in_order_over_the_wire():
    srv = VerifydServer(verify_fn=host_verify, max_batch=16, max_delay=0.005)
    srv.start()
    try:
        h, p = srv.address
        addr = f"{h}:{p}"
        expectations = [
            (LEVEL_SHED_RPC, {protocol.CLASS_RPC}),
            (LEVEL_SHED_LIGHT, {protocol.CLASS_RPC, protocol.CLASS_LIGHT}),
            (
                LEVEL_SHED_BLOCKSYNC,
                {
                    protocol.CLASS_RPC,
                    protocol.CLASS_LIGHT,
                    protocol.CLASS_BLOCKSYNC,
                },
            ),
        ]
        for level, shed_classes in expectations:
            srv.brownout.force(level)
            for klass in (
                protocol.CLASS_RPC,
                protocol.CLASS_LIGHT,
                protocol.CLASS_BLOCKSYNC,
                protocol.CLASS_CONSENSUS,
            ):
                c = _client(addr)
                if klass in shed_classes:
                    with pytest.raises(VerifydRejectedError) as ei:
                        c.verify(*make_lanes(2, seed=level), klass=klass)
                    assert (
                        ei.value.status
                        == protocol.STATUS_RESOURCE_EXHAUSTED
                    )
                    assert "brownout" in str(ei.value)
                else:
                    got = c.verify(*make_lanes(2, seed=level), klass=klass)
                    assert got == [True, True]
                c.close()
        srv.brownout.force(None)
    finally:
        srv.stop()


def test_host_consensus_rung_survives_a_dead_device():
    """Rung 5: the device path is GONE (verify_fn raises on every call)
    yet consensus still answers correct verdicts via the host oracle,
    with correct bad-lane attribution; sheddable classes shed."""

    def dead_device(pks, msgs, sigs):
        raise DeviceFault("chip fell off the bus", permanent=True)

    srv = VerifydServer(verify_fn=dead_device, max_batch=16, max_delay=0.005)
    srv.brownout.force(LEVEL_HOST_CONSENSUS)
    srv.start()
    try:
        h, p = srv.address
        c = _client(f"{h}:{p}", tenant="chain-a")
        got = c.verify(
            *make_lanes(4, seed=9, bad={2}), klass=protocol.CLASS_CONSENSUS
        )
        assert got == [True, True, False, True]
        with pytest.raises(VerifydRejectedError):
            c.verify(*make_lanes(2, seed=9), klass=protocol.CLASS_RPC)
        c.close()
        assert srv.stats()["host_direct_lanes"] == 4
        stats = srv.tenant_stats()["chain-a"]
        assert stats["host_direct"] == 4
        assert stats["sheds"] == 1
    finally:
        srv.stop()


def test_shrink_shares_rung_host_directs_consensus_past_share():
    """Rung 4: budgets shrink to 1/4; consensus PAST the shrunken share
    is never shed — it verifies host-direct instead."""
    gate = threading.Event()
    in_flight = threading.Event()

    def gated(pks, msgs, sigs):
        in_flight.set()
        gate.wait(10)
        return host_verify(pks, msgs, sigs)

    # tenant_cap 8 -> shrunken share 2
    srv = VerifydServer(
        verify_fn=gated, max_batch=16, max_delay=0.005, tenant_cap=8
    )
    srv.brownout.force(LEVEL_SHRINK_SHARES)
    srv.start()
    try:
        h, p = srv.address
        results = {}
        # 2 consensus lanes occupy the full shrunken share (gated)
        t1 = threading.Thread(
            target=lambda: results.__setitem__(
                "first",
                _client(f"{h}:{p}", tenant="chain-a").verify(
                    *make_lanes(2, seed=3), klass=protocol.CLASS_CONSENSUS
                ),
            )
        )
        t1.start()
        assert in_flight.wait(timeout=5)
        deadline = time.monotonic() + 5
        while (
            srv.tenant_stats().get("chain-a", {}).get("depth", 0) < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.002)
        # consensus past the share: host-direct (blocked flush bypassed)
        c2 = _client(f"{h}:{p}", tenant="chain-a")
        got = c2.verify(
            *make_lanes(3, seed=4, bad={1}), klass=protocol.CLASS_CONSENSUS
        )
        assert got == [True, False, True]
        assert srv.stats()["host_direct_lanes"] == 3
        c2.close()
        gate.set()
        t1.join(timeout=10)
        assert results["first"] == [True, True]
    finally:
        gate.set()
        srv.stop()


# --- fault injection mid-dispatch --------------------------------------------


def test_device_fault_mid_dispatch_zero_silent_drops():
    """DeviceFault raised INSIDE a flush: the scheduler's fallback
    verifies the same lanes on the host oracle — every concurrent
    caller gets correct verdicts, nobody hangs, nobody is dropped."""
    fail_once = [True]

    def flaky(pks, msgs, sigs):
        if fail_once[0]:
            fail_once[0] = False
            raise DeviceFault("injected mid-dispatch")
        return host_verify(pks, msgs, sigs)

    srv = VerifydServer(verify_fn=flaky, max_batch=64, max_delay=0.02)
    srv.start()
    try:
        h, p = srv.address
        results = {}
        errors = []

        def call(i):
            try:
                c = _client(f"{h}:{p}")
                bad = {1} if i % 2 else ()
                results[i] = (
                    c.verify(*make_lanes(3, seed=i, bad=bad)),
                    bad,
                )
                c.close()
            except Exception as exc:
                errors.append((i, exc))

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert not errors, errors
        assert len(results) == 6  # zero drops
        for i, (got, bad) in results.items():
            want = [j not in bad for j in range(3)]
            assert got == want, (i, got)
        # flush threads may still be unwinding: locked snapshot only
        sstats = srv.scheduler.stats()
        assert sstats["flush_errors"] >= 1
        assert sstats["fallback_flushes"] >= 1  # the fault was absorbed
    finally:
        srv.stop()


def test_permanent_device_fault_every_flush_still_answers():
    def dead(pks, msgs, sigs):
        raise DeviceFault("permanently dead", permanent=True)

    srv = VerifydServer(verify_fn=dead, max_batch=8, max_delay=0.005)
    srv.start()
    try:
        h, p = srv.address
        c = _client(f"{h}:{p}")
        for i in range(3):
            assert c.verify(*make_lanes(2, seed=20 + i, bad={0})) == [
                False,
                True,
            ]
        c.close()
        assert srv.scheduler.stats()["fallback_flushes"] >= 3
    finally:
        srv.stop()


# --- connection chaos --------------------------------------------------------


def test_mid_frame_disconnect_leaves_server_serving():
    """Clients that die mid-preface or mid-frame must not wedge the
    event loop or leak their connection into other requests."""
    srv = VerifydServer(verify_fn=host_verify, max_batch=8, max_delay=0.005)
    srv.start()
    try:
        h, p = srv.address
        # half a preface, then gone
        s1 = socket.create_connection((h, p), timeout=2)
        s1.sendall(PREFACE[: len(PREFACE) // 2])
        s1.close()
        # full preface then a torn frame header, then gone
        s2 = socket.create_connection((h, p), timeout=2)
        s2.sendall(PREFACE + b"\x00\x00\x40\x00")  # length says 64, sends 0
        s2.close()
        # garbage that is not HTTP/2 at all
        s3 = socket.create_connection((h, p), timeout=2)
        s3.sendall(b"GET / HTTP/1.1\r\n\r\n")
        s3.close()
        # the server still answers real clients promptly
        c = _client(f"{h}:{p}")
        assert c.verify(*make_lanes(3, seed=30, bad={1})) == [
            True, False, True,
        ]
        c.close()
    finally:
        srv.stop()


def test_slow_reader_does_not_stall_other_clients():
    """A connection that completes the preface and then goes silent
    (slowloris-style) must not block service to healthy clients."""
    srv = VerifydServer(verify_fn=host_verify, max_batch=8, max_delay=0.005)
    srv.start()
    stalled = []
    try:
        h, p = srv.address
        for _ in range(3):
            s = socket.create_connection((h, p), timeout=2)
            s.sendall(PREFACE)  # then... nothing, ever
            stalled.append(s)
        t0 = time.monotonic()
        c = _client(f"{h}:{p}")
        assert c.verify(*make_lanes(4, seed=31)) == [True] * 4
        c.close()
        assert time.monotonic() - t0 < 5.0
    finally:
        for s in stalled:
            s.close()
        srv.stop()


def test_kill_and_restart_under_continuous_load():
    """The server dies and comes back on the same port while clients
    keep submitting: every call either succeeds (possibly via retry) or
    fails EXPLICITLY — no hangs, no silent losses."""
    srv = VerifydServer(verify_fn=host_verify, max_batch=16, max_delay=0.005)
    srv.start()
    h, p = srv.address
    outcomes = []
    outcomes_mtx = threading.Lock()
    stop_flag = threading.Event()

    def loader(i):
        c = VerifydClient(
            f"{h}:{p}", retries=8, backoff=0.05, fallback=False
        )
        while not stop_flag.is_set():
            try:
                got = c.verify(*make_lanes(2, seed=40 + i))
                outcome = "ok" if got == [True, True] else "bad"
            except (VerifydUnavailableError, VerifydRejectedError):
                outcome = "explicit_error"
            with outcomes_mtx:
                outcomes.append(outcome)
            time.sleep(0.02)
        c.close()

    threads = [threading.Thread(target=loader, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    srv2 = None
    try:
        time.sleep(0.2)  # load established
        srv.stop()  # chaos: the daemon dies under load
        time.sleep(0.3)
        srv2 = VerifydServer(
            verify_fn=host_verify, host=h, port=p,
            max_batch=16, max_delay=0.005,
        )
        srv2.start()  # ...and comes back on the same port
        time.sleep(0.5)
        stop_flag.set()
        for t in threads:
            t.join(timeout=15)
        with outcomes_mtx:
            snapshot = list(outcomes)
        assert len(snapshot) == sum(
            1 for o in snapshot if o in ("ok", "explicit_error")
        )  # every call resolved explicitly, none vanished
        assert snapshot.count("ok") >= 4  # service genuinely resumed
        assert "bad" not in snapshot
        # post-restart requests land on the new instance
        assert srv2.stats()["requests_served"] >= 1
    finally:
        stop_flag.set()
        for t in threads:
            t.join(timeout=5)
        if srv2 is not None:
            srv2.stop()


# --- tenant flood isolation (acceptance) -------------------------------------


def test_tenant_flood_victim_p99_and_explicit_sheds():
    """An aggressor tenant floods rpc traffic; the victim tenant's
    consensus p99 stays within 3x its unloaded p99 (floored at 50ms so
    a fast box doesn't make the bound vacuous), every aggressor request
    resolves explicitly, and the aggressor's sheds stay in ITS bucket."""

    def modeled(pks, msgs, sigs):
        time.sleep(0.0003 * len(pks))  # modeled device: ~0.3ms/lane
        return [True] * len(pks)

    srv = VerifydServer(
        verify_fn=modeled, max_batch=64, max_delay=0.002,
        admission_cap=256, tenant_cap=48,
    )
    srv.start()
    h, p = srv.address
    addr = f"{h}:{p}"

    # signing is pure-Python and GIL-heavy: build every lane up front
    # so the timed region measures the SERVICE, not key arithmetic
    victim_lanes = [make_lanes(4, seed=50 + i) for i in range(15)]
    flood_lanes = make_lanes(16, seed=60)

    def victim_round(c):
        lat = []
        for lanes in victim_lanes:
            t0 = time.monotonic()
            got = c.verify(*lanes, klass=protocol.CLASS_CONSENSUS)
            lat.append(time.monotonic() - t0)
            assert got == [True] * 4
        lat.sort()
        return lat[-1]  # p99 ~ max of 15 samples

    try:
        victim = VerifydClient(addr, tenant="victim", fallback=False)
        victim_round(victim)  # warm-up: connections, schedulers, JIT-ish
        unloaded_p99 = victim_round(victim)

        flood_outcomes = []
        flood_mtx = threading.Lock()
        flood_stop = threading.Event()

        def aggressor():
            c = VerifydClient(
                addr, tenant="flood", fallback=False, shed_retries=0
            )
            while not flood_stop.is_set():
                try:
                    c.verify(*flood_lanes, klass=protocol.CLASS_RPC)
                    out = "ok"
                except VerifydRejectedError as exc:
                    assert (
                        exc.status == protocol.STATUS_RESOURCE_EXHAUSTED
                    )
                    out = "shed"
                    time.sleep(0.002)  # a real client would back off
                with flood_mtx:
                    flood_outcomes.append(out)
            c.close()

        floods = [threading.Thread(target=aggressor) for _ in range(6)]
        for t in floods:
            t.start()
        time.sleep(0.1)  # flood established
        try:
            loaded_p99 = victim_round(victim)
        finally:
            flood_stop.set()
            for t in floods:
                t.join(timeout=10)
        victim.close()

        from tendermint_tpu.libs import sanitizer

        # under tpusan every lock acquire pays vector-clock bookkeeping,
        # so the SLO measures the instrumentation, not the tier: keep
        # the assertion but widen the floor (same rationale as the
        # sanitizer-gated shed threshold below)
        floor = 0.15 if sanitizer.hb_enabled() else 0.05
        assert loaded_p99 <= 3 * max(unloaded_p99, floor), (
            f"victim p99 {loaded_p99 * 1e3:.1f}ms vs unloaded "
            f"{unloaded_p99 * 1e3:.1f}ms"
        )
        with flood_mtx:
            sheds = flood_outcomes.count("shed")
        # the flood genuinely overran its budget AND every overrun was
        # an explicit wire status (the aggressor loop asserts the code).
        # Under tpusan the instrumented flood threads are too slow to
        # overrun anything, so the load threshold only applies bare.
        from tendermint_tpu.libs import sanitizer

        if not sanitizer.hb_enabled():
            assert sheds >= 1
        stats = srv.tenant_stats()
        assert stats["flood"]["sheds"] == sheds
        assert stats.get("victim", {}).get("sheds", 0) == 0
    finally:
        srv.stop()


# --- zero-copy ingress chaos (slab rings) ------------------------------------


def _noop_verify(pks, msgs, sigs):
    return [True] * len(pks)


def _junk_request(n, seed=0, **kw):
    return protocol.VerifyRequest(
        pks=[bytes([seed % 251 + 1]) * 32] * n,
        msgs=[b"ring-%d-%d" % (seed, i) for i in range(n)],
        sigs=[b"\x09" * 64] * n,
        **kw,
    )


def _shm_server(**kw):
    kw.setdefault("verify_fn", _noop_verify)
    kw.setdefault("max_batch", 64)
    kw.setdefault("max_delay", 0.001)
    kw.setdefault("shm", "on")
    srv = VerifydServer(**kw)
    srv.start()
    return srv


def test_torn_slab_client_died_mid_write_explicit_invalid_and_reclaim():
    """A writer killed between stamp_begin and publication leaves an
    odd generation in the slab: the server answers STATUS_INVALID with
    the torn diagnosis (never a silent drop), counts it, retires the
    slot, and the very next request reuses the ring."""
    srv = _shm_server()
    try:
        t = shm.connect(srv.address[1])
        try:
            seq, slot, gen = t._acquire(time.monotonic() + 5)
            base = t._ring.slab_base(slot)
            shm.stamp_begin(t._ring.buf, base, gen)
            # ...the writer "dies" here: header never published...
            t._send_commit(seq, slot, 1)
            resp = t._wait(seq, time.monotonic() + 10)
            assert resp.status == protocol.STATUS_INVALID
            assert "torn" in resp.message
            assert srv.stats()["shm_torn_slabs"] == 1
            # the slot was retired, not leaked: a full ring of
            # follow-up calls cycles through it cleanly
            for i in range(shm.DEFAULT_NSLABS + 1):
                resp = t.call(_junk_request(2, seed=i), timeout=10.0)
                assert resp.status == protocol.STATUS_OK
        finally:
            t.close()
        assert srv.stats()["shm_torn_slabs"] == 1  # exactly the one
    finally:
        srv.stop()


def test_invalid_slab_releases_its_backlog_lanes():
    """A live-but-buggy client's invalid slabs must not leak their lane
    counts into the pressure signal: each STATUS_INVALID answer releases
    the lanes its COMMIT booked, so ``shm_backlog()`` returns to zero
    instead of permanently inflating load_depth and the brownout
    ladder while the session stays up."""
    srv = _shm_server()
    try:
        t = shm.connect(srv.address[1])
        try:
            for i in range(3):
                seq, slot, gen = t._acquire(time.monotonic() + 5)
                base = t._ring.slab_base(slot)
                shm.stamp_begin(t._ring.buf, base, gen)
                t._send_commit(seq, slot, 5)  # books 5 lanes, slab torn
                resp = t._wait(seq, time.monotonic() + 10)
                assert resp.status == protocol.STATUS_INVALID
            assert srv.stats()["shm_torn_slabs"] == 3
            # session still up, every booked lane released
            assert srv.shm_backlog() == 0
            resp = t.call(_junk_request(2, seed=9), timeout=10.0)
            assert resp.status == protocol.STATUS_OK
            assert srv.shm_backlog() == 0
        finally:
            t.close()
    finally:
        srv.stop()


def test_janitor_timeout_fails_loud_and_never_reuses_held_slab(monkeypatch):
    """Held-slab entries unresolved past the janitor grace mean the
    scheduler still holds memoryviews into the slab — under sustained
    overload that is legitimate, not wedged. Handing the slot back
    would let the client rewrite bytes a pending flush has yet to
    materialise (silently wrong verdicts); the janitor must instead
    freeze TAIL and drop the doorbell so the failure is loud and the
    client falls back to TCP."""
    monkeypatch.setattr(shm, "_JANITOR_GRACE_S", 0.3)
    release = threading.Event()

    def gated(pks, msgs, sigs):
        release.wait(20)
        return [True] * len(pks)

    srv = _shm_server(verify_fn=gated)
    try:
        t = shm.connect(srv.address[1])
        try:
            resp = t.call(
                _junk_request(2, seed=1, deadline_ms=80), timeout=10.0
            )
            assert resp.status == protocol.STATUS_DEADLINE_EXCEEDED
            # grace expires with the flush still in flight: the session
            # dies loud instead of retiring the slab under the flush
            deadline = time.monotonic() + 5
            while t.alive and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not t.alive
            assert t._ring.tail() == 0  # held slab never handed back
            assert srv.stats()["shm_fallbacks"] >= 1
        finally:
            release.set()
            t.close()
    finally:
        release.set()
        srv.stop()


def test_stale_generation_replay_is_torn():
    """Replaying a slot without re-filling it (cursor corruption, a
    duplicated doorbell frame) trips the strictly-newer generation
    check — the seqlock's defense against reading a retired slab."""
    srv = _shm_server()
    try:
        t = shm.connect(srv.address[1])
        try:
            # one full ring lap retires generation 2 in every slot
            for i in range(t._ring.nslabs):
                resp = t.call(_junk_request(1, seed=i), timeout=10.0)
                assert resp.status == protocol.STATUS_OK
            # forge the next commit (slot 0 again) WITHOUT re-filling:
            # the slab still carries the retired generation 2
            with t._mtx:
                seq = t._head
                t._head = seq + 1
                t._ring.set_head(t._head)
                t._waiting.add(seq)
            assert seq % t._ring.nslabs == 0
            t._send_commit(seq, 0, 1)
            resp = t._wait(seq, time.monotonic() + 10)
            assert resp.status == protocol.STATUS_INVALID
            assert "stale" in resp.message
            assert srv.stats()["shm_torn_slabs"] == 1
        finally:
            t.close()
    finally:
        srv.stop()


def test_client_killed_mid_write_server_reclaims_segment():
    """SIGKILL equivalent: the doorbell socket dies with a slab write
    in progress. The server must drop the session AND unlink the
    segment so a dead client's ring cannot pin memory."""
    srv = _shm_server()
    try:
        t = shm.connect(srv.address[1])
        seg_name = t._seg.name
        seg_path = os.path.join("/dev/shm", seg_name.lstrip("/"))
        has_dev_shm = os.path.exists(seg_path)
        # a write in progress when the client dies
        seq, slot, gen = t._acquire(time.monotonic() + 5)
        shm.stamp_begin(t._ring.buf, t._ring.slab_base(slot), gen)
        # the kill: no farewell frame, no segment cleanup. shutdown()
        # models kernel-side fd teardown on process death — a bare
        # close() here would be weaker than death, because the reader
        # thread parked in recv pins the description and no EOF would
        # ever reach the server
        t._sock.shutdown(socket.SHUT_RDWR)
        t._sock.close()
        deadline = time.monotonic() + 5
        while (
            srv.stats()["shm_sessions"] > 0 and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert srv.stats()["shm_sessions"] == 0
        if has_dev_shm:
            deadline = time.monotonic() + 5
            while os.path.exists(seg_path) and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not os.path.exists(seg_path), "segment leaked"
        t.close()  # the client half of the mapping (idempotent)
    finally:
        srv.stop()


def test_server_restart_with_live_ring_client_falls_back_then_renegotiates():
    """The daemon restarts while a slab-ring session is live: the
    client's next call rides TCP explicitly (no hang, no loss), and
    after the retry cooldown it renegotiates a fresh ring against the
    new instance."""
    srv = _shm_server()
    h, p = srv.address
    c = VerifydClient(
        f"{h}:{p}", shm="auto", fallback=False, retries=10, backoff=0.05
    )
    srv2 = None
    try:
        assert c.verify(
            *_lanes_of(_junk_request(3, seed=1))
        ) == [True] * 3
        assert c.transport == "shm"
        srv.stop()
        srv2 = _shm_server(host=h, port=p)
        # the dead ring is detected, the call resolves over TCP
        assert c.verify(
            *_lanes_of(_junk_request(3, seed=2))
        ) == [True] * 3
        time.sleep(1.1)  # shm renegotiation cooldown
        assert c.verify(
            *_lanes_of(_junk_request(3, seed=3))
        ) == [True] * 3
        assert c.transport == "shm"  # fresh ring against the new server
        deadline = time.monotonic() + 5
        while (
            srv2.stats()["shm_sessions"] < 1 and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert srv2.stats()["shm_sessions"] == 1
        c.close()
    finally:
        c.close()
        if srv2 is not None:
            srv2.stop()
        else:
            srv.stop()


def _lanes_of(req):
    return req.pks, req.msgs, req.sigs


def test_slow_consumer_shm_backlog_feeds_admission_and_brownout():
    """Slab lanes committed but not yet drained MUST count as pressure:
    with the drain workers wedged, the scheduler is provably idle yet
    TCP rpc traffic sheds on queue depth and the brownout ladder
    escalates — the shm-only overload the ISSUE's acceptance demands.
    Once the consumer resumes, every wedged call resolves explicitly."""
    gate = threading.Event()
    srv = _shm_server(
        admission_cap=64,
        brownout=BrownoutController(escalate_after=0.05, cooldown_fn=None),
    )
    shm._TEST_DRAIN_GATE = gate.wait
    statuses = []
    st_mtx = threading.Lock()
    try:
        h, p = srv.address
        t = shm.connect(p)

        def submit(i):
            # consensus class: never shed, so post-release statuses stay
            # explicit regardless of the ladder's level at drain time
            resp = t.call(
                _junk_request(
                    100, seed=i, klass=protocol.CLASS_CONSENSUS
                ),
                timeout=30.0,
            )
            with st_mtx:
                statuses.append(resp.status)

        writers = [
            threading.Thread(target=submit, args=(i,)) for i in range(4)
        ]
        for th in writers:
            th.start()
        deadline = time.monotonic() + 5
        while srv.shm_backlog() < 400 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv.shm_backlog() >= 400  # lanes visible as pressure...
        assert srv.scheduler.load_depth() == 0  # ...with an IDLE scheduler
        # rpc probes over the escalation window: depth(=shm backlog)
        # alone must shed them and walk the ladder up
        probe = _client(f"{h}:{p}", shm="off")
        sheds = 0
        t_end = time.monotonic() + 0.3
        while time.monotonic() < t_end:
            try:
                probe.verify(
                    *_lanes_of(_junk_request(2, seed=77)),
                    klass=protocol.CLASS_RPC,
                )
            except VerifydRejectedError as exc:
                assert exc.status == protocol.STATUS_RESOURCE_EXHAUSTED
                sheds += 1
            time.sleep(0.01)
        probe.close()
        assert sheds >= 1, "shm-only backlog did not shed rpc"
        assert srv.brownout.level > LEVEL_NORMAL
        gate.set()
        for th in writers:
            th.join(timeout=30)
        t.close()
        assert len(statuses) == 4  # zero silent drops
        assert all(s == protocol.STATUS_OK for s in statuses), statuses
        deadline = time.monotonic() + 5
        while srv.shm_backlog() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.shm_backlog() == 0
    finally:
        shm._TEST_DRAIN_GATE = None
        gate.set()
        srv.stop()


# --- continuous batching proof (acceptance) ----------------------------------


def test_trace_proves_admission_during_inflight_dispatch():
    """The continuous-batching demonstration the issue asks for: a
    ``scheduler_admit_inflight`` instant lands INSIDE the time window
    of a ``scheduler_dispatch`` span — lanes were admitted while a
    kernel was on the device."""
    prior_mode = tracing.tracer.mode
    tracing.configure("ring")
    tracing.tracer.export(clear=True)  # drain other tests' events
    gate = threading.Event()
    in_flight = threading.Event()

    def gated(pks, msgs, sigs):
        in_flight.set()
        gate.wait(10)
        return host_verify(pks, msgs, sigs)

    srv = VerifydServer(
        verify_fn=gated, max_batch=4, max_delay=0.01,
        continuous=True, pipeline_depth=2,
    )
    srv.start()
    try:
        h, p = srv.address
        results = {}

        def call(key, seed):
            c = _client(f"{h}:{p}")
            results[key] = c.verify(*make_lanes(4, seed=seed))
            c.close()

        t1 = threading.Thread(target=call, args=("a", 70))
        t1.start()
        assert in_flight.wait(timeout=5)  # dispatch 1 holds the device
        t2 = threading.Thread(target=call, args=("b", 71))
        t2.start()
        # wait for the second group's admission to be traced
        deadline = time.monotonic() + 5
        while (
            srv.scheduler.stats()["inflight_admissions"] < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        gate.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert results["a"] == [True] * 4
        assert results["b"] == [True] * 4

        events = tracing.tracer.export()["traceEvents"]
        dispatches = [
            e
            for e in events
            if e.get("ph") == "X" and e["name"] == "scheduler_dispatch"
        ]
        admits = [
            e
            for e in events
            if e.get("ph") == "i"
            and e["name"] == "scheduler_admit_inflight"
        ]
        assert dispatches and admits
        contained = any(
            d["ts"] <= a["ts"] <= d["ts"] + d["dur"]
            for a in admits
            for d in dispatches
        )
        assert contained, "no admission instant inside a dispatch span"
        # the instant itself carries the proof: a kernel was in flight
        assert all(a["args"]["inflight"] >= 1 for a in admits)
    finally:
        gate.set()
        srv.stop()
        tracing.configure(prior_mode)


# --- federation shard loss (ISSUE 19 acceptance) -----------------------------


def test_federation_shard_kill_mid_load_reroutes_with_bounded_p99():
    """A 2-shard federation loses a shard while three committees load
    it continuously: every submitted batch still resolves with CORRECT
    verdicts (re-routed to the survivor, host oracle worst case — the
    lanes are really signed, so a wrong routing decision cannot hide
    behind a modeled True), the router's counters explain the re-routes,
    and the victim committees' post-kill p99 stays bounded — failover
    is a transient, not a new steady state."""
    from tendermint_tpu.verifyd.federation import FederationClient

    servers = []
    addrs = []
    for sid in range(2):
        srv = VerifydServer(
            verify_fn=host_verify, max_batch=32, max_delay=0.002,
            shard_id=sid,
        )
        srv.start()
        h, p = srv.address
        servers.append(srv)
        addrs.append(f"{h}:{p}")
    fed = FederationClient(
        addrs, dead_retry_s=60.0, failover_backoff_s=0.005
    )
    committees = [make_lanes(4, seed=50 + c) for c in range(3)]
    for pks, _, _ in committees:
        fed.note_validator_set(list(dict.fromkeys(pks)))
    victim = fed.shard_for(committees[0][0][0])

    killed = threading.Event()
    stop_flag = threading.Event()
    mtx = threading.Lock()
    outcomes = []  # (after_kill, ok, latency_s)

    def loader(c):
        pks, msgs, sigs = committees[c]
        while not stop_flag.is_set():
            t0 = time.perf_counter()
            try:
                got = fed.verify(pks, msgs, sigs)
                ok = got == [True] * 4
            except Exception:  # the ladder must never raise
                ok = False
            with mtx:
                outcomes.append(
                    (killed.is_set(), ok, time.perf_counter() - t0)
                )
            time.sleep(0.01)

    threads = [
        threading.Thread(target=loader, args=(c,)) for c in range(3)
    ]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)  # load established, placements warm
        servers[victim].stop()  # chaos: one shard dies under load
        killed.set()
        time.sleep(0.9)
        stop_flag.set()
        for t in threads:
            t.join(timeout=15)
        with mtx:
            snapshot = list(outcomes)
        # zero silent drops, zero wrong verdicts — before AND after
        assert snapshot and all(ok for _, ok, _ in snapshot)
        post = sorted(lat for after, _, lat in snapshot if after)
        assert len(post) >= 5  # the fleet kept serving after the kill
        st = fed.stats()
        assert st["failovers"] >= 1  # the ladder actually walked
        assert st["rerouted_lanes"] >= 4
        assert victim not in fed.alive_shards()
        # bounded victim p99: the failover transient (client retries +
        # ladder backoff) may hit a few calls, the steady state must
        # recover to the survivor's direct path
        p99 = post[min(len(post) - 1, int(len(post) * 0.99))]
        assert p99 < 2.0, f"post-kill p99 {p99:.3f}s — failover wedged"
        p50 = post[len(post) // 2]
        assert p50 < 0.25, f"post-kill p50 {p50:.3f}s — no steady state"
    finally:
        stop_flag.set()
        for t in threads:
            t.join(timeout=5)
        fed.close()
        for s in servers:
            s.stop()
