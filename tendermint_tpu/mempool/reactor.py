"""Mempool reactor: tx gossip on channel 0x30
(internal/mempool/reactor.go). Own CheckTx-accepted txs are broadcast;
received txs run through CheckTx before re-gossip (dedupe via the
seen-cache stops loops)."""

from __future__ import annotations

import threading

from tendermint_tpu.mempool.mempool import TxMempool
from tendermint_tpu.p2p.router import Channel, Envelope, Router

MEMPOOL_CHANNEL = 0x30


class MempoolReactor:
    def __init__(self, mempool: TxMempool, router: Router):
        self.mempool = mempool
        self.channel = router.open_channel(MEMPOOL_CHANNEL)
        self._stop_flag = threading.Event()
        self._thread = None

    def start(self) -> None:
        self._stop_flag.clear()
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_flag.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def broadcast_tx(self, tx: bytes) -> None:
        """Called after local CheckTx admission (reactor.go broadcast)."""
        self.channel.broadcast(tx)

    def check_and_broadcast_tx(self, tx: bytes, sender: str = "") -> None:
        res = self.mempool.check_tx(tx, sender)
        if res.is_ok():
            self.broadcast_tx(tx)

    def _recv_loop(self) -> None:
        while not self._stop_flag.is_set():
            env = self.channel.receive(timeout=0.2)
            if env is None:
                continue
            try:
                res = self.mempool.check_tx(env.message, sender=env.from_peer)
                if res.is_ok():
                    # Re-gossip so txs flood the network; the seen-cache on
                    # every node breaks cycles.
                    self.channel.broadcast(env.message)
            except (KeyError, ValueError, OverflowError):
                pass  # duplicate / invalid / full: drop
