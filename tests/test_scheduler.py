"""Accumulate-with-deadline verify scheduler tests (SURVEY §7 latency
duality seam)."""

import threading
import time

import pytest

from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.crypto.ed25519_ref import verify_zip215
from tendermint_tpu.crypto.scheduler import VerifyScheduler


def host_verify(pks, msgs, sigs):
    return [verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]


@pytest.fixture()
def sched():
    s = VerifyScheduler(host_verify, max_batch=32, max_delay=0.05)
    s.start()
    yield s
    s.stop()


def _signed(i: int):
    priv = Ed25519PrivKey.from_seed(bytes([i]) * 32)
    msg = b"sched-msg-%d" % i
    return priv.pub_key().bytes(), msg, priv.sign(msg)


class TestDeadline:
    def test_lone_entry_answers_within_deadline(self, sched):
        pk, msg, sig = _signed(1)
        t0 = time.monotonic()
        assert sched.verify(pk, msg, sig)
        elapsed = time.monotonic() - t0
        # one flush, no batch partners: the deadline bounds the wait
        assert elapsed < 1.0
        assert sched.flushes == 1

    def test_bad_signature_fails_only_itself(self, sched):
        good = [_signed(i) for i in range(4)]
        results = {}

        def submit(idx, pk, msg, sig):
            results[idx] = sched.verify(pk, msg, sig)

        threads = []
        for i, (pk, msg, sig) in enumerate(good):
            bad_sig = bytes(64) if i == 2 else sig
            t = threading.Thread(target=submit, args=(i, pk, msg, bad_sig))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=10)
        assert results == {0: True, 1: True, 2: False, 3: True}


class TestBatching:
    def test_concurrent_callers_share_flushes(self):
        calls = []

        def counting_verify(pks, msgs, sigs):
            calls.append(len(pks))
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(counting_verify, max_batch=64, max_delay=0.2)
        s.start()
        try:
            entries = [_signed(i % 8) for i in range(40)]
            results = [None] * 40

            def submit(i):
                pk, msg, sig = entries[i]
                results[i] = s.verify(pk, msg, sig)

            threads = [
                threading.Thread(target=submit, args=(i,)) for i in range(40)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert all(results)
            # 40 concurrent verifies amortized into far fewer flushes;
            # only the 8 unique (pk, msg, sig) triples cost verifier
            # lanes — duplicates within a flush coalesce.
            assert len(calls) < 10, calls
            assert 8 <= sum(calls) <= 40
            assert s.entries_verified == 40
            assert sum(calls) + s.entries_coalesced == 40
        finally:
            s.stop()

    def test_duplicate_submissions_coalesce_to_one_lane(self):
        calls = []

        def counting_verify(pks, msgs, sigs):
            calls.append(len(pks))
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(counting_verify, max_batch=64, max_delay=60.0)
        s.start()
        try:
            good = _signed(1)
            bad = (good[0], good[1], bytes(64))
            handles = [s.submit(*good) for _ in range(5)]
            handles += [s.submit(*bad) for _ in range(3)]
            # force the flush now rather than waiting out the deadline
            with s._wake:
                s.max_delay = 0.0
                s._wake.notify_all()
            oks = [s.wait(h) for h in handles]
            assert oks == [True] * 5 + [False] * 3
            # 8 submissions, 2 unique triples, 1 flush
            assert calls == [2], calls
            assert s.entries_coalesced == 6
            assert s.entries_verified == 8
        finally:
            s.stop()

    def test_max_batch_flushes_without_deadline(self):
        s = VerifyScheduler(host_verify, max_batch=4, max_delay=60.0)
        s.start()
        try:
            entries = [_signed(i) for i in range(4)]
            results = [None] * 4
            threads = [
                threading.Thread(
                    target=lambda i=i: results.__setitem__(
                        i, s.verify(*entries[i])
                    )
                )
                for i in range(4)
            ]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            # the batch-size trigger fired: nowhere near the 60s deadline
            assert time.monotonic() - t0 < 10
            assert all(results)
        finally:
            s.stop()


class TestFailureModes:
    def test_verifier_exception_fails_closed(self):
        def broken(pks, msgs, sigs):
            raise RuntimeError("device on fire")

        s = VerifyScheduler(broken, max_batch=8, max_delay=0.01)
        s.start()
        try:
            pk, msg, sig = _signed(1)
            assert s.verify(pk, msg, sig) is False
        finally:
            s.stop()

    def test_stop_fails_pending_closed(self):
        started = threading.Event()

        def slow(pks, msgs, sigs):
            started.set()
            time.sleep(0.5)
            return [True] * len(pks)

        s = VerifyScheduler(slow, max_batch=1, max_delay=0.01)
        s.start()
        pk, msg, sig = _signed(1)
        out = {}
        t = threading.Thread(target=lambda: out.setdefault("r", s.verify(pk, msg, sig)))
        t.start()
        started.wait(timeout=5)
        s.stop()
        t.join(timeout=5)
        assert out["r"] in (True, False)  # resolved, never hung

    def test_submit_after_stop_raises(self):
        s = VerifyScheduler(host_verify)
        s.start()
        s.stop()
        with pytest.raises(RuntimeError):
            s.verify(b"\x00" * 32, b"m", b"\x00" * 64)
