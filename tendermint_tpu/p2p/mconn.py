"""MConnection: multiplexed, prioritized, flow-controlled peer connection.

The reference multiplexes every reactor channel over one TCP connection
with per-channel priority queues, ~1400-byte packetization, flow-rate
throttling, and ping/pong keepalive (internal/p2p/conn/connection.go:
75-700). This module is that layer for the TPU build, speaking over any
length-delimited frame stream (here: the SecretConnection message layer).

Scheduling follows the reference's least-sent-relative-to-priority rule
(sendPacketMsg → channel with lowest recentlySent/priority); send and
receive sides are token-bucket rate-limited (flowrate analog); pings fly
every ``ping_interval`` and a missing pong for ``pong_timeout`` errors
the connection (connection.go:48-49 defaults).

Wire format inside each frame: 1 type byte (PING/PONG/MSG); MSG carries
u16 channel id, u8 eof, payload — a logical message is the concatenation
of packet payloads up to the eof packet (PacketMsg analog).
"""

from __future__ import annotations

import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

_PKT_PING = 0x01
_PKT_PONG = 0x02
_PKT_MSG = 0x03

# connection.go:29-49 / config.go P2P defaults. The rates are the
# reference's *config-level* defaults (config.go SendRate/RecvRate =
# 5120000), not connection.go's internal 512000 fallback — every real
# node runs with the former.
DEFAULT_MAX_PACKET_PAYLOAD = 1400
DEFAULT_SEND_RATE = 5120000  # bytes/sec (5MB/s)
DEFAULT_RECV_RATE = 5120000
DEFAULT_PING_INTERVAL = 60.0
DEFAULT_PONG_TIMEOUT = 90.0
DEFAULT_SEND_TIMEOUT = 10.0  # connection.go:47 defaultSendTimeout
DEFAULT_SEND_QUEUE_CAPACITY = 1024  # messages per channel
DEFAULT_RECV_MESSAGE_CAPACITY = 22020096  # 21MB
MAX_RECV_CHANNELS = 256  # distinct channel states per connection

# Reactor channel priorities, as each reference reactor registers them
# (consensus/reactor.go:38-68, blocksync:45, mempool:85, evidence:38,
# statesync:80-107, pex:62).
DEFAULT_CHANNEL_PRIORITIES: Dict[int, int] = {
    0x20: 8,   # consensus state
    0x21: 12,  # consensus data (block parts)
    0x22: 10,  # consensus votes
    0x23: 5,   # consensus vote-set bits
    0x30: 5,   # mempool
    0x38: 6,   # evidence
    0x40: 5,   # blocksync
    0x60: 6,   # statesync snapshot
    0x61: 3,   # statesync chunk
    0x62: 5,   # statesync light block
    0x63: 2,   # statesync params
    0x00: 1,   # pex
}


@dataclass
class MConnConfig:
    max_packet_payload: int = DEFAULT_MAX_PACKET_PAYLOAD
    send_rate: int = DEFAULT_SEND_RATE
    recv_rate: int = DEFAULT_RECV_RATE
    ping_interval: float = DEFAULT_PING_INTERVAL
    pong_timeout: float = DEFAULT_PONG_TIMEOUT
    send_timeout: float = DEFAULT_SEND_TIMEOUT
    send_queue_capacity: int = DEFAULT_SEND_QUEUE_CAPACITY
    recv_message_capacity: int = DEFAULT_RECV_MESSAGE_CAPACITY
    channel_priorities: Dict[int, int] = field(
        default_factory=lambda: dict(DEFAULT_CHANNEL_PRIORITIES)
    )


class _TokenBucket:
    """flowrate.Monitor-in-spirit: cap sustained bytes/sec, with one
    second of burst."""

    def __init__(self, rate: int):
        self.rate = max(1, rate)
        self.capacity = float(self.rate)
        self.tokens = self.capacity
        self.last = time.monotonic()
        self._lock = threading.Lock()

    def consume(self, n: int, cancelled: threading.Event) -> None:
        """Block until n tokens are available (sleeping off the deficit).

        n is clamped to the bucket capacity: a single packet larger than
        one second of rate must still eventually pass (paying a full
        bucket), never deadlock the connection.
        """
        n = min(n, int(self.capacity))
        while True:
            with self._lock:
                now = time.monotonic()
                self.tokens = min(
                    self.capacity, self.tokens + (now - self.last) * self.rate
                )
                self.last = now
                if self.tokens >= n:
                    self.tokens -= n
                    return
                deficit = (n - self.tokens) / self.rate
            if cancelled.wait(min(deficit, 0.25)):
                return


class _ChannelState:
    __slots__ = ("priority", "queue", "sending", "recently_sent", "recv_buf")

    def __init__(self, priority: int, capacity: int):
        self.priority = max(1, priority)
        self.queue: deque = deque(maxlen=capacity)
        self.sending: Optional[memoryview] = None  # partially-sent message
        self.recently_sent = 0.0
        self.recv_buf = bytearray()


class MConnectionError(Exception):
    pass


class MConnection:
    """One multiplexed connection over a frame stream.

    ``send_frame(bytes)`` / ``recv_frame() -> bytes`` are the underlying
    transport (SecretConnection messages for TCP). ``on_receive`` is
    called off the recv routine with complete (channel_id, message)
    pairs; ``on_error`` once, with the fatal exception.
    """

    def __init__(
        self,
        send_frame: Callable[[bytes], None],
        recv_frame: Callable[[], bytes],
        on_receive: Callable[[int, bytes], None],
        on_error: Callable[[Exception], None],
        config: Optional[MConnConfig] = None,
    ):
        self.config = config or MConnConfig()
        self._send_frame = send_frame
        self._recv_frame = recv_frame
        self._on_receive = on_receive
        self._on_error = on_error
        self._channels: Dict[int, _ChannelState] = {}
        self._chan_lock = threading.Lock()
        self._send_ready = threading.Event()
        self._stop = threading.Event()
        self._send_bucket = _TokenBucket(self.config.send_rate)
        self._recv_bucket = _TokenBucket(self.config.recv_rate)
        self._frame_lock = threading.Lock()
        self._last_pong = time.monotonic()
        self._ping_outstanding = False
        self._ping_sent = 0.0
        self._recv_buffered = 0
        self._threads = []
        self._errored = threading.Event()

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for target, name in (
            (self._send_routine, "mconn-send"),
            (self._recv_routine, "mconn-recv"),
            (self._ping_routine, "mconn-ping"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._send_ready.set()

    def _error(self, e: Exception) -> None:
        if not self._errored.is_set():
            self._errored.set()
            self.stop()
            try:
                self._on_error(e)
            except Exception:
                pass

    # --- sending ------------------------------------------------------------

    def _chan(self, channel_id: int) -> _ChannelState:
        with self._chan_lock:
            st = self._channels.get(channel_id)
            if st is None:
                st = _ChannelState(
                    self.config.channel_priorities.get(channel_id, 1),
                    self.config.send_queue_capacity,
                )
                self._channels[channel_id] = st
            return st

    @property
    def errored(self) -> bool:
        return self._errored.is_set()

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def send(self, channel_id: int, msg: bytes) -> bool:
        """Enqueue a message, blocking up to ``send_timeout`` for queue
        space; False on timeout or stop (connection.go Send blocks on the
        channel sendQueue with defaultSendTimeout then reports false)."""
        st = self._chan(channel_id)
        deadline = time.monotonic() + self.config.send_timeout
        while True:
            if self._stop.is_set():
                return False
            with self._chan_lock:
                if len(st.queue) < st.queue.maxlen:
                    st.queue.append(msg)
                    self._send_ready.set()
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)  # send routine drains continuously

    def _pick_channel(self) -> Optional[Tuple[int, _ChannelState]]:
        """Lowest recently_sent/priority among channels with pending data
        (connection.go sendPacketMsg:390-420)."""
        best = None
        best_score = None
        with self._chan_lock:
            for cid, st in self._channels.items():
                if st.sending is None and not st.queue:
                    continue
                score = st.recently_sent / st.priority
                if best_score is None or score < best_score:
                    best, best_score = (cid, st), score
        return best

    def _send_routine(self) -> None:
        max_payload = self.config.max_packet_payload
        last_decay = time.monotonic()
        try:
            while not self._stop.is_set():
                picked = self._pick_channel()
                if picked is None:
                    self._send_ready.wait(timeout=0.1)
                    self._send_ready.clear()
                    continue
                cid, st = picked
                with self._chan_lock:
                    if st.sending is None:
                        if not st.queue:
                            continue
                        st.sending = memoryview(st.queue.popleft())
                    chunk = bytes(st.sending[:max_payload])
                    st.sending = st.sending[max_payload:]
                    eof = 1 if len(st.sending) == 0 else 0
                    if eof:
                        st.sending = None
                    st.recently_sent += len(chunk)
                pkt = (
                    bytes([_PKT_MSG])
                    + struct.pack(">HB", cid, eof)
                    + chunk
                )
                self._send_bucket.consume(len(pkt), self._stop)
                if self._stop.is_set():
                    return
                with self._frame_lock:
                    self._send_frame(pkt)
                now = time.monotonic()
                if now - last_decay >= 1.0:
                    # exponential decay so a quiet channel regains
                    # scheduling weight (flowrate's sliding window analog)
                    with self._chan_lock:
                        for s in self._channels.values():
                            s.recently_sent *= 0.5
                    last_decay = now
        except Exception as e:
            self._error(MConnectionError(f"send failed: {e}"))

    # --- receiving ----------------------------------------------------------

    def _recv_routine(self) -> None:
        try:
            while not self._stop.is_set():
                frame = self._recv_frame()
                self._recv_bucket.consume(len(frame), self._stop)
                if not frame:
                    raise MConnectionError("empty frame")
                ptype = frame[0]
                if ptype == _PKT_PING:
                    with self._frame_lock:
                        self._send_frame(bytes([_PKT_PONG]))
                    continue
                if ptype == _PKT_PONG:
                    self._last_pong = time.monotonic()
                    self._ping_outstanding = False
                    continue
                if ptype != _PKT_MSG:
                    raise MConnectionError(f"unknown packet type {ptype}")
                if len(frame) < 4:
                    raise MConnectionError("short msg packet")
                cid, eof = struct.unpack_from(">HB", frame, 1)
                payload = frame[4:]
                # Channels open dynamically (router reactors), so unknown
                # ids are accepted — but bounded: a hostile peer spraying
                # packets across the 64Ki id space must not allocate
                # unbounded channel states or reassembly buffers
                # (connection.go instead rejects unregistered channels;
                # same resource bound, looser coupling).
                with self._chan_lock:
                    known = cid in self._channels
                if not known and len(self._channels) >= MAX_RECV_CHANNELS:
                    raise MConnectionError(
                        f"too many distinct channels (> {MAX_RECV_CHANNELS})"
                    )
                st = self._chan(cid)
                st.recv_buf += payload
                self._recv_buffered += len(payload)
                if len(st.recv_buf) > self.config.recv_message_capacity:
                    raise MConnectionError(
                        f"message on channel {cid:#x} exceeds recv capacity"
                    )
                if self._recv_buffered > 3 * self.config.recv_message_capacity:
                    raise MConnectionError(
                        "aggregate reassembly buffers exceed capacity"
                    )
                if eof:
                    msg = bytes(st.recv_buf)
                    self._recv_buffered -= len(st.recv_buf)
                    st.recv_buf = bytearray()
                    self._on_receive(cid, msg)
        except Exception as e:
            self._error(MConnectionError(f"recv failed: {e}"))

    # --- keepalive ----------------------------------------------------------

    def _ping_routine(self) -> None:
        """Ping every ping_interval; the pong clock starts when the
        unanswered ping was SENT (connection.go arms pongTimeout in
        sendRoutine), checked at a finer wake so the effective timeout
        tracks the configured one."""
        wake = min(
            self.config.ping_interval, max(0.05, self.config.pong_timeout / 3)
        )
        last_ping = 0.0
        try:
            while not self._stop.wait(wake):
                now = time.monotonic()
                if (
                    self._ping_outstanding
                    and now - self._ping_sent > self.config.pong_timeout
                ):
                    raise MConnectionError("pong timeout")
                if now - last_ping >= self.config.ping_interval:
                    with self._frame_lock:
                        self._send_frame(bytes([_PKT_PING]))
                    last_ping = now
                    if not self._ping_outstanding:
                        self._ping_outstanding = True
                        self._ping_sent = now
        except Exception as e:
            self._error(e)
