"""Canonical wire encoding (protobuf wire format, hand-rolled).

The reference framework signs and hashes protobuf-encoded canonical
structures (reference: types/canonical.go, proto/tendermint/types/
canonical.proto). Byte-exact encoding is consensus-critical: every
sign-bytes and every hashed struct must serialize identically across
implementations. This package provides a minimal, dependency-free
protobuf wire codec plus the canonical message encoders.
"""

from tendermint_tpu.encoding.proto import (  # noqa: F401
    Reader,
    encode_bytes_field,
    encode_fixed64_field,
    encode_message_field,
    encode_sfixed64_field,
    encode_string_field,
    encode_varint,
    encode_varint_field,
    length_delimited,
    tag,
)
