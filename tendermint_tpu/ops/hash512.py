"""Batched SHA-512 (+ mod-L reduction) on device: fused challenge prep.

The verifier's challenge scalar ``k = SHA-512(R || A || M) mod L`` was
the last hot-path stage still computed on the host CPU (crypto/hashing
.py: C extension or hashlib). For the batches that dominate consensus —
N fixed-width vote/commit sign-bytes — this module computes it on
device instead: the host packs raw bytes into padded SHA-512 blocks
(one ``(N, B*128)`` uint8 matrix, no hashing work), and a jitted kernel
runs the 80-round compression plus the byte-limb Barrett reduction, so
the challenge never round-trips through host memory and the host "prep"
stage shrinks to byte packing.

Representation: one 64-bit SHA word is an (hi, lo) pair of uint32 lane
vectors — f64/i64 are banned on this accelerator path (tpulint TPJ003),
and uint32 pairs map directly onto the VPU. The mod-L reduction mirrors
crypto/hashing.reduce_mod_l limb for limb (radix 2^8 in int32 columns,
``q = floor(floor(x/2^240) * mu / 2^272)``, three conditional
subtracts), so device and host scalars are bit-identical — pinned by
the parity battery in tests/test_device_hash.py.

Constants are derived, not transcribed: round constants are the
fractional cube roots of the first 80 primes and the init state the
fractional square roots of the first 8, computed exactly with integer
Newton roots at import.

Env knobs::

    TENDERMINT_TPU_DEVICE_HASH         auto (default: on for tpu/axon) | on | off
    TENDERMINT_TPU_DEVICE_HASH_MAXLEN  widest per-lane message the fused
                                       path accepts (default 512 bytes)
"""

from __future__ import annotations

import math
import os
import threading
from functools import lru_cache
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.hashing import L

_ENV = "TENDERMINT_TPU_DEVICE_HASH"
_MAXLEN_ENV = "TENDERMINT_TPU_DEVICE_HASH_MAXLEN"

_MASK64 = (1 << 64) - 1


def _primes(count: int):
    out = []
    cand = 2
    while len(out) < count:
        if all(cand % p for p in out if p * p <= cand):
            out.append(cand)
        cand += 1
    return out


def _icbrt(n: int) -> int:
    """floor(n ** (1/3)) by integer Newton iteration."""
    x = 1 << -(-n.bit_length() // 3)
    while True:
        y = (2 * x + n // (x * x)) // 3
        if y >= x:
            return x
        x = y


_P80 = _primes(80)
# K[t] = frac(cbrt(p_t)) * 2^64; H0[i] = frac(sqrt(p_i)) * 2^64.
_K64 = [_icbrt(p << 192) & _MASK64 for p in _P80]
_H64 = [math.isqrt(p << 128) & _MASK64 for p in _P80[:8]]
_K_HI = [k >> 32 for k in _K64]
_K_LO = [k & 0xFFFFFFFF for k in _K64]
_H_HI = [h >> 32 for h in _H64]
_H_LO = [h & 0xFFFFFFFF for h in _H64]

# Round constants as a (80, 2) uint32 (hi, lo) table the round loop
# indexes dynamically, and the init state as plain python ints.
_K_ARR = np.array(list(zip(_K_HI, _K_LO)), dtype=np.uint32)

# Byte limbs (little-endian) of the Barrett constants; python ints so
# the traced kernel folds them in as scalars.
_MU = (1 << 512) // L
_MU_BYTES = [(_MU >> (8 * i)) & 0xFF for i in range((_MU.bit_length() + 7) // 8)]
_L_BYTES = [(L >> (8 * i)) & 0xFF for i in range(32)]


# --- 64-bit word ops on (hi, lo) uint32 pairs --------------------------------


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, l, r: int):
    """Rotate right by static r in [1, 63], r % 32 != 0 (true for every
    rotation SHA-512 uses)."""
    hh, ll = (h, l) if r < 32 else (l, h)
    rr = r % 32
    s = 32 - rr
    return (hh >> rr) | (ll << s), (ll >> rr) | (hh << s)


def _shr64(h, l, n: int):
    """Logical shift right by static n in [1, 31]."""
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def _small_sigma0(h, l):
    return _xor3(_rotr64(h, l, 1), _rotr64(h, l, 8), _shr64(h, l, 7))


def _small_sigma1(h, l):
    return _xor3(_rotr64(h, l, 19), _rotr64(h, l, 61), _shr64(h, l, 6))


def _big_sigma0(h, l):
    return _xor3(_rotr64(h, l, 28), _rotr64(h, l, 34), _rotr64(h, l, 39))


def _big_sigma1(h, l):
    return _xor3(_rotr64(h, l, 14), _rotr64(h, l, 18), _rotr64(h, l, 41))


# --- compression -------------------------------------------------------------


def _sched_step(t, wbuf):
    """Message-schedule fill: w[t] = s1(w[t-2]) + w[t-7] + s0(w[t-15])
    + w[t-16]; wbuf is (80, 2, N) uint32."""
    w2 = jax.lax.dynamic_index_in_dim(wbuf, t - 2, keepdims=False)
    w7 = jax.lax.dynamic_index_in_dim(wbuf, t - 7, keepdims=False)
    w15 = jax.lax.dynamic_index_in_dim(wbuf, t - 15, keepdims=False)
    w16 = jax.lax.dynamic_index_in_dim(wbuf, t - 16, keepdims=False)
    s1 = _small_sigma1(w2[0], w2[1])
    s0 = _small_sigma0(w15[0], w15[1])
    acc = _add64(s1[0], s1[1], w7[0], w7[1])
    acc = _add64(acc[0], acc[1], s0[0], s0[1])
    acc = _add64(acc[0], acc[1], w16[0], w16[1])
    return jax.lax.dynamic_update_index_in_dim(
        wbuf, jnp.stack(acc), t, axis=0
    )


def _make_round(wbuf, k_arr):
    def round_step(t, vars8):
        """One compression round; vars8 is (8, 2, N) uint32 = a..h."""
        a, b, c, d = vars8[0], vars8[1], vars8[2], vars8[3]
        e, f, g, hh = vars8[4], vars8[5], vars8[6], vars8[7]
        wt = jax.lax.dynamic_index_in_dim(wbuf, t, keepdims=False)
        kt = jax.lax.dynamic_index_in_dim(k_arr, t, keepdims=False)
        ch = ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))
        bs1 = _big_sigma1(e[0], e[1])
        t1 = _add64(hh[0], hh[1], bs1[0], bs1[1])
        t1 = _add64(t1[0], t1[1], ch[0], ch[1])
        t1 = _add64(t1[0], t1[1], kt[0], kt[1])
        t1 = _add64(t1[0], t1[1], wt[0], wt[1])
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        bs0 = _big_sigma0(a[0], a[1])
        t2 = _add64(bs0[0], bs0[1], maj[0], maj[1])
        new_e = jnp.stack(_add64(d[0], d[1], t1[0], t1[1]))
        new_a = jnp.stack(_add64(t1[0], t1[1], t2[0], t2[1]))
        return jnp.stack([new_a, a, b, c, new_e, e, f, g])

    return round_step


def _sha512_blocks(data: jnp.ndarray) -> jnp.ndarray:
    """(N, B*128) uint8 pre-padded blocks -> (N, 64) uint8 digests.

    The block count is static (part of the traced shape); the schedule
    and round loops run as fori_loops so the traced graph stays small
    (the fully unrolled form took minutes to compile). Every lane runs
    the same compression — pure SIMD over the batch like the verify
    kernel.
    """
    n = data.shape[0]
    nblocks = data.shape[1] // 128
    k_arr = jnp.asarray(_K_ARR)  # (80, 2)
    state = jnp.stack(
        [
            jnp.stack(
                [
                    jnp.full((n,), _H_HI[i], dtype=jnp.uint32),
                    jnp.full((n,), _H_LO[i], dtype=jnp.uint32),
                ]
            )
            for i in range(8)
        ]
    )  # (8, 2, N)
    for blk in range(nblocks):
        bb = data[:, blk * 128 : (blk + 1) * 128]
        bb = bb.reshape(n, 16, 8).astype(jnp.uint32)
        hi = (
            (bb[:, :, 0] << 24) | (bb[:, :, 1] << 16)
            | (bb[:, :, 2] << 8) | bb[:, :, 3]
        )  # (N, 16)
        lo = (
            (bb[:, :, 4] << 24) | (bb[:, :, 5] << 16)
            | (bb[:, :, 6] << 8) | bb[:, :, 7]
        )
        w0 = jnp.stack([hi.T, lo.T], axis=1)  # (16, 2, N)
        wbuf = jnp.concatenate(
            [w0, jnp.zeros((64, 2, n), dtype=jnp.uint32)], axis=0
        )
        wbuf = jax.lax.fori_loop(16, 80, _sched_step, wbuf)
        vars8 = jax.lax.fori_loop(0, 80, _make_round(wbuf, k_arr), state)
        lo_s = state[:, 1] + vars8[:, 1]
        carry = (lo_s < state[:, 1]).astype(jnp.uint32)
        hi_s = state[:, 0] + vars8[:, 0] + carry
        state = jnp.stack([hi_s, lo_s], axis=1)
    # (8, 2, 4, N) big-endian bytes per 64-bit word, C-order flatten
    # gives word0 hi b3..b0, word0 lo b3..b0, word1 ... = the digest.
    by = jnp.stack([(state >> s) & 0xFF for s in (24, 16, 8, 0)], axis=2)
    return by.reshape(64, n).T.astype(jnp.uint8)


# --- byte-limb Barrett reduction mod L ---------------------------------------
#
# Mirror of crypto/hashing.reduce_mod_l in radix 2^8 / int32: column
# magnitudes stay below 36 * 255^2 < 2^22, far inside int32.


def _mul_const_bytes(x: jnp.ndarray, const_bytes, out_len: int) -> jnp.ndarray:
    """(N, a) int32 byte limbs times a constant's byte limbs -> (N,
    out_len) un-carried columns (out_len >= a + len(const_bytes))."""
    a = x.shape[1]
    cols = jnp.zeros((x.shape[0], out_len), dtype=jnp.int32)
    for j, cb in enumerate(const_bytes):
        cols = cols.at[:, j : j + a].add(x * cb)
    return cols


def _carry_bytes(cols: jnp.ndarray, nlimbs: int) -> jnp.ndarray:
    """Carry-propagate int32 columns into nlimbs byte limbs (overflow
    beyond nlimbs dropped — callers rely on the mod-2^(8*nlimbs))."""
    outs = []
    c = jnp.zeros(cols.shape[0], dtype=jnp.int32)
    for i in range(nlimbs):
        v = c + cols[:, i]
        outs.append(v & 0xFF)
        c = v >> 8
    return jnp.stack(outs, axis=1)


def _sub_l_bytes(x: jnp.ndarray):
    """(N, 32) byte limbs minus L -> (limbs, borrow_out)."""
    outs = []
    borrow = jnp.zeros(x.shape[0], dtype=jnp.int32)
    for i in range(32):
        v = x[:, i] - _L_BYTES[i] - borrow
        borrow = (v < 0).astype(jnp.int32)
        outs.append(v + (borrow << 8))
    return jnp.stack(outs, axis=1), borrow


def _reduce_mod_l_bytes(digest: jnp.ndarray) -> jnp.ndarray:
    """(N, 64) uint8 little-endian 512-bit values -> (N, 32) uint8 mod L.

    Same shift choices as the host Barrett (q from x >> 240, then
    >> 272; up to three conditional subtracts), so verdicts match the
    host path bit for bit.
    """
    x = digest.astype(jnp.int32)
    q1 = x[:, 30:]  # (N, 34): x >> 240
    q2_len = 34 + len(_MU_BYTES) + 1
    q2 = _carry_bytes(_mul_const_bytes(q1, _MU_BYTES, q2_len), q2_len)
    q = q2[:, 34:]  # >> 272; q < 2^261 fits the remaining limbs
    ql_cols = _mul_const_bytes(q, _L_BYTES, q.shape[1] + 32)
    ql = _carry_bytes(ql_cols, 32)  # mod 2^256, as on host
    outs = []
    borrow = jnp.zeros(x.shape[0], dtype=jnp.int32)
    for i in range(32):
        v = x[:, i] - ql[:, i] - borrow
        borrow = (v < 0).astype(jnp.int32)
        outs.append(v + (borrow << 8))
    r = jnp.stack(outs, axis=1)
    for _ in range(3):
        sub, borrow = _sub_l_bytes(r)
        r = jnp.where((borrow == 0)[:, None], sub, r)
    return r.astype(jnp.uint8)


def _challenge_kernel(data: jnp.ndarray) -> jnp.ndarray:
    return _reduce_mod_l_bytes(_sha512_blocks(data))


@lru_cache(maxsize=8)
def _compiled_sha512(backend: Optional[str]):
    return jax.jit(_sha512_blocks, backend=backend)


@lru_cache(maxsize=8)
def _compiled_challenge(backend: Optional[str]):
    return jax.jit(_challenge_kernel, backend=backend)


# --- host-side packing and entry points --------------------------------------


def _pack(rows: np.ndarray) -> np.ndarray:
    """(N, T) uint8 messages (all the same length) -> (N, B*128) padded
    SHA-512 blocks: 0x80, zeros, 128-bit big-endian bit length."""
    n, total = rows.shape
    padded = ((total + 17 + 127) // 128) * 128
    buf = np.zeros((n, padded), dtype=np.uint8)
    buf[:, :total] = rows
    buf[:, total] = 0x80
    buf[:, -16:] = np.frombuffer((total * 8).to_bytes(16, "big"), dtype=np.uint8)
    return buf


def device_hash_mode() -> str:
    return os.environ.get(_ENV, "auto").lower()


def _platform(backend: Optional[str]) -> str:
    try:
        if backend:
            return jax.local_devices(backend=backend)[0].platform
        return jax.default_backend()
    except Exception:
        return "unknown"


def device_hash_enabled(backend: Optional[str] = None) -> bool:
    """Whether the fused device-hash path serves eligible batches."""
    m = device_hash_mode()
    if m in ("1", "on", "true", "yes", "all"):
        return not _BROKEN
    if m in ("0", "off", "none", "false"):
        return False
    return not _BROKEN and _platform(backend) in ("tpu", "axon")


def max_msg_len() -> int:
    try:
        return max(0, int(os.environ.get(_MAXLEN_ENV, "512")))
    except ValueError:
        return 512


_BROKEN = False  # sticky per-process fallback after a kernel failure
_metrics = None
_metrics_lock = threading.Lock()
_device_lanes = 0  # guarded-by: _metrics_lock


def bind_metrics(metrics) -> None:
    global _metrics
    with _metrics_lock:
        _metrics = metrics


def _count_lanes(n: int) -> None:
    global _device_lanes
    with _metrics_lock:
        _device_lanes += n
        metrics = _metrics
    if metrics is not None:
        metrics.hash_device_lanes.inc(n)


def stats() -> dict:
    with _metrics_lock:
        return {"device_lanes": _device_lanes, "broken": _BROKEN}


def reset_stats() -> None:
    global _device_lanes
    with _metrics_lock:
        _device_lanes = 0


def sha512_device(msgs, backend: Optional[str] = None) -> np.ndarray:
    """Uniform-length messages -> (N, 64) uint8 digests, hashed on
    device (parity/test entry point; the hot path uses
    :func:`try_challenge_device`). Accepts a (N, T) uint8 matrix or a
    sequence of equal-length byte strings."""
    if isinstance(msgs, np.ndarray):
        mat = msgs.astype(np.uint8, copy=False)
    else:
        n = len(msgs)
        if n == 0:
            return np.zeros((0, 64), dtype=np.uint8)
        w = len(msgs[0])
        mat = np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(n, w)
    out = _compiled_sha512(backend)(jnp.asarray(_pack(mat)))
    return np.asarray(out)


def try_challenge_device(
    prefix: np.ndarray, msgs: Sequence[bytes], backend: Optional[str] = None
):
    """Fused challenge scalars for one chunk, or None for the host path.

    Returns a DEVICE-resident (N, 32) uint8 array of ``SHA-512(prefix_i
    || msg_i) mod L`` when the fused path applies: device hashing
    enabled for this backend and every message the same (bounded)
    length — true for the vote/commit batches that dominate consensus.
    Any kernel failure marks the path broken for the process and
    returns None; the caller's host hashing is always correct.
    """
    global _BROKEN
    if not device_hash_enabled(backend):
        return None
    n = len(msgs)
    if n == 0:
        return None
    w = len(msgs[0])
    if w > max_msg_len():
        return None
    for m in msgs:
        if len(m) != w:
            return None
    try:
        mat = np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(n, w)
        data = _pack(np.concatenate([prefix, mat], axis=1))
        out = _compiled_challenge(backend)(jnp.asarray(data))
    except Exception:
        _BROKEN = True
        import warnings

        warnings.warn(
            "device SHA-512 kernel failed; challenge hashing falls back "
            "to the host path for this process"
        )
        return None
    _count_lanes(n)
    return out
