"""Per-IP inbound connection limiting (internal/p2p/conn_tracker.go).

Caps concurrent inbound connections per remote IP so one address cannot
exhaust the node's peer slots or accept loop. ``add`` reserves a slot
(False = over the limit, reject the connection); ``remove`` releases it
when the connection dies at any stage — handshake failure included.
"""

from __future__ import annotations

import threading
from typing import Dict


class ConnTracker:
    def __init__(self, max_per_ip: int = 16):
        self.max_per_ip = max_per_ip
        self._counts: Dict[str, int] = {}
        self._mtx = threading.Lock()

    def add(self, ip: str) -> bool:
        with self._mtx:
            n = self._counts.get(ip, 0)
            if n >= self.max_per_ip:
                return False
            self._counts[ip] = n + 1
            return True

    def remove(self, ip: str) -> None:
        with self._mtx:
            n = self._counts.get(ip, 0)
            if n <= 1:
                self._counts.pop(ip, None)
            else:
                self._counts[ip] = n - 1

    def count(self, ip: str) -> int:
        with self._mtx:
            return self._counts.get(ip, 0)

    def total(self) -> int:
        with self._mtx:
            return sum(self._counts.values())
