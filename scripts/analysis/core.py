"""tpulint core: the pluggable AST-analysis framework.

The repo's hard bugs have not been syntax errors — they were lock
discipline (a field read outside its mutex), trace purity (host work
baked into a jitted kernel), and wire compatibility (proto3 zero
omission turning consensus priority into rpc priority). Generic linters
cannot see those because the invariants are project conventions, not
language rules. This framework turns each convention into a checker:

- a :class:`Checker` subclass declares its finding ``codes`` and
  implements ``check_module`` (per-file) and/or ``check_project``
  (whole-package analyses like the dead-instrument audit);
- the :class:`Runner` parses every target file once into a
  :class:`Module` (source, AST, comment map), fans modules out to the
  enabled checkers, and diffs the findings against a checked-in
  baseline so pre-existing debt is grandfathered while NEW findings
  fail CI;
- output is ``path:line: CODE message`` — the ruff/mypy shape every
  editor already knows how to jump on.

Suppression, from most to least surgical:

- fix the code;
- inline ``# tpulint: disable=CODE1,CODE2`` on the offending line;
- a ``# guarded-by: none(<reason>)`` annotation (lock checker only);
- the baseline file (``scripts/analysis/baseline.txt``), refreshed via
  ``--update-baseline`` — for grandfathered findings that should shrink
  over time, never grow.

Baseline keys are ``path: CODE message`` *without* line numbers (an
unrelated edit above a finding must not un-grandfather it), compared as
a multiset so N identical findings in one file need N baseline entries.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from collections import Counter as _Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.txt"
)

_DISABLE_RE = re.compile(r"tpulint:\s*disable=([A-Z0-9, ]+)")


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``path:line: CODE message``."""

    path: str  # repo-relative, forward slashes
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def baseline_key(self) -> str:
        # Line numbers drift under unrelated edits; the baseline keys on
        # the stable triple instead.
        return f"{self.path}: {self.code} {self.message}"


class Module:
    """One parsed source file, shared by every checker.

    ``comments`` maps line number -> comment text (without ``#``),
    extracted with :mod:`tokenize` so a ``#`` inside a string literal
    can never masquerade as an annotation.
    """

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = (rel or path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            pass  # a file that parses but mis-tokenizes keeps an empty map

    def comment_on(self, line: int) -> str:
        return self.comments.get(line, "")

    def disabled_codes(self, line: int) -> frozenset:
        """Codes suppressed by ``# tpulint: disable=...`` on this line."""
        m = _DISABLE_RE.search(self.comments.get(line, ""))
        if not m:
            return frozenset()
        return frozenset(c.strip() for c in m.group(1).split(",") if c.strip())


class Project:
    """The whole target set, for cross-file checkers."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)

    def module(self, rel_suffix: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel.endswith(rel_suffix):
                return m
        return None


class Checker:
    """Base class: subclasses set ``name``/``codes`` and override one or
    both hooks. Findings for suppressed lines are filtered centrally."""

    name = "base"
    #: code -> one-line description (surfaced by --list-checkers)
    codes: Dict[str, str] = {}

    def check_module(self, module: Module) -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: Project) -> Iterator[Finding]:
        return iter(())


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """Child -> parent for one tree (ast has no parent links)."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def decorator_names(
    node: ast.AST,
) -> List[Tuple[str, Optional[ast.Call]]]:
    """``(terminal name, call node or None)`` per decorator on a
    class/function: ``@instrument_attrs(exclude=...)`` yields
    ``("instrument_attrs", <Call>)``, ``@sanitizer.instrument_attrs``
    yields ``("instrument_attrs", None)``."""
    out: List[Tuple[str, Optional[ast.Call]]] = []
    for dec in getattr(node, "decorator_list", []):
        call = dec if isinstance(dec, ast.Call) else None
        target = call.func if call is not None else dec
        name = dotted_name(target) or ""
        if name:
            out.append((name.split(".")[-1], call))
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``self._m._lock`` -> "self._m._lock"; None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --- discovery ---------------------------------------------------------------


def iter_py_files(roots: Sequence[str]) -> Iterator[str]:
    for root in roots:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def load_modules(roots: Sequence[str], repo_root: str = REPO_ROOT) -> List[Module]:
    modules = []
    for path in iter_py_files(roots):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, repo_root)
        with open(ap, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            modules.append(Module(ap, src, rel=rel))
        except SyntaxError as exc:
            raise SystemExit(f"tpulint: cannot parse {rel}: {exc}")
    return modules


# --- baseline ----------------------------------------------------------------


def load_baseline(
    path: str,
    repo_root: str = REPO_ROOT,
    pruned: Optional[List[str]] = None,
) -> "_Counter[str]":
    """Load the baseline multiset, dropping entries for deleted files.

    ``--update-baseline`` used to leave keys for files that no longer
    exist as permanent dead weight (they never match a finding, so they
    are never reported stale by normal runs against default paths, and
    they survive every refresh of an unrelated subtree). Each key embeds
    its repo-relative path before the first ``: ``, so prune any whose
    file is gone; callers that pass ``pruned`` get the dropped keys back
    to surface as a note.
    """
    counts: "_Counter[str]" = _Counter()
    if not os.path.exists(path):
        return counts
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            rel = line.split(": ", 1)[0]
            if not os.path.exists(os.path.join(repo_root, rel)):
                if pruned is not None:
                    pruned.append(line)
                continue
            counts[line] += 1
    return counts


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    keys = sorted(f.baseline_key() for f in findings)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(
            "# tpulint baseline: grandfathered findings (one key per line,\n"
            "# repeated keys allowed). Regenerate with\n"
            "#   python -m scripts.analysis --update-baseline\n"
            "# The goal is for this file to shrink, never grow.\n"
        )
        for k in keys:
            fh.write(k + "\n")


# --- runner ------------------------------------------------------------------


class Runner:
    def __init__(self, checkers: Sequence[Checker]):
        self.checkers = list(checkers)

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        project = Project(modules)
        findings: List[Finding] = []
        for checker in self.checkers:
            for mod in modules:
                for f in checker.check_module(mod):
                    if f.code not in mod.disabled_codes(f.line):
                        findings.append(f)
            for f in checker.check_project(project):
                mod = next(
                    (m for m in modules if m.rel == f.path), None
                )
                if mod is None or f.code not in mod.disabled_codes(f.line):
                    findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.code, f.message))
        return findings


def diff_baseline(
    findings: Sequence[Finding], baseline: "_Counter[str]"
) -> Tuple[List[Finding], List[str]]:
    """(new findings not covered by the baseline, stale baseline keys)."""
    remaining = _Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
        else:
            new.append(f)
    stale = sorted(
        key for key, n in remaining.items() for _ in range(n) if n > 0
    )
    return new, stale
