"""HTTP JSON-RPC client (rpc/client/http analog, stdlib urllib only)."""

from __future__ import annotations

import itertools
import json
import urllib.request
from typing import Any, Dict, Optional


class RPCClientError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"RPC error {code}: {message}")
        self.code = code
        self.message = message
        self.data = data


class HTTPClient:
    """JSON-RPC over HTTP POST. Method calls are plain dicts in/out."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self._ids = itertools.count(1)

    def call(self, method: str, params: Optional[Dict[str, Any]] = None, timeout: Optional[float] = None) -> Any:
        req = {
            "jsonrpc": "2.0",
            "id": next(self._ids),
            "method": method,
            "params": params or {},
        }
        # cross-process trace propagation: when this thread is inside a
        # recorded span, ride its context as the optional "trace" member
        # so the server's handler spans link under ours in a merged
        # fleet timeline (rpc/server._dispatch attaches it)
        from tendermint_tpu.libs import tracing

        ctx = tracing.current_context()
        if ctx is not None:
            req["trace"] = ctx.to_header()
        data = json.dumps(req).encode()
        http_req = urllib.request.Request(
            self.url,
            data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(http_req, timeout=timeout or self.timeout) as resp:
            body = json.loads(resp.read().decode())
        if "error" in body and body["error"] is not None:
            e = body["error"]
            raise RPCClientError(e.get("code", -1), e.get("message", ""), e.get("data", ""))
        return body.get("result")

    # -- convenience wrappers (rpc/client/client.go surface) ------------------

    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def block(self, height: Optional[int] = None):
        return self.call("block", {"height": height} if height is not None else {})

    def commit(self, height: Optional[int] = None):
        return self.call("commit", {"height": height} if height is not None else {})

    def validators(self, height: Optional[int] = None, page: int = 1, per_page: int = 100):
        p: Dict[str, Any] = {"page": page, "per_page": per_page}
        if height is not None:
            p["height"] = height
        return self.call("validators", p)

    def broadcast_tx_sync(self, tx: bytes):
        import base64

        return self.call("broadcast_tx_sync", {"tx": base64.b64encode(tx).decode()})

    def broadcast_tx_commit(self, tx: bytes, timeout: float = 30.0):
        import base64

        return self.call(
            "broadcast_tx_commit",
            {"tx": base64.b64encode(tx).decode()},
            timeout=timeout + 5.0,
        )

    def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return self.call(
            "abci_query",
            {"path": path, "data": "0x" + data.hex(), "height": height, "prove": prove},
        )

    def abci_info(self):
        return self.call("abci_info")

    def tx(self, tx_hash: bytes):
        return self.call("tx", {"hash": "0x" + tx_hash.hex()})

    def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return self.call(
            "tx_search", {"query": query, "page": page, "per_page": per_page}
        )

    def block_search(self, query: str, page: int = 1, per_page: int = 30):
        return self.call(
            "block_search", {"query": query, "page": page, "per_page": per_page}
        )

    def events(self, query: str = "", after: int = 0, wait_time: float = 5.0, max_items: int = 100):
        params: Dict[str, Any] = {
            "maxItems": max_items,
            "after": after,
            "waitTime": wait_time,
        }
        if query:
            params["filter"] = {"query": query}
        return self.call("events", params, timeout=wait_time + 5.0)
