"""Scheduler-batched vote ingest (consensus/reactor.py VotePreverifier).

Pins the VERDICT-prescribed contract for routing concurrent vote
verifies through the accumulate-with-deadline scheduler (reference
seam: types/vote_set.go:211-222, types/validation.go:12-16):

- N concurrent single-vote submissions coalesce into at most
  ceil(N / max_batch) batch-verifier calls;
- p99 added latency stays under the scheduler's max_delay bound;
- the preverifier marks only genuinely valid votes, preserves arrival
  order, and fails OPEN (unresolvable or invalid votes are forwarded
  unmarked for the state loop's inline verify — never dropped);
- Vote.verify honors a pre-verified mark only for the exact
  (chain_id, pubkey) it was issued for.
"""

import threading
import time

import pytest

from tendermint_tpu.consensus.reactor import VotePreverifier
from tendermint_tpu.crypto.scheduler import VerifyScheduler
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Timestamp,
)
from tendermint_tpu.types.block import BlockID, PartSetHeader, Vote, VoteError

from helpers import CHAIN_ID, make_block_id, make_validators


# --- scheduler coalescing + latency (VERDICT item 2 done-criterion) --------


def test_concurrent_submissions_coalesce_and_bound_latency():
    max_batch = 64
    max_delay = 0.25
    calls = []

    def verify_fn(pks, msgs, sigs):
        calls.append(len(pks))
        return [True] * len(pks)

    sched = VerifyScheduler(verify_fn, max_batch=max_batch, max_delay=max_delay)
    sched.start()
    try:
        n = 256
        latencies = [0.0] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait()
            t0 = time.monotonic()
            assert sched.verify(b"pk%d" % i, b"msg%d" % i, b"sig%d" % i)
            latencies[i] = time.monotonic() - t0

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
    finally:
        sched.stop()

    assert sum(calls) == n
    import math

    assert len(calls) <= math.ceil(n / max_batch), calls
    latencies.sort()
    p99 = latencies[int(0.99 * n) - 1]
    assert p99 < max_delay, f"p99 added latency {p99:.4f}s >= {max_delay}s"


def test_lone_vote_answered_within_deadline():
    sched = VerifyScheduler(
        lambda p, m, s: [True] * len(p), max_batch=1024, max_delay=0.05
    )
    sched.start()
    try:
        t0 = time.monotonic()
        assert sched.verify(b"pk", b"msg", b"sig")
        dt = time.monotonic() - t0
        assert dt < 0.05 * 4  # deadline plus scheduling slack, never 1024 waits
    finally:
        sched.stop()


# --- preverifier behavior ---------------------------------------------------


class _FakeState:
    def __init__(self, validators):
        self.chain_id = CHAIN_ID
        self.validators = validators


class _FakeRS:
    def __init__(self, height, validators):
        self.height = height
        self.validators = validators


class _FakeCS:
    """The slice of ConsensusState the preverifier touches."""

    def __init__(self, height, validators):
        self.rs = _FakeRS(height, validators)
        self.state = _FakeState(validators)
        self.received = []
        self._evt = threading.Event()

    def add_vote_from_peer(self, vote, peer_id):
        self.received.append((vote, peer_id))
        self._evt.set()

    def wait_received(self, k, timeout=5.0):
        deadline = time.monotonic() + timeout
        while len(self.received) < k and time.monotonic() < deadline:
            time.sleep(0.005)
        return len(self.received) >= k


def _signed_vote(privs, vset, idx, height=5, round_=0, block_id=None):
    val = vset.validators[idx]
    vote = Vote(
        type=SIGNED_MSG_TYPE_PREVOTE,
        height=height,
        round=round_,
        block_id=block_id or make_block_id(),
        timestamp=Timestamp.from_unix_ns(1_700_000_000_000_000_000),
        validator_address=val.address,
        validator_index=idx,
    )
    vote.signature = privs[idx].sign(vote.sign_bytes(CHAIN_ID))
    return vote


@pytest.fixture()
def net(monkeypatch):
    # Back the shared scheduler with the host oracle: device batching is
    # pinned by test_ops_ed25519/test_mxu_field; here the contract under
    # test is the preverifier's behavior, which must not depend on
    # first-compile latency.
    from tendermint_tpu.crypto import batch as cbatch
    from tendermint_tpu.crypto.ed25519_ref import verify_zip215

    sched = VerifyScheduler(
        lambda pks, msgs, sigs: [
            verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)
        ],
        max_delay=0.01,
    )
    sched.start()
    monkeypatch.setattr(cbatch, "_shared_scheduler", sched)
    privs, vset = make_validators(4)
    cs = _FakeCS(height=5, validators=vset)
    pv = VotePreverifier(cs)
    pv.start()
    assert pv._warm.wait(timeout=5), "warmup must complete against host oracle"
    yield privs, vset, cs, pv
    pv.stop()
    sched.stop()


def test_valid_vote_marked_and_forwarded(net):
    privs, vset, cs, pv = net
    vote = _signed_vote(privs, vset, 1)
    pv.submit(vote, "peer-a")
    assert cs.wait_received(1)
    got, peer = cs.received[0]
    assert peer == "peer-a"
    assert got._pre_verified is not None
    assert got._pre_verified[:2] == (
        CHAIN_ID,
        vset.validators[1].pub_key.bytes(),
    )
    assert pv.batched == 1
    # the mark lets VoteSet's verify path skip the host verify
    got.verify(CHAIN_ID, vset.validators[1].pub_key)


def test_invalid_vote_forwarded_unmarked_not_dropped(net):
    privs, vset, cs, pv = net
    vote = _signed_vote(privs, vset, 2)
    vote.signature = bytes(64)  # garbage
    pv.submit(vote, "peer-b")
    assert cs.wait_received(1)
    got, _ = cs.received[0]
    assert got._pre_verified is None  # fail-open: inline path decides
    with pytest.raises(VoteError):
        got.verify(CHAIN_ID, vset.validators[2].pub_key)


def test_unresolvable_height_passes_through(net):
    privs, vset, cs, pv = net
    vote = _signed_vote(privs, vset, 0, height=99)
    pv.submit(vote, "peer-c")
    assert cs.wait_received(1)
    got, _ = cs.received[0]
    assert got._pre_verified is None
    assert pv.passthrough == 1 and pv.batched == 0


def test_order_preserved_under_mixed_outcomes(net):
    privs, vset, cs, pv = net
    votes = []
    for i in range(8):
        v = _signed_vote(privs, vset, i % 4, round_=i)
        if i % 3 == 0:
            v.signature = bytes(64)
        votes.append(v)
        pv.submit(v, f"p{i}")
    assert cs.wait_received(8)
    assert [id(v) for v, _ in cs.received] == [id(v) for v in votes]


def test_extension_pre_verified_for_precommit(net):
    privs, vset, cs, pv = net
    val = vset.validators[3]
    vote = Vote(
        type=SIGNED_MSG_TYPE_PRECOMMIT,
        height=5,
        round=0,
        block_id=make_block_id(),
        timestamp=Timestamp.from_unix_ns(1_700_000_000_000_000_000),
        validator_address=val.address,
        validator_index=3,
        extension=b"oracle-price:42",
    )
    vote.signature = privs[3].sign(vote.sign_bytes(CHAIN_ID))
    vote.extension_signature = privs[3].sign(vote.extension_sign_bytes(CHAIN_ID))
    pv.submit(vote, "peer-x")
    assert cs.wait_received(1)
    got, _ = cs.received[0]
    assert got._pre_verified_ext is not None
    assert got._pre_verified_ext[:2] == (CHAIN_ID, val.pub_key.bytes())
    got.verify_extension(CHAIN_ID, val.pub_key)


# --- the mark is key- and chain-scoped -------------------------------------


def test_mark_only_honored_for_matching_key():
    privs, vset = make_validators(2)
    vote = _signed_vote(privs, vset, 0)
    other = vset.validators[1].pub_key
    mine = vset.validators[0].pub_key
    # mark for the wrong key: verify against the right key re-verifies
    # inline (and passes, signature is genuine)
    vote.mark_pre_verified(CHAIN_ID, other.bytes())
    vote.verify(CHAIN_ID, mine)
    # a forged vote marked for a different chain id is still rejected
    forged = _signed_vote(privs, vset, 0)
    forged.signature = bytes(64)
    forged.mark_pre_verified("other-chain", mine.bytes())
    with pytest.raises(VoteError):
        forged.verify(CHAIN_ID, mine)


def test_wedged_engine_flips_cold_and_stops_feeding(monkeypatch):
    """When flushes stop returning verdicts (device wedge), the
    preverifier must go cold after MISS_LIMIT consecutive deadline
    misses — so a hung engine is no longer fed — while every affected
    vote still reaches the state machine unmarked (fail-open)."""
    from tendermint_tpu.crypto import batch as cbatch

    def stuck_verify(pks, msgs, sigs):
        time.sleep(2.0)  # far past the test's verdict deadline
        return [True] * len(pks)

    sched = VerifyScheduler(stuck_verify, max_delay=0.005)
    sched.start()
    monkeypatch.setattr(cbatch, "_shared_scheduler", sched)
    privs, vset = make_validators(4)
    cs = _FakeCS(height=5, validators=vset)
    pv = VotePreverifier(cs)
    monkeypatch.setattr(pv, "WAIT_DEADLINE", 0.05)
    pv._warm.set()  # pretend warmup succeeded before the wedge
    pv.start()
    try:
        n = pv.MISS_LIMIT + 2
        for i in range(n):
            pv.submit(_signed_vote(privs, vset, i % 4, round_=i), f"p{i}")
            time.sleep(0.08)  # let each deadline lapse -> consecutive misses
        assert cs.wait_received(n, timeout=10), len(cs.received)
        assert all(v._pre_verified is None for v, _ in cs.received)
        assert not pv._warm.is_set(), "preverifier must go cold after misses"
        assert pv._deadline_misses >= pv.MISS_LIMIT
    finally:
        pv.stop()
        sched.stop()
