"""Per-peer send-queue disciplines (p2p/pqueue.py) and their
backpressure behavior under a stalled peer.

Reference: internal/p2p/router.go:216-238 (queue factory), pqueue.go
(WDRR), rqueue.go (simple priority). The VERDICT-named gap: with one
FIFO, a flooding peer starves consensus traffic; these tests pin what
each discipline drops when the queue is full.
"""

import time

import pytest

from tendermint_tpu.p2p.pqueue import (
    DEFAULT_PRIORITIES,
    FIFOQueue,
    SimplePriorityQueue,
    WDRRQueue,
    make_send_queue,
)
from tendermint_tpu.p2p.router import Envelope

BLOCKSYNC = 0x40  # priority 5
VOTE = 0x22  # priority 10
LIGHT_BLOCK = 0x62  # priority 2


def _env(ch, i=0):
    return Envelope(ch, b"m%d" % i)


# --- factory ----------------------------------------------------------------


def test_factory_selects_types():
    assert isinstance(make_send_queue("fifo", 4), FIFOQueue)
    assert isinstance(make_send_queue("priority", 4), WDRRQueue)
    assert isinstance(make_send_queue("simple-priority", 4), SimplePriorityQueue)
    with pytest.raises(ValueError):
        make_send_queue("wdrr", 4)


# --- FIFO -------------------------------------------------------------------


def test_fifo_drops_new_on_full():
    q = FIFOQueue(3)
    assert all(q.put(_env(BLOCKSYNC, i)) for i in range(3))
    assert not q.put(_env(VOTE, 99))  # fifo has no priority lane
    assert q.get().message == b"m0"


def test_fifo_close_wakes_getter():
    q = FIFOQueue(3)
    import threading

    got = []
    t = threading.Thread(target=lambda: got.append(q.get(timeout=5)))
    t.start()
    time.sleep(0.05)
    q.close()
    t.join(timeout=2)
    assert got == [None] and q.closed


# --- WDRR priority ----------------------------------------------------------


def test_wdrr_full_queue_protects_consensus_votes():
    """The stalled-peer scenario: a blocksync flood fills the queue;
    an arriving consensus vote evicts a blocksync envelope instead of
    being dropped, and queued votes are never cannibalised by more
    blocksync traffic."""
    q = WDRRQueue(8)
    for i in range(8):
        assert q.put(_env(BLOCKSYNC, i))
    assert len(q) == 8
    # vote outranks blocksync: admitted by evicting the OLDEST blocksync
    assert q.put(_env(VOTE, 100))
    assert len(q) == 8
    assert q.dropped.get(BLOCKSYNC) == 1
    # more blocksync at full with an equal-priority floor: dropped
    assert not q.put(_env(BLOCKSYNC, 9))
    assert q.dropped.get(BLOCKSYNC) == 2
    # lower-priority statesync traffic is dropped too, not the vote
    assert not q.put(_env(LIGHT_BLOCK, 0))
    # the vote is still queued and dequeues ahead of the flood
    first = q.get()
    assert first.channel_id == VOTE


def test_wdrr_low_priority_not_starved():
    """WRR (not strict priority): under a sustained high-priority
    stream, low-priority envelopes still dequeue — at most `priority`
    high envelopes per round."""
    q = WDRRQueue(100)
    for i in range(30):
        q.put(_env(VOTE, i))
    q.put(_env(LIGHT_BLOCK, 0))
    order = [q.get().channel_id for _ in range(31)]
    pos = order.index(LIGHT_BLOCK)
    # votes have priority 10: the light-block envelope must appear
    # within the first round (10 votes + lower lanes), not after all 30
    assert pos <= 12, f"light block starved until position {pos}"


def test_wdrr_incoming_lowest_is_dropped():
    q = WDRRQueue(4)
    for i in range(4):
        assert q.put(_env(VOTE, i))
    assert not q.put(_env(LIGHT_BLOCK, 0))  # nothing lower to evict
    assert len(q) == 4


# --- simple priority --------------------------------------------------------


def test_simple_priority_orders_strictly():
    q = SimplePriorityQueue(10)
    q.put(_env(BLOCKSYNC, 0))
    q.put(_env(VOTE, 1))
    q.put(_env(LIGHT_BLOCK, 2))
    q.put(_env(VOTE, 3))
    got = [q.get().channel_id for _ in range(4)]
    assert got == [VOTE, VOTE, BLOCKSYNC, LIGHT_BLOCK]


def test_simple_priority_fifo_within_class_and_eviction():
    q = SimplePriorityQueue(3)
    q.put(_env(VOTE, 0))
    q.put(_env(BLOCKSYNC, 1))
    q.put(_env(BLOCKSYNC, 2))
    # full; a vote evicts the newest lowest-priority envelope
    assert q.put(_env(VOTE, 3))
    assert q.dropped.get(BLOCKSYNC) == 1
    got = [q.get().message for _ in range(3)]
    assert got == [b"m0", b"m3", b"m1"]  # votes FIFO, then old blocksync
    # full of votes: lower-priority incoming is rejected
    q2 = SimplePriorityQueue(2)
    q2.put(_env(VOTE, 0))
    q2.put(_env(VOTE, 1))
    assert not q2.put(_env(BLOCKSYNC, 9))


# --- router integration -----------------------------------------------------


def test_router_uses_configured_discipline():
    from tests.test_p2p import make_router
    from tendermint_tpu.p2p.transport import MemoryNetwork
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.peermanager import PeerAddress, PeerManager
    from tendermint_tpu.p2p.router import Router
    from tendermint_tpu.p2p.transport import NodeInfo

    net = MemoryNetwork()
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network="q-chain", listen_addr="nq1")
    pm = PeerManager(nk.node_id)
    r1 = Router(info, pm, net.transport("nq1"), queue_type="priority")
    r2, nk2, pm2 = make_router(net, "nq2", chain="q-chain")
    ch_vote = r1.open_channel(VOTE)
    ch_bs = r1.open_channel(BLOCKSYNC)
    r2.open_channel(VOTE)
    r2.open_channel(BLOCKSYNC)
    r1.start()
    r2.start()
    try:
        pm.add_address(PeerAddress(nk2.node_id, "nq2"))
        deadline = time.monotonic() + 5
        while not r1.connected_peers() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert r1.connected_peers() == [nk2.node_id]
        sq = r1._peer_send_queues[nk2.node_id]
        assert isinstance(sq, WDRRQueue)
        ch_vote.broadcast(b"a vote")
        ch_bs.broadcast(b"a block")
    finally:
        r1.stop()
        r2.stop()


def test_router_rejects_unknown_queue_type():
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.peermanager import PeerManager
    from tendermint_tpu.p2p.router import Router
    from tendermint_tpu.p2p.transport import MemoryNetwork, NodeInfo

    net = MemoryNetwork()
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network="x", listen_addr="nx")
    with pytest.raises(ValueError):
        Router(info, PeerManager(nk.node_id), net.transport("nx"),
               queue_type="bogus")


# --- disconnect perturbation (router quarantine) ----------------------------


def test_disconnect_all_drops_and_reconnects():
    """unsafe_disconnect_peers' engine: all peers drop, dial/accept stay
    quarantined for the duration, then persistent-peer retry reconnects
    (the e2e 'disconnect' perturbation, perturb.go:42-72 analog)."""
    from tests.test_p2p import make_router
    from tendermint_tpu.p2p.peermanager import PeerAddress
    from tendermint_tpu.p2p.transport import MemoryNetwork

    net = MemoryNetwork()
    r1, nk1, pm1 = make_router(net, "dq1")
    r2, nk2, pm2 = make_router(net, "dq2")
    r1.open_channel(0x7E)
    r2.open_channel(0x7E)
    r1.start()
    r2.start()
    try:
        pm1.add_address(PeerAddress(nk2.node_id, "dq2"), persistent=True)
        deadline = time.monotonic() + 5
        while not r1.connected_peers() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert r1.connected_peers()

        dropped = r1.disconnect_all(duration=1.0)
        assert dropped == 1
        assert r1.connected_peers() == []
        # still quarantined shortly after: no reconnect yet
        time.sleep(0.3)
        assert r1.connected_peers() == []
        # after the quarantine lapses the persistent peer comes back
        deadline = time.monotonic() + 20
        while not r1.connected_peers() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert r1.connected_peers() == [nk2.node_id], "no reconnect"
    finally:
        r1.stop()
        r2.stop()
