"""Operator CLI: ``python -m tendermint_tpu <command>``.

The cmd/tendermint analog (main.go:29-61). Commands:

  init            scaffold a home dir (config.toml, genesis, keys)
  start           run a node (or a PEX-only seed with mode="seed")
  testnet         generate N localhost validator home dirs
  show-node-id    print the p2p identity
  show-validator  print the validator pubkey JSON
  unsafe-reset-all  wipe chain data, keep keys (reset privval state)
  rollback        roll state back one height (rollback.go)
  inspect         chain state of a STOPPED node (JSON, or --serve RPC)
  replay          re-sync the ABCI app from the block store (Handshaker)
  light           light-client RPC proxy verified from a trust anchor
  verifyd         run the shared verification daemon (owns the device)
  debug dump      diagnostic tarball from a RUNNING node
  wal2json        decode a consensus WAL to JSON records
  abci            drive an ABCI socket app (info/echo/query/check-tx)
  compact-db      drop dead filedb records (node stopped)
  key-migrate     re-encode every store into another backend/engine dir
  reindex-event   rebuild the tx/block index from stored blocks
  confix          migrate config.toml to the current schema

Every command takes ``--home`` (default ``~/.tendermint_tpu``). The node
stack is the library's own — no pytest involved — which is the round-2
gap this closes: a node runnable from the shell.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time
from typing import List, Optional

from tendermint_tpu.config import Config

DEFAULT_HOME = os.path.expanduser("~/.tendermint_tpu")


def _load_cfg(args) -> Config:
    return Config.load(args.home)


# --- init -------------------------------------------------------------------


def cmd_init(args) -> int:
    """commands/init.go: config + genesis + node key + privval key."""
    from tendermint_tpu.encoding.canonical import Timestamp
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    cfg = Config(home=args.home)
    if os.path.exists(cfg.config_file()) and not args.force:
        print(f"found existing config at {cfg.config_file()}", file=sys.stderr)
        return 1
    os.makedirs(cfg.config_dir(), exist_ok=True)
    os.makedirs(cfg.data_dir(), exist_ok=True)
    cfg.save()

    NodeKey.load_or_gen(cfg.node_key_file())
    pv = FilePV.load_or_generate(
        cfg.privval_key_file(), cfg.privval_state_file()
    )

    if not os.path.exists(cfg.genesis_file()):
        chain_id = args.chain_id or f"test-chain-{os.urandom(3).hex()}"
        doc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Timestamp.from_unix_ns(time.time_ns()),
            validators=[
                GenesisValidator(pub_key=pv.get_pub_key(), power=10)
            ],
        )
        doc.save_as(cfg.genesis_file())
    print(f"initialized node home at {args.home}")
    return 0


# --- start ------------------------------------------------------------------


def _make_app_client(cfg: Config):
    """internal/proxy ClientFactory: choose the ABCI transport from the
    proxy_app string (client.go:26-66)."""
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication

    spec = cfg.base.proxy_app
    snap = cfg.base.app_snapshot_interval
    if spec == "kvstore":
        return LocalClient(KVStoreApplication(snapshot_interval=snap))
    if spec == "persistent_kvstore":
        from tendermint_tpu.storage import open_db

        os.makedirs(cfg.data_dir(), exist_ok=True)
        return LocalClient(
            KVStoreApplication(
                db=open_db("filedb", cfg.data_dir(), "app"),
                snapshot_interval=snap,
            )
        )
    if spec.startswith("tcp://"):
        from tendermint_tpu.abci.socket_client import SocketClient

        host, _, port = spec[6:].rpartition(":")
        return SocketClient(host or "127.0.0.1", int(port))
    if spec.startswith("grpc://"):
        from tendermint_tpu.abci.grpc_client import GrpcClient

        host, _, port = spec[7:].rpartition(":")
        return GrpcClient(host or "127.0.0.1", int(port))
    raise ValueError(
        f"unknown proxy_app {spec!r} "
        "(kvstore | persistent_kvstore | tcp://host:port | grpc://host:port)"
    )


def _build_node(cfg: Config):
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc

    genesis = GenesisDoc.from_file(cfg.genesis_file())
    node_cfg = cfg.to_node_config(chain_id=genesis.chain_id)
    node_key = NodeKey.load_or_gen(cfg.node_key_file())
    priv_val = None
    if not cfg.privval.laddr:
        priv_val = FilePV.load_or_generate(
            cfg.privval_key_file(), cfg.privval_state_file()
        )
    return Node(
        node_cfg,
        genesis,
        _make_app_client(cfg),
        priv_validator=priv_val,
        node_key=node_key,
    )


def _run_seed(cfg: Config) -> int:
    """Seed-only mode: PEX address gossip, no chain services."""
    from tendermint_tpu.node.seed import SeedNode
    from tendermint_tpu.types.genesis import GenesisDoc

    genesis = GenesisDoc.from_file(cfg.genesis_file())
    seed = SeedNode(
        home=cfg.config_dir(),
        chain_id=genesis.chain_id,
        listen_addr=cfg.p2p.laddr,
        bootstrap_peers=cfg.p2p.persistent_peers,
        moniker=cfg.base.moniker,
        max_connections=cfg.p2p.max_connections,
        log_level=cfg.base.log_level,
    )
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    seed.start()
    print(
        f"seed {seed.node_key.node_id} started (p2p {seed.listen_addr})",
        flush=True,
    )
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        seed.stop()
    return 0


def cmd_start(args) -> int:
    """commands/run_node.go: assemble and run until SIGINT/SIGTERM."""
    cfg = _load_cfg(args)
    if getattr(args, "trace", ""):
        cfg.base.trace = args.trace
    if cfg.base.mode not in ("full", "seed"):
        print(
            f"error: [base] mode must be 'full' or 'seed', "
            f"got {cfg.base.mode!r}",
            file=sys.stderr,
        )
        return 1
    if cfg.base.mode == "seed":
        return _run_seed(cfg)

    def _stop(_sig, _frm):
        # raising interrupts even blocking calls (accept() in the signer
        # wait, handshake replay) instead of waiting for them to finish
        raise KeyboardInterrupt

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    node = None
    try:
        node = _build_node(cfg)
        node.start()
        verify_banner = ""
        if cfg.ops.verify_remote:
            from tendermint_tpu.verifyd.client import remote_transport

            transport = remote_transport() or "tcp"
            verify_banner = (
                f", verify {cfg.ops.verify_remote} via {transport}"
            )
        print(
            f"node {node.node_key.node_id} started "
            f"(p2p {cfg.p2p.laddr}, rpc {cfg.rpc.laddr}{verify_banner})",
            flush=True,
        )
        last_height = -1
        while True:
            time.sleep(0.2)
            if node.failed is not None:
                print(f"error: {node.failed}", file=sys.stderr, flush=True)
                return 1
            if node.height != last_height:
                last_height = node.height
                print(f"height={last_height}", flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        # a second signal must not abort the shutdown mid-way
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        if node is not None:
            node.stop()
    return 0


# --- testnet ----------------------------------------------------------------


def cmd_testnet(args) -> int:
    """commands/testnet.go: N validator home dirs wired as a localhost
    mesh with a shared genesis."""
    from tendermint_tpu.encoding.canonical import Timestamp
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    n = args.validators
    homes = [os.path.join(args.output_dir, f"node{i}") for i in range(n)]
    pvs: List = []
    node_keys: List = []
    cfgs: List[Config] = []
    for i, home in enumerate(homes):
        cfg = Config(home=home)
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"127.0.0.1:{args.starting_port + 2 * i}"
        cfg.rpc.laddr = f"127.0.0.1:{args.starting_port + 2 * i + 1}"
        os.makedirs(cfg.config_dir(), exist_ok=True)
        os.makedirs(cfg.data_dir(), exist_ok=True)
        node_keys.append(NodeKey.load_or_gen(cfg.node_key_file()))
        pvs.append(
            FilePV.load_or_generate(
                cfg.privval_key_file(), cfg.privval_state_file()
            )
        )
        cfgs.append(cfg)

    chain_id = args.chain_id or f"testnet-{os.urandom(3).hex()}"
    doc = GenesisDoc(
        chain_id=chain_id,
        genesis_time=Timestamp.from_unix_ns(time.time_ns()),
        validators=[
            GenesisValidator(pub_key=pv.get_pub_key(), power=10) for pv in pvs
        ],
    )
    peers = [
        f"{node_keys[i].node_id}@{cfgs[i].p2p.laddr}" for i in range(n)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = [p for j, p in enumerate(peers) if j != i]
        cfg.save()
        doc.save_as(cfg.genesis_file())
    print(f"wrote {n} node homes under {args.output_dir} (chain {chain_id})")
    return 0


# --- key/identity inspection ------------------------------------------------


def cmd_show_node_id(args) -> int:
    from tendermint_tpu.p2p.key import NodeKey

    cfg = Config(home=args.home)
    print(NodeKey.load_or_gen(cfg.node_key_file()).node_id)
    return 0


def cmd_show_validator(args) -> int:
    import base64

    from tendermint_tpu.privval.file_pv import FilePV

    cfg = Config(home=args.home)
    pv = FilePV.load(cfg.privval_key_file(), cfg.privval_state_file())
    pub = pv.get_pub_key()
    print(
        json.dumps(
            {
                "type": pub.type,
                "value": base64.b64encode(pub.bytes()).decode(),
            }
        )
    )
    return 0


# --- data-dir surgery -------------------------------------------------------


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go: wipe <home>/data, keep keys, reset sign-state."""
    cfg = Config(home=args.home)
    if os.path.isdir(cfg.data_dir()):
        shutil.rmtree(cfg.data_dir())
    os.makedirs(cfg.data_dir(), exist_ok=True)
    # fresh privval sign-state (file.go ResetFilePV): without the data dir
    # the old one is gone already; recreate a zeroed state file
    from tendermint_tpu.privval.file_pv import FilePV

    if os.path.exists(cfg.privval_key_file()):
        FilePV.load_or_generate(
            cfg.privval_key_file(), cfg.privval_state_file()
        )
    print(f"reset chain data in {cfg.data_dir()}")
    return 0


def _open_stores(cfg: Config):
    from tendermint_tpu.state import StateStore
    from tendermint_tpu.storage import open_db
    from tendermint_tpu.storage.blockstore import BlockStore

    db_backend = cfg.base.db_backend
    state_db = open_db(db_backend, cfg.data_dir(), "state")
    block_db = open_db(db_backend, cfg.data_dir(), "blockstore")
    return StateStore(state_db), BlockStore(block_db)


def cmd_rollback(args) -> int:
    """commands/rollback.go → internal/state/rollback.go."""
    from tendermint_tpu.state.rollback import rollback_state

    cfg = _load_cfg(args)
    state_store, block_store = _open_stores(cfg)
    height, app_hash = rollback_state(
        state_store, block_store, hard=args.hard
    )
    print(f"rolled back state to height {height}, app hash {app_hash.hex()}")
    return 0


def cmd_inspect(args) -> int:
    """commands/inspect.go: read-only view over a STOPPED node's data.
    Default prints a JSON summary; --serve starts the reference's
    inspect RPC server (internal/inspect/inspect.go:31) so operators can
    run block/commit/validators/tx_search queries against the stores
    without booting consensus."""
    cfg = _load_cfg(args)
    if getattr(args, "serve", ""):
        return _inspect_serve(cfg, args.serve)
    state_store, block_store = _open_stores(cfg)
    state = state_store.load()
    out = {
        "latest_block_height": block_store.height(),
        "base_height": block_store.base(),
    }
    if state is not None and not state.is_empty():
        out.update(
            {
                "state_height": state.last_block_height,
                "app_hash": state.app_hash.hex(),
                "chain_id": state.chain_id,
                "validators": [
                    {
                        "address": v.address.hex(),
                        "power": v.voting_power,
                    }
                    for v in state.validators.validators
                ],
            }
        )
    print(json.dumps(out, indent=2))
    return 0


def _inspect_serve(cfg: Config, laddr: str) -> int:
    """Read-only RPC over the stores: the route table is the normal
    Environment's, restricted to handlers that need no live services."""
    from tendermint_tpu.indexer import KVIndexer
    from tendermint_tpu.rpc.core import Environment
    from tendermint_tpu.rpc.server import RPCServer
    from tendermint_tpu.storage import open_db
    from tendermint_tpu.types.genesis import GenesisDoc

    genesis = GenesisDoc.from_file(cfg.genesis_file())
    state_store, block_store = _open_stores(cfg)
    from tendermint_tpu.storage import db_exists

    indexer = None
    if db_exists(cfg.base.db_backend, cfg.data_dir(), "tx_index"):
        indexer = KVIndexer(
            open_db(cfg.base.db_backend, cfg.data_dir(), "tx_index")
        )
    state = state_store.load()
    env = Environment(
        genesis=genesis,
        block_store=block_store,
        state_store=state_store,
        indexer=indexer,
        get_state=lambda: state,
        is_syncing=lambda: False,
    )
    read_only = {
        name: fn
        for name, fn in env.routes().items()
        if name
        in (
            "health",
            "blockchain",
            "genesis",
            "genesis_chunked",
            "block",
            "block_by_hash",
            "block_results",
            "commit",
            "header",
            "header_by_hash",
            "validators",
            "consensus_params",
            "tx",
            "tx_search",
            "block_search",
        )
    }
    host, _, port = laddr.rpartition(":")
    server = RPCServer(read_only, host=host or "127.0.0.1", port=int(port))
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    server.start()
    print(f"inspect server on {server.url} (read-only)", flush=True)
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        server.stop()
    return 0


def cmd_replay(args) -> int:
    """commands/replay.go: hand the stored chain back to the app via the
    Handshaker (replay.go:204-550)."""
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.state import state_from_genesis
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.types.genesis import GenesisDoc

    cfg = _load_cfg(args)
    genesis = GenesisDoc.from_file(cfg.genesis_file())
    state_store, block_store = _open_stores(cfg)
    state = state_store.load()
    if state is None or state.is_empty():
        state = state_from_genesis(genesis)
    app = _make_app_client(cfg)
    app.start()
    block_exec = BlockExecutor(state_store, app, block_store)
    hs = Handshaker(state_store, block_store, block_exec, genesis)
    hs.handshake(app, state)
    print(f"replayed {hs.n_blocks_replayed} blocks into the app")
    return 0


def cmd_light(args) -> int:
    """commands/light.go: run a light-client RPC proxy verified against a
    primary full node with optional witnesses."""
    from tendermint_tpu.light.client import LightClient, TrustOptions
    from tendermint_tpu.light.provider import HTTPProvider
    from tendermint_tpu.light.proxy import LightProxy

    witnesses = [
        HTTPProvider(args.chain_id, w) for w in (args.witness or [])
    ]
    client = LightClient(
        chain_id=args.chain_id,
        trust_options=TrustOptions(
            period=args.trust_period,
            height=args.trust_height,
            hash=bytes.fromhex(args.trust_hash),
        ),
        primary=HTTPProvider(args.chain_id, args.primary),
        witnesses=witnesses,
        sequential=args.sequential,
    )
    proxy = LightProxy(client, args.primary, laddr=args.laddr)
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    proxy.start()
    print(f"light proxy for {args.chain_id} on {proxy.url}", flush=True)
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        proxy.stop()
    return 0


def _verifyd_stats(args) -> int:
    """``verifyd stats``: poll every shard's STATS_PATH gossip snapshot
    and print the fleet roll-up — per-shard rows plus the owner-wise
    aggregate, reusing the introspect owner labels so partitioned vs
    replicated table bytes are visible at a glance."""
    from tendermint_tpu.verifyd.federation import FederationClient

    shards = [a.strip() for a in (args.shards or "").split(",") if a.strip()]
    if not shards:
        shards = [args.listen]
    fed = FederationClient(shards)
    try:
        rows = fed.memstats_rows(timeout=2.0)
        if not rows:
            print("verifyd stats: no shard reachable", flush=True)
            return 1
        print(
            f"{'shard':<8} {'addr':<22} {'served':>8} {'misroute':>9} "
            f"{'pinned':>7} {'host_B':>10} {'device_B':>10}"
        )
        agg_owner: dict = {}
        agg = {"served": 0, "misroutes": 0, "pinned": 0, "host": 0}
        for label in sorted(rows):
            row = rows[label]
            dev = row.get("device_bytes") or {}
            dev_total = sum(int(v) for v in dev.values())
            for owner, n in dev.items():
                agg_owner[owner] = agg_owner.get(owner, 0) + int(n)
            served = int(row.get("requests_served", 0))
            mis = int(row.get("misroutes", 0))
            pinned = int(row.get("pinned_keys", 0))
            host_b = int(row.get("host_staged_bytes", 0))
            agg["served"] += served
            agg["misroutes"] += mis
            agg["pinned"] += pinned
            agg["host"] += host_b
            print(
                f"{label:<8} {row.get('addr', ''):<22} {served:>8} "
                f"{mis:>9} {pinned:>7} {host_b:>10} {dev_total:>10}"
            )
        print(
            f"{'fleet':<8} {'(aggregate)':<22} {agg['served']:>8} "
            f"{agg['misroutes']:>9} {agg['pinned']:>7} {agg['host']:>10} "
            f"{sum(agg_owner.values()):>10}"
        )
        for owner in sorted(agg_owner):
            print(f"  {owner}: {agg_owner[owner]} bytes (fleet)")
        tenants = fed.fleet_tenants()
        for label in sorted(tenants):
            ts = tenants[label]
            print(
                f"  tenant {label}: p99={ts['p99_ms']}ms "
                f"slo={ts['slo_ms'] or 'none'} "
                f"slo_sheds={ts['slo_sheds']} lanes={ts['lanes']}"
            )
        return 0
    finally:
        fed.close()


def cmd_verifyd(args) -> int:
    """Run the standalone verification service (verifyd/server.py): one
    resident accelerator serving batched signature verification to many
    nodes/light clients. ``--metrics HOST:PORT`` additionally serves the
    Prometheus registry (and /debug/traces) over HTTP. With
    ``--shard-id/--shards`` the daemon serves as one federation shard
    (verifyd/federation.py) and its /debug/memstats grows the fleet
    roll-up; the ``stats`` action prints that roll-up and exits."""
    from tendermint_tpu.libs.metrics import (
        EvloopMetrics,
        Registry,
        VerifydMetrics,
    )
    from tendermint_tpu.parallel import mesh
    from tendermint_tpu.verifyd.server import VerifydServer

    if args.action == "stats":
        return _verifyd_stats(args)
    mesh.manager.configure(args.mesh)
    if args.trace:
        from tendermint_tpu.libs import tracing

        tracing.configure(args.trace)
    tenant_slos = {}
    for spec in args.tenant_slo:
        name, sep, ms = spec.partition("=")
        if not sep or not name or not ms.isdigit():
            print(f"bad --tenant-slo {spec!r} (want TENANT=MS)", flush=True)
            return 2
        tenant_slos[name] = int(ms)
    host, _, port = args.listen.rpartition(":")
    reg = Registry()
    server = VerifydServer(
        host=host or "127.0.0.1",
        port=int(port),
        max_batch=args.max_batch,
        max_delay=args.max_delay,
        admission_cap=args.admission_cap,
        max_pending=args.max_pending,
        continuous=(
            None if args.continuous == "auto" else args.continuous == "on"
        ),
        pipeline_depth=args.pipeline_depth,
        tenant_cap=args.tenant_cap,
        tenant_pin_quota=args.tenant_pin_quota,
        max_tenants=args.max_tenants,
        metrics=VerifydMetrics(reg),
        evloop_metrics=EvloopMetrics(reg),
        shm=None if args.shm == "auto" else args.shm,
        dyn_batch=(
            None if args.dyn_batch == "auto" else args.dyn_batch == "on"
        ),
        tenant_slos=tenant_slos,
        shard_id=args.shard_id,
    )
    metrics_server = None
    if args.metrics:
        from tendermint_tpu.rpc.server import RPCServer

        mhost, _, mport = args.metrics.rpartition(":")
        metrics_server = RPCServer(
            {}, host=mhost or "127.0.0.1", port=int(mport),
            metrics_registry=reg,
        )
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    # installed AFTER the daemon's own handlers so a SIGTERM dumps the
    # flight-recorder ring first and then chains into the graceful stop
    from tendermint_tpu.libs import flightrec

    flightrec.install()
    # continuous kernel profiler + device-byte ledger (ops/introspect):
    # the serving tier's dispatch spans feed the per-bucket digests, and
    # the --metrics RPC server also answers GET /debug/memstats
    from tendermint_tpu.ops import introspect

    introspect.install()
    introspect.set_shard_identity(args.shard_id)
    # federated daemon: GET /debug/memstats (and the flight recorder)
    # grow a fleet section — per-shard device-byte rows polled from the
    # shard list's STATS_PATH endpoints, cached so memstats polling
    # doesn't turn into a gossip storm
    fleet_fed = None
    if args.shards:
        from tendermint_tpu.verifyd.federation import FederationClient

        fleet_fed = FederationClient(
            [a.strip() for a in args.shards.split(",") if a.strip()]
        )
        fleet_cache = {"t": -10.0, "rows": {}}

        def _fleet_rows():
            now = time.monotonic()
            if now - fleet_cache["t"] >= 2.0:
                fleet_cache["t"] = now
                fleet_cache["rows"] = fleet_fed.memstats_rows(timeout=1.0)
            return fleet_cache["rows"]

        introspect.set_fleet_provider(_fleet_rows)
    server.start()
    if metrics_server is not None:
        metrics_server.start()
    shost, sport = server.address
    shm_banner = server.shm_socket_path or "off"
    # the RESOLVED scheduler knobs (post mesh sizing, post controller):
    # what A/B runs should record as the config actually under test
    knobs = server.stats().get("scheduler") or {}
    print(
        f"verifyd serving on {shost}:{sport} "
        f"(max_batch={knobs.get('max_batch', server.max_batch)}, "
        f"max_delay={knobs.get('max_delay', args.max_delay)}s, "
        f"admission_cap={args.admission_cap}, "
        f"continuous={server.scheduler.continuous}, "
        f"pipeline_depth={knobs.get('pipeline_depth', args.pipeline_depth)}, "
        f"dyn_batch={'on' if server.dyn_batch else 'off'}, "
        f"tenant_slos={sorted(tenant_slos) if tenant_slos else 'none'}, "
        f"tenant_cap={args.tenant_cap}, "
        f"shm={shm_banner}, "
        f"shard={args.shard_id if args.shard_id >= 0 else 'standalone'}"
        f"{'/' + str(len(args.shards.split(','))) if args.shards else ''})",
        flush=True,
    )
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        if metrics_server is not None:
            metrics_server.stop()
        server.stop()
        if fleet_fed is not None:
            introspect.set_fleet_provider(None)
            fleet_fed.close()
    return 0


def cmd_lightd(args) -> int:
    """Run the light-client serving tier (light/lightd.py): a LightClient
    with a verified-header cache behind the selector event loop, serving
    ``light_header``/``light_status`` to many concurrent light clients.
    The Prometheus registry (cache traffic, serve latency, event-loop
    connections) is exposed on the same listener at GET /metrics."""
    from tendermint_tpu.libs.metrics import (
        EvloopMetrics,
        LightMetrics,
        Registry,
    )
    from tendermint_tpu.light.client import LightClient, TrustOptions
    from tendermint_tpu.light.lightd import LightServer
    from tendermint_tpu.light.provider import HTTPProvider, RetryingProvider

    if args.trace:
        from tendermint_tpu.libs import tracing

        tracing.configure(args.trace)
    reg = Registry()
    light_metrics = LightMetrics(reg)
    primary = RetryingProvider(HTTPProvider(args.chain_id, args.primary))
    witnesses = [
        RetryingProvider(HTTPProvider(args.chain_id, w))
        for w in (args.witness or [])
    ]
    client = LightClient(
        chain_id=args.chain_id,
        trust_options=TrustOptions(
            period=args.trust_period,
            height=args.trust_height,
            hash=bytes.fromhex(args.trust_hash),
        ),
        primary=primary,
        witnesses=witnesses,
        metrics=light_metrics,
    )
    host, _, port = args.listen.rpartition(":")
    server = LightServer(
        client,
        host=host or "127.0.0.1",
        port=int(port or 0),
        cache_capacity=args.cache_capacity,
        metrics=light_metrics,
        registry=reg,
        evloop_metrics=EvloopMetrics(reg),
        workers=args.workers,
    )
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    # installed AFTER the daemon's own handlers so a SIGTERM dumps the
    # flight-recorder ring first and then chains into the graceful stop
    from tendermint_tpu.libs import flightrec

    flightrec.install()
    server.start()
    print(
        f"lightd for {args.chain_id} on {server.url} "
        f"(cache_capacity={args.cache_capacity})",
        flush=True,
    )
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        server.stop()
    return 0


def cmd_debug_dump(args) -> int:
    """commands/debug/dump.go: collect a diagnostic bundle from a RUNNING
    node — status, consensus dump, net info, metrics — plus the home's
    config and WAL files, into one tar.gz."""
    import io
    import json as jsonlib
    import tarfile
    import urllib.request

    from tendermint_tpu.rpc.client import HTTPClient

    client = HTTPClient(args.rpc)
    bundle: Dict[str, bytes] = {}
    for method in (
        "status",
        "dump_consensus_state",
        "consensus_state",
        "net_info",
        "num_unconfirmed_txs",
    ):
        try:
            doc = client.call(method)
            bundle[f"{method}.json"] = jsonlib.dumps(doc, indent=2).encode()
        except Exception as e:
            bundle[f"{method}.err"] = str(e).encode()
    try:
        with urllib.request.urlopen(
            f"{args.rpc.rstrip('/')}/metrics", timeout=5
        ) as resp:
            bundle["metrics.prom"] = resp.read()
    except Exception as e:
        bundle["metrics.err"] = str(e).encode()

    home_files = []
    if args.home and os.path.isdir(args.home):
        cfg = Config(home=args.home)
        for path in [cfg.config_file(), cfg.genesis_file()]:
            if os.path.exists(path):
                home_files.append(path)
        wal_base = os.path.join(args.home, "cs.wal")
        wal_dir = os.path.dirname(wal_base)
        if os.path.isdir(wal_dir):
            for name in sorted(os.listdir(wal_dir)):
                if name.startswith("cs.wal"):
                    home_files.append(os.path.join(wal_dir, name))

    out_path = args.output
    with tarfile.open(out_path, "w:gz") as tar:
        for name, data in sorted(bundle.items()):
            info = tarfile.TarInfo(f"dump/{name}")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
        for path in home_files:
            tar.add(path, arcname=f"dump/home/{os.path.basename(path)}")
    print(f"wrote debug dump to {out_path} ({len(bundle)} rpc docs, "
          f"{len(home_files)} home files)")
    return 0


def cmd_confix(args) -> int:
    """internal/libs/confix analog: migrate a config.toml written by an
    older version to the current schema — keys the current schema lacks
    are dropped, missing keys gain defaults, known keys keep their
    values. Prints a report; --dry-run skips the rewrite."""
    import tomllib

    cfg_path = Config(home=args.home).config_file()
    with open(cfg_path, "rb") as fh:
        old_doc = tomllib.load(fh)
    cfg = Config.load(args.home)  # tolerant load: unknown keys ignored
    new_text = cfg.to_toml()
    new_doc = tomllib.loads(new_text)

    def _keys(doc):
        out = set()
        for section, table in doc.items():
            if isinstance(table, dict):
                out.update(f"{section}.{k}" for k in table)
            else:
                out.add(section)
        return out

    old_keys, new_keys = _keys(old_doc), _keys(new_doc)
    dropped = sorted(old_keys - new_keys)
    added = sorted(new_keys - old_keys)
    for key in dropped:
        print(f"  - {key} (unknown to this version; dropped)")
    for key in added:
        print(f"  + {key} (new; default applied)")
    if not dropped and not added:
        print("config already matches the current schema")
        return 0
    if getattr(args, "dry_run", False):
        print("dry run: config not rewritten")
        return 0
    backup = cfg_path + ".bak"
    shutil.copyfile(cfg_path, backup)
    cfg.save()
    print(f"rewrote {cfg_path} (backup at {backup})")
    return 0


def cmd_reindex_event(args) -> int:
    """commands/reindex_event.go analog: rebuild the tx/block event index
    from stored blocks plus the persisted FinalizeBlock responses —
    recovers search after enabling tx_index late or losing the index db.
    Run on a STOPPED node."""
    from tendermint_tpu.indexer import KVIndexer
    from tendermint_tpu.storage import db_exists, open_db

    cfg = _load_cfg(args)
    state_store, block_store = _open_stores(cfg)
    if db_exists(cfg.base.db_backend, cfg.data_dir(), "tx_index"):
        # Rebuild from scratch: merging into a stale index would keep
        # phantom records for blocks discarded by rollback. The probe
        # open proves no node holds the db before we delete it.
        probe = open_db(cfg.base.db_backend, cfg.data_dir(), "tx_index")
        probe.close()
        for f in os.listdir(cfg.data_dir()):
            if f.startswith("tx_index"):
                os.unlink(os.path.join(cfg.data_dir(), f))
    from tendermint_tpu.indexer.sink import KVEventSink, MultiSink, SQLEventSink

    # Rebuild EVERY configured sink, not just kv — the live node and the
    # offline rebuild share the sink entry point so they cannot diverge.
    sink_names = [
        "sql" if s == "psql" else s for s in (cfg.indexer.sinks or ["kv"])
    ]
    sinks = []
    idx_db = None
    if "kv" in sink_names:
        idx_db = open_db(cfg.base.db_backend, cfg.data_dir(), "tx_index")
        sinks.append(KVEventSink(KVIndexer(idx_db)))
    if "sql" in sink_names:
        import sqlite3

        sql_path = os.path.join(cfg.data_dir(), "tx_events.sqlite")
        if os.path.exists(sql_path):
            os.unlink(sql_path)  # rebuild from scratch, as with kv
        chain_id = ""
        try:
            from tendermint_tpu.types.genesis import GenesisDoc

            chain_id = GenesisDoc.from_file(cfg.genesis_file()).chain_id
        except Exception:
            pass
        sinks.append(
            SQLEventSink(sqlite3.connect(sql_path), chain_id or "unknown")
        )
    sink = MultiSink(sinks)
    base = max(block_store.base(), 1)
    height = block_store.height()
    indexed_blocks = indexed_txs = skipped = 0
    for h in range(base, height + 1):
        block = block_store.load_block(h)
        fres = state_store.load_decoded_finalize_block_response(h)
        if block is None or fres is None:
            skipped += 1
            continue
        # same single entry point the live node writes through, so the
        # rebuilt index is byte-identical to what the node would produce
        sink.index_finalized_block(h, block.data.txs, fres)
        indexed_blocks += 1
        indexed_txs += min(len(fres.tx_results), len(block.data.txs))
    sink.close()
    if idx_db is not None:
        idx_db.close()
    print(
        f"reindexed {indexed_blocks} blocks, {indexed_txs} txs "
        f"({skipped} heights skipped: block or responses pruned)"
    )
    return 0


def cmd_key_migrate(args) -> int:
    """scripts/keymigrate (cmd/tendermint/main.go:29-61 key-migrate):
    re-encode every store into a (possibly different) backend. The
    reference migrates legacy key formats to orderedcode in place; here
    the same walk serves backend migration (filedb <-> memdb snapshots,
    forcing the C++ or Python filedb engine), which is this tree's
    only key-format seam. Run on a STOPPED node."""
    from tendermint_tpu.storage import open_db

    cfg = Config(home=args.home)
    data = cfg.data_dir()
    if not os.path.isdir(data):
        raise FileNotFoundError(data)
    names = sorted(
        f[: -len(".fdb")] for f in os.listdir(data) if f.endswith(".fdb")
    )
    if not names:
        print(f"no databases to migrate in {data}")
        return 0
    out_dir = args.out or (data.rstrip(os.sep) + "-migrated")
    if os.path.abspath(out_dir) == os.path.abspath(data):
        print("error: --out must differ from the data dir", file=sys.stderr)
        return 1
    if os.path.isdir(out_dir) and os.listdir(out_dir):
        # Merging into a stale snapshot would silently keep records the
        # source has since deleted — a corrupt "migration".
        print(
            f"error: output dir {out_dir} is not empty; remove it or "
            "pass a fresh --out",
            file=sys.stderr,
        )
        return 1
    os.makedirs(out_dir, exist_ok=True)
    for name in names:
        src = open_db("filedb", data, name)
        dst = open_db(args.to_backend, out_dir, name)
        n = 0
        batch = dst.new_batch()
        for k, v in src.iterator():
            batch.set(k, v)
            n += 1
            if n % 10000 == 0:
                batch.write()
                batch = dst.new_batch()
        batch.write()
        src.close()
        dst.close()
        print(f"{name}: migrated {n} keys -> {args.to_backend} in {out_dir}")
    return 0


def cmd_compact_db(args) -> int:
    """commands/compact.go analog: rewrite every filedb in <home>/data
    dropping dead (overwritten/deleted) records. Run on a STOPPED node."""
    from tendermint_tpu.storage import open_db

    cfg = Config(home=args.home)
    data = cfg.data_dir()
    if not os.path.isdir(data):
        raise FileNotFoundError(data)
    names = sorted(
        f[: -len(".fdb")] for f in os.listdir(data) if f.endswith(".fdb")
    )
    if not names:
        print(f"no filedb databases in {data}")
        return 0
    for name in names:
        path = os.path.join(data, name + ".fdb")
        before = os.path.getsize(path)
        db = open_db("filedb", data, name)
        db.compact()
        db.close()
        after = os.path.getsize(path)
        print(
            f"{name}.fdb: {before} -> {after} bytes "
            f"({(1 - after / before) * 100 if before else 0:.0f}% reclaimed)"
        )
    return 0


def cmd_wal2json(args) -> int:
    """scripts/wal2json analog: decode a consensus WAL (all rotated
    chunks) to one JSON document per record on stdout."""
    import dataclasses
    import json as jsonlib

    from tendermint_tpu.consensus import wal as walmod

    if not os.path.exists(args.wal):
        # an empty group and a typo'd path look identical to the reader;
        # distinguish them here (main() maps this to a clean error)
        raise FileNotFoundError(args.wal)
    w = walmod.WAL(args.wal)
    for offset, msg in w.iter_messages():
        doc: Dict[str, object] = {"offset": offset, "type": type(msg).__name__}
        if dataclasses.is_dataclass(msg):
            for f in dataclasses.fields(msg):
                v = getattr(msg, f.name)
                if isinstance(v, bytes):
                    v = v.hex()
                elif dataclasses.is_dataclass(v) or hasattr(v, "__dict__"):
                    v = repr(v)
                doc[f.name] = v
        else:
            doc["repr"] = repr(msg)
        print(jsonlib.dumps(doc, default=repr))
    return 0


def cmd_abci(args) -> int:
    """abci/cmd/abci-cli analog: drive an ABCI socket app manually."""
    import base64
    import json as jsonlib

    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.socket_client import SocketClient

    host, _, port = args.addr.replace("tcp://", "").rpartition(":")
    client = SocketClient(host or "127.0.0.1", int(port))
    client.start()
    try:
        if args.abci_cmd == "info":
            r = client.info(abci.RequestInfo())
            print(
                jsonlib.dumps(
                    {
                        "data": r.data,
                        "version": r.version,
                        "app_version": r.app_version,
                        "last_block_height": r.last_block_height,
                        "last_block_app_hash": r.last_block_app_hash.hex(),
                    }
                )
            )
        elif args.abci_cmd == "echo":
            r = client.echo(args.message)
            print(r)
        elif args.abci_cmd == "query":
            r = client.query(
                abci.RequestQuery(
                    data=args.data.encode(), path=args.path or ""
                )
            )
            print(
                jsonlib.dumps(
                    {
                        "code": r.code,
                        "key": base64.b64encode(r.key).decode(),
                        "value": base64.b64encode(r.value).decode(),
                        "log": r.log,
                        "height": r.height,
                    }
                )
            )
        elif args.abci_cmd == "check-tx":
            r = client.check_tx(
                abci.RequestCheckTx(
                    tx=args.tx.encode(), type=abci.CHECK_TX_TYPE_NEW
                )
            )
            print(
                jsonlib.dumps({"code": r.code, "codespace": r.codespace})
            )
    finally:
        client.stop()
    return 0


# --- entry ------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tendermint_tpu",
        description="TPU-native BFT state-machine-replication node",
    )
    ap.add_argument(
        "--home",
        default=os.environ.get("TMHOME", DEFAULT_HOME),
        help="node home directory",
    )
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="scaffold config/genesis/keys")
    p.add_argument("--chain-id", default="")
    p.add_argument("--force", action="store_true")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run the node")
    p.add_argument(
        "--trace",
        default="",
        help="span tracing: off | ring (serve at /debug/traces) | "
        "<path> (write Chrome-trace JSON at exit); overrides config/env",
    )
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("testnet", help="generate localhost testnet homes")
    p.add_argument("--validators", "-v", type=int, default=4)
    p.add_argument("--output-dir", "-o", default="./testnet")
    p.add_argument("--chain-id", default="")
    p.add_argument("--starting-port", type=int, default=26656)
    p.set_defaults(fn=cmd_testnet)

    p = sub.add_parser("show-node-id", help="print p2p identity")
    p.set_defaults(fn=cmd_show_node_id)

    p = sub.add_parser("show-validator", help="print validator pubkey")
    p.set_defaults(fn=cmd_show_validator)

    p = sub.add_parser(
        "unsafe-reset-all", help="wipe chain data, keep keys"
    )
    p.set_defaults(fn=cmd_unsafe_reset_all)

    p = sub.add_parser("rollback", help="roll state back one height")
    p.add_argument(
        "--hard", action="store_true", help="also delete the block"
    )
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser("inspect", help="dump stored chain state (node stopped)")
    p.add_argument(
        "--serve",
        default="",
        metavar="HOST:PORT",
        help="serve a read-only RPC over the stores instead of printing",
    )
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("replay", help="replay stored blocks into the app")
    p.set_defaults(fn=cmd_replay)

    p = sub.add_parser("light", help="run a light-client RPC proxy")
    p.add_argument("primary", help="primary full node RPC url")
    p.add_argument("--chain-id", required=True)
    p.add_argument("--trust-height", type=int, required=True)
    p.add_argument("--trust-hash", required=True, help="hex header hash")
    p.add_argument("--trust-period", type=float, default=14 * 86400.0)
    p.add_argument("--witness", action="append", default=[])
    p.add_argument("--laddr", default="127.0.0.1:0")
    p.add_argument("--sequential", action="store_true")
    p.set_defaults(fn=cmd_light)

    p = sub.add_parser(
        "verifyd", help="run the shared verification daemon"
    )
    p.add_argument(
        "action", nargs="?", choices=("serve", "stats"), default="serve",
        help="serve (default) runs the daemon; stats prints a fleet "
        "roll-up (per-shard rows + aggregate) from --shards/--listen",
    )
    p.add_argument(
        "--listen", default="127.0.0.1:26670", metavar="HOST:PORT",
        help="gRPC listen address",
    )
    p.add_argument(
        "--shard-id", type=int, default=-1,
        help="this daemon's federation shard ordinal (stamped on every "
        "response, wire field 6; -1 = standalone)",
    )
    p.add_argument(
        "--shards", default="", metavar="HOST:PORT,HOST:PORT,...",
        help="the full federation shard list (verifyd/federation.py): "
        "clients consistent-hash validator-set digests across it; a "
        "serving daemon also uses it for the /debug/memstats fleet "
        "roll-up, and `verifyd stats` polls it",
    )
    p.add_argument(
        "--max-batch", type=int, default=None,
        help="flush when this many lanes are pending "
        "(default: 256 × mesh devices)",
    )
    p.add_argument(
        "--mesh", type=int, default=0,
        help="devices the sharded verify engine may span "
        "(0 = all; 1 disables sharding; TENDERMINT_TPU_MESH applies at 0)",
    )
    p.add_argument(
        "--max-delay", type=float, default=0.002,
        help="max seconds the oldest lane waits before a flush",
    )
    p.add_argument(
        "--admission-cap", type=int, default=1024,
        help="pending-lane ceiling before light/rpc load is shed",
    )
    p.add_argument(
        "--max-pending", type=int, default=4096,
        help="hard pending-lane cap for ALL classes",
    )
    p.add_argument(
        "--continuous", choices=("auto", "on", "off"), default="auto",
        help="continuous batching (dispatch pipeline): auto follows "
        "TENDERMINT_TPU_CONT_BATCH (default on); off restores the "
        "flush-barrier path",
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="dispatches outstanding at once under continuous batching",
    )
    p.add_argument(
        "--tenant-cap", type=int, default=512,
        help="outstanding sheddable lanes one tenant may hold",
    )
    p.add_argument(
        "--tenant-pin-quota", type=int, default=256,
        help="resident-table pins one tenant may hold (ops/resident.py)",
    )
    p.add_argument(
        "--max-tenants", type=int, default=16,
        help="distinct tenant metric/budget buckets; overflow shares one",
    )
    p.add_argument(
        "--shm", choices=("auto", "on", "off"), default="auto",
        help="zero-copy shared-memory ingress for co-located callers "
        "(verifyd/shm.py): auto follows TENDERMINT_TPU_SHM; off is "
        "pure TCP",
    )
    p.add_argument(
        "--dyn-batch", choices=("auto", "on", "off"), default="auto",
        help="deadline-aware dynamic batching (crypto/adaptive.py): "
        "auto follows TENDERMINT_TPU_DYN_BATCH (default on); off pins "
        "the static max-batch/max-delay config",
    )
    p.add_argument(
        "--tenant-slo", action="append", default=[],
        metavar="TENANT=MS",
        help="declare a tenant's p99 latency target in ms (repeatable); "
        "sustained breach sheds that tenant's light/rpc traffic before "
        "the global brownout ladder moves",
    )
    p.add_argument(
        "--metrics", default="", metavar="HOST:PORT",
        help="serve /metrics (and /debug/traces) here",
    )
    p.add_argument(
        "--trace", default="",
        help="span tracing: off | ring | <chrome-trace path>",
    )
    p.set_defaults(fn=cmd_verifyd)

    p = sub.add_parser(
        "lightd", help="run the light-client serving tier"
    )
    p.add_argument("primary", help="primary full node RPC url")
    p.add_argument("--chain-id", required=True)
    p.add_argument("--trust-height", type=int, required=True)
    p.add_argument("--trust-hash", required=True, help="hex header hash")
    p.add_argument("--trust-period", type=float, default=14 * 86400.0)
    p.add_argument("--witness", action="append", default=[])
    p.add_argument(
        "--listen", default="127.0.0.1:26671", metavar="HOST:PORT",
        help="JSON-RPC listen address (also serves /metrics)",
    )
    p.add_argument(
        "--cache-capacity", type=int, default=10_000,
        help="verified-header cache size (LRU entries)",
    )
    p.add_argument(
        "--workers", type=int, default=None,
        help="event-loop worker threads (default: evloop default)",
    )
    p.add_argument(
        "--trace", default="",
        help="span tracing: off | ring | <chrome-trace path>",
    )
    p.set_defaults(fn=cmd_lightd)

    p = sub.add_parser(
        "debug", help="collect diagnostics from a running node"
    )
    dsub = p.add_subparsers(dest="debug_cmd", required=True)
    d = dsub.add_parser("dump", help="status+consensus+metrics+WAL tarball")
    d.add_argument("--rpc", default="http://127.0.0.1:26657")
    d.add_argument("--output", "-o", default="tm-debug-dump.tgz")
    d.set_defaults(fn=cmd_debug_dump)

    p = sub.add_parser(
        "compact-db", help="compact filedb databases (node stopped)"
    )
    p.set_defaults(fn=cmd_compact_db)

    p = sub.add_parser(
        "key-migrate",
        help="re-encode every store into another backend/engine dir",
    )
    p.add_argument(
        "--to-backend", default="filedb-c",
        choices=["filedb", "filedb-c", "filedb-py"],
    )
    p.add_argument("--out", default="", help="output data dir (must differ)")
    p.set_defaults(fn=cmd_key_migrate)

    p = sub.add_parser(
        "reindex-event",
        help="rebuild the tx/block event index from stored blocks",
    )
    p.set_defaults(fn=cmd_reindex_event)

    p = sub.add_parser(
        "confix", help="migrate config.toml to the current schema"
    )
    p.add_argument("--dry-run", action="store_true")
    p.set_defaults(fn=cmd_confix)

    p = sub.add_parser("wal2json", help="decode a consensus WAL to JSON")
    p.add_argument("wal", help="path to the WAL head file")
    p.set_defaults(fn=cmd_wal2json)

    p = sub.add_parser("abci", help="drive an ABCI socket app manually")
    asub = p.add_subparsers(dest="abci_cmd", required=True)
    a = asub.add_parser("info")
    a.add_argument("--addr", default="tcp://127.0.0.1:26658")
    a.set_defaults(fn=cmd_abci)
    a = asub.add_parser("echo")
    a.add_argument("message")
    a.add_argument("--addr", default="tcp://127.0.0.1:26658")
    a.set_defaults(fn=cmd_abci)
    a = asub.add_parser("query")
    a.add_argument("data")
    a.add_argument("--path", default="")
    a.add_argument("--addr", default="tcp://127.0.0.1:26658")
    a.set_defaults(fn=cmd_abci)
    a = asub.add_parser("check-tx")
    a.add_argument("tx")
    a.add_argument("--addr", default="tcp://127.0.0.1:26658")
    a.set_defaults(fn=cmd_abci)

    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        print(f"error: {e} (run `init` first?)", file=sys.stderr)
        return 1
    except BrokenPipeError:
        return 0  # stdout consumer (e.g. `head`) closed early
    except (ValueError, OSError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except Exception as e:
        # operator-facing failures from deeper layers (a remote signer
        # never dialing in, a corrupt WAL under wal2json) should read as
        # errors, not tracebacks
        from tendermint_tpu.consensus.wal import WALCorruptionError
        from tendermint_tpu.privval.remote import RemoteSignerError

        if isinstance(e, (RemoteSignerError, WALCorruptionError)):
            print(f"error: {e}", file=sys.stderr)
            return 1
        raise


if __name__ == "__main__":
    raise SystemExit(main())
