"""Host-side precomputed tables for the device verifier.

The fixed-base table [0..15]B (extended coordinates, Z=1) is computed
once at import with the pure-Python oracle and shipped to the device as
a constant — the analog of curve25519-voi's precomputed basepoint tables
(reference dependency of crypto/ed25519).
"""

from __future__ import annotations

import numpy as np

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.ops.field import NLIMBS, P, int_to_limbs


def _affine_extended(pt) -> tuple:
    """Oracle extended point -> affine extended (x, y, 1, x*y) ints."""
    x_, y_, z_, _ = pt
    zinv = pow(z_, P - 2, P)
    x = x_ * zinv % P
    y = y_ * zinv % P
    return (x, y, 1, x * y % P)


def _point_limbs(pt) -> np.ndarray:
    """(4, 20) int32 limbs for one affine-extended point."""
    return np.array([int_to_limbs(c) for c in _affine_extended(pt)], dtype=np.int32)


def _build_base_table(width: int = 16) -> np.ndarray:
    """(width, 4, 20, 1) multiples [0..width-1]B; index 0 = identity."""
    out = np.zeros((width, 4, NLIMBS), dtype=np.int32)
    out[0] = np.array(
        [int_to_limbs(0), int_to_limbs(1), int_to_limbs(1), int_to_limbs(0)],
        dtype=np.int32,
    )
    acc = ref.B_POINT
    for i in range(1, width):
        out[i] = _point_limbs(acc)
        acc = ref.pt_add(acc, ref.B_POINT)
    return out[:, :, :, None]  # broadcastable over batch


B_TABLE = _build_base_table()
