"""Consensus state machine tests.

The in-process analog of internal/consensus/state_test.go: a single
validator self-commits blocks ("onlyValidatorIsUs", node/node.go:286-294),
and a 4-validator in-process network (common_test.go style, with the
loopback broadcaster playing the role of the in-memory p2p transport)
reaches consensus across rounds.
"""

import threading
import time

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.abci import types as abci
from tendermint_tpu.consensus.state import Broadcaster, ConsensusState
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.privval import FilePV
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.storage import MemDB
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.params import ConsensusParams, TimeoutParams

CHAIN_ID = "cons-chain"
BASE_NS = 1_700_000_000_000_000_000


def fast_params() -> ConsensusParams:
    p = ConsensusParams()
    p.timeout = TimeoutParams(
        propose=0.5, propose_delta=0.1, vote=0.2, vote_delta=0.1, commit=0.05
    )
    return p


def build_validator(tmp_path, n_vals=1, index=0, privs=None):
    """One validator's full stack: app + stores + executor + consensus."""
    if privs is None:
        privs = [
            FilePV.generate(
                str(tmp_path / f"key{i}.json"), str(tmp_path / f"state{i}.json")
            )
            for i in range(n_vals)
        ]
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp.from_unix_ns(BASE_NS),
        consensus_params=fast_params(),
        validators=[
            GenesisValidator(pub_key=pv.get_pub_key(), power=10) for pv in privs
        ],
    )
    sm_state = state_from_genesis(gen)
    app = KVStoreApplication()
    client = LocalClient(app)
    client.start()
    init = client.init_chain(abci.RequestInitChain(chain_id=CHAIN_ID, initial_height=1))
    sm_state.app_hash = init.app_hash
    state_store = StateStore(MemDB())
    state_store.save(sm_state)
    block_store = BlockStore(MemDB())
    block_exec = BlockExecutor(state_store, client, block_store)
    cs = ConsensusState(
        sm_state,
        block_exec,
        block_store,
        priv_validator=privs[index],
        wal=WAL(str(tmp_path / f"wal{index}.log")),
    )
    return cs, privs, app


def wait_for_height(cs_list, height, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(cs.block_store.height() >= height for cs in cs_list):
            return True
        time.sleep(0.02)
    return False


class TestSingleValidator:
    def test_self_commits_blocks(self, tmp_path):
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        try:
            assert wait_for_height([cs], 3), (
                f"only reached height {cs.block_store.height()}"
            )
        finally:
            cs.stop()
        # Chain is verifiable: every stored commit validates.
        from tendermint_tpu.types import verify_commit

        for h in range(1, 3):
            commit = cs.block_store.load_block_commit(h)
            meta = cs.block_store.load_block_meta(h)
            vals = cs.block_exec.state_store.load_validators(h)
            verify_commit(CHAIN_ID, vals, meta.block_id, h, commit)

    def test_wal_replay_restart(self, tmp_path):
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        assert wait_for_height([cs], 2)
        cs.stop()
        height_before = cs.block_store.height()
        # Restart from the same stores + WAL: must resume, not double-sign.
        sm_state = cs.block_exec.state_store.load()
        cs2 = ConsensusState(
            sm_state,
            cs.block_exec,
            cs.block_store,
            priv_validator=privs[0],
            wal=WAL(str(tmp_path / "wal0.log")),
        )
        cs2.start()
        try:
            assert wait_for_height([cs2], height_before + 2)
        finally:
            cs2.stop()


class LoopbackNet(Broadcaster):
    """In-process 'network': every broadcast is delivered to all other
    validators' peer queues (the p2ptest memory-transport analog)."""

    def __init__(self):
        self.nodes = []

    def attach(self, cs):
        net = self

        class NodeB(Broadcaster):
            def broadcast_proposal(self, proposal):
                net.deliver(cs, "proposal", proposal)

            def broadcast_block_part(self, height, round_, part):
                net.deliver(cs, "part", (height, round_, part))

            def broadcast_vote(self, vote):
                net.deliver(cs, "vote", vote)

        cs.broadcaster = NodeB()
        self.nodes.append(cs)

    def deliver(self, sender, kind, payload):
        for node in self.nodes:
            if node is sender:
                continue
            if kind == "proposal":
                node.add_proposal_from_peer(payload, "peer")
            elif kind == "part":
                h, r, p = payload
                node.add_block_part_from_peer(h, r, p, "peer")
            else:
                node.add_vote_from_peer(payload, "peer")


class TestFourValidatorNetwork:
    def test_network_commits(self, tmp_path):
        privs = [
            FilePV.generate(
                str(tmp_path / f"key{i}.json"), str(tmp_path / f"state{i}.json")
            )
            for i in range(4)
        ]
        net = LoopbackNet()
        nodes = []
        for i in range(4):
            cs, _, _ = build_validator(tmp_path, n_vals=4, index=i, privs=privs)
            net.attach(cs)
            nodes.append(cs)
        for cs in nodes:
            cs.start()
        try:
            assert wait_for_height(nodes, 3, timeout=60), (
                f"heights: {[cs.block_store.height() for cs in nodes]}"
            )
            # All nodes converged on identical blocks.
            h1 = [cs.block_store.load_block_meta(1).block_id for cs in nodes]
            assert all(b == h1[0] for b in h1)
        finally:
            for cs in nodes:
                cs.stop()

    def test_network_survives_one_silent_node(self, tmp_path):
        privs = [
            FilePV.generate(
                str(tmp_path / f"key{i}.json"), str(tmp_path / f"state{i}.json")
            )
            for i in range(4)
        ]
        net = LoopbackNet()
        nodes = []
        for i in range(4):
            cs, _, _ = build_validator(tmp_path, n_vals=4, index=i, privs=privs)
            net.attach(cs)
            nodes.append(cs)
        # Node 3 never starts: 3/4 = 30/40 power > 2/3 still commits.
        for cs in nodes[:3]:
            cs.start()
        try:
            assert wait_for_height(nodes[:3], 2, timeout=90), (
                f"heights: {[cs.block_store.height() for cs in nodes[:3]]}"
            )
        finally:
            for cs in nodes[:3]:
                cs.stop()


class TestPeerRobustness:
    def test_malformed_peer_input_does_not_kill_loop(self, tmp_path):
        """A bad proposal signature or bogus block part from a peer must be
        dropped, not crash the receive routine (liveness)."""
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        try:
            from tendermint_tpu.types import Proposal
            from tendermint_tpu.types.part_set import Part
            from tendermint_tpu.crypto import merkle
            from tests.helpers import make_block_id

            bad = Proposal(
                height=cs.rs.height, round=0, pol_round=-1,
                block_id=make_block_id(), timestamp=Timestamp.from_unix_ns(BASE_NS),
                signature=b"\x01" * 64,
            )
            cs.add_proposal_from_peer(bad, "evil")
            cs.add_block_part_from_peer(
                cs.rs.height, 0,
                Part(index=0, bytes=b"junk",
                     proof=merkle.Proof(total=1, index=0, leaf_hash=b"\x02" * 32)),
                "evil",
            )
            # The node still commits blocks afterwards.
            assert wait_for_height([cs], 2, timeout=30)
        finally:
            cs.stop()
