"""PEX (peer exchange) reactor: channel 0x00 (internal/p2p/pex/reactor.go).

Periodically asks connected peers for addresses and feeds responses into
the peer manager's address book. Wire: tag byte + JSON address list.
"""

from __future__ import annotations

import json
import threading

from tendermint_tpu.p2p.peermanager import PeerAddress, PeerManager
from tendermint_tpu.p2p.router import Channel, Envelope, Router

PEX_CHANNEL = 0x00

TAG_PEX_REQUEST = 1
TAG_PEX_RESPONSE = 2

REQUEST_INTERVAL = 2.0
MAX_ADDRESSES = 100


class PexReactor:
    def __init__(self, peer_manager: PeerManager, router: Router):
        self.peer_manager = peer_manager
        self.channel = router.open_channel(PEX_CHANNEL)
        self._stop_flag = threading.Event()
        self._threads = []

    def start(self) -> None:
        self._stop_flag.clear()
        for fn in (self._recv_loop, self._request_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop_flag.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()

    def _request_loop(self) -> None:
        while not self._stop_flag.is_set():
            self.channel.broadcast(bytes([TAG_PEX_REQUEST]))
            self._stop_flag.wait(REQUEST_INTERVAL)

    def _recv_loop(self) -> None:
        while not self._stop_flag.is_set():
            env = self.channel.receive(timeout=0.2)
            if env is None:
                continue
            try:
                self._handle(env)
            except Exception:
                pass

    def _handle(self, env: Envelope) -> None:
        tag = env.message[0]
        if tag == TAG_PEX_REQUEST:
            addresses = [
                str(a) for a in self.peer_manager.sample_addresses(MAX_ADDRESSES)
            ]
            self.channel.send(
                Envelope(
                    PEX_CHANNEL,
                    bytes([TAG_PEX_RESPONSE]) + json.dumps(addresses).encode(),
                    to_peer=env.from_peer,
                )
            )
        elif tag == TAG_PEX_RESPONSE:
            for s in json.loads(env.message[1:].decode())[:MAX_ADDRESSES]:
                try:
                    self.peer_manager.add_address(PeerAddress.parse(s))
                except ValueError:
                    pass
