"""RPC route handlers bound to a node Environment.

Mirrors internal/rpc/core: the ``Environment`` struct holds handles to
every service (routes.go:28-80, env.go), and each handler is a thin
adapter from JSON params to those services. Route names and response
shapes follow the reference; int64s are strings, hashes hex, tx bytes
base64 (see rpc/encoding.py).
"""

from __future__ import annotations

import base64
import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu import eventbus as eb
from tendermint_tpu.libs.pubsub import Query, QueryError
from tendermint_tpu.crypto import merkle
from tendermint_tpu.rpc import encoding as enc
from tendermint_tpu.rpc.server import INTERNAL_ERROR, INVALID_PARAMS, RPCError


def _to_bytes_param(v: Any) -> bytes:
    """'0x'-prefixed hex or base64 (the reference URI convention: hex
    MUST carry the 0x prefix so e.g. a 64-char tx hash is never
    mis-parsed as base64 — rpc/jsonrpc/server http_uri_handler)."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        if v.startswith("0x") or v.startswith("0X"):
            try:
                return bytes.fromhex(v[2:])
            except ValueError:
                raise RPCError(INVALID_PARAMS, f"invalid hex param: {v!r}")
        try:
            return base64.b64decode(v, validate=True)
        except Exception:
            raise RPCError(INVALID_PARAMS, f"cannot decode bytes param: {v!r}")
    raise RPCError(INVALID_PARAMS, f"cannot decode bytes param: {v!r}")


class Environment:
    """Service handles for RPC handlers (internal/rpc/core/env.go)."""

    def __init__(
        self,
        *,
        node_info=None,
        genesis=None,
        block_store=None,
        state_store=None,
        consensus=None,
        mempool=None,
        evidence_pool=None,
        app_client=None,
        event_bus: Optional[eb.EventBus] = None,
        indexer=None,
        peer_manager=None,
        get_state: Optional[Callable] = None,
        is_syncing: Optional[Callable[[], bool]] = None,
        consensus_reactor=None,
        router=None,
        unsafe: bool = False,
    ):
        self.node_info = node_info
        self.genesis = genesis
        self.block_store = block_store
        self.state_store = state_store
        self.consensus = consensus
        self.mempool = mempool
        self.evidence_pool = evidence_pool
        self.app = app_client
        self.event_bus = event_bus
        self.indexer = indexer
        self.peer_manager = peer_manager
        self.get_state = get_state or (lambda: None)
        self.is_syncing = is_syncing or (lambda: False)
        self.consensus_reactor = consensus_reactor
        self.router = router
        self.unsafe = unsafe

    # -- route table ----------------------------------------------------------

    def routes(self) -> Dict[str, Callable]:
        """internal/rpc/core/routes.go:28-80."""
        routes = {
            "health": self.health,
            "status": self.status,
            "net_info": self.net_info,
            "blockchain": self.blockchain,
            "genesis": self.genesis_route,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "commit": self.commit,
            "header": self.header,
            "header_by_hash": self.header_by_hash,
            "validators": self.validators,
            "consensus_state": self.consensus_state,
            "dump_consensus_state": self.dump_consensus_state,
            "consensus_params": self.consensus_params,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_async": self.broadcast_tx_sync,  # alias, routes.go:64
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "check_tx": self.check_tx,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            "abci_query": self.abci_query,
            "abci_info": self.abci_info,
            "broadcast_evidence": self.broadcast_evidence,
            "events": self.events,
            "subscribe": self.subscribe_poll,
            "genesis_chunked": self.genesis_chunked,
            "remove_tx": self.remove_tx,
        }
        if self.unsafe:
            # Reference routes.go AddUnsafeRoutes / the pprof server
            # behind PprofListenAddress (node.go OnStart): the whole
            # diagnostic+operator surface requires the explicit
            # [rpc] unsafe opt-in — thread dumps leak peer identities
            # (router-send-<peer> thread names) to whoever can ask.
            routes["dump_routines"] = self.dump_routines
            routes["unsafe_disconnect_peers"] = self.unsafe_disconnect_peers
            routes["unsafe_start_profiler"] = self.unsafe_start_profiler
            routes["unsafe_stop_profiler"] = self.unsafe_stop_profiler
        return routes

    # -- info routes ----------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        return {}

    def status(self) -> Dict[str, Any]:
        state = self.get_state()
        latest_height = self.block_store.height() if self.block_store else 0
        latest_meta = (
            self.block_store.load_block_meta(latest_height)
            if latest_height > 0
            else None
        )
        val_info = {}
        if state is not None and self.consensus is not None:
            pv = getattr(self.consensus, "priv_validator", None)
            if pv is not None:
                addr = pv.get_pub_key().address()
                _, val = state.validators.get_by_address(addr)
                val_info = {
                    "address": enc.hex_bytes(addr),
                    "pub_key": {
                        "type": pv.get_pub_key().type,
                        "value": enc.b64(pv.get_pub_key().bytes()),
                    },
                    "voting_power": str(val.voting_power if val else 0),
                }
        return {
            "node_info": self._node_info_json(),
            "sync_info": {
                "latest_block_hash": enc.hex_bytes(
                    latest_meta.block_id.hash if latest_meta else b""
                ),
                "latest_app_hash": enc.hex_bytes(
                    state.app_hash if state is not None else b""
                ),
                "latest_block_height": str(latest_height),
                "latest_block_time": enc.rfc3339(
                    latest_meta.header.time
                    if latest_meta
                    else enc.Timestamp(0, 0)
                ),
                "earliest_block_height": str(
                    self.block_store.base() if self.block_store else 0
                ),
                "catching_up": bool(self.is_syncing()),
            },
            "validator_info": val_info,
        }

    def _node_info_json(self) -> Dict[str, Any]:
        ni = self.node_info
        if ni is None:
            return {}
        return {
            "id": getattr(ni, "node_id", ""),
            "listen_addr": getattr(ni, "listen_addr", ""),
            "network": getattr(ni, "network", ""),
            "version": getattr(ni, "version", ""),
            "moniker": getattr(ni, "moniker", ""),
        }

    def net_info(self) -> Dict[str, Any]:
        peers = []
        if self.peer_manager is not None:
            for pid in self.peer_manager.connected_peers():
                peers.append({"node_id": pid})
        return {
            "listening": True,
            "listeners": [],
            "n_peers": str(len(peers)),
            "peers": peers,
        }

    def unsafe_disconnect_peers(self, duration: float = 5.0) -> Dict[str, Any]:
        """Drop all peer connections and quarantine dial/accept for
        ``duration`` seconds — the process-level 'disconnect'
        perturbation the e2e runner drives (perturb.go:42-72 network
        disconnect analog)."""
        if self.router is None:
            raise RPCError(INTERNAL_ERROR, "router unavailable")
        duration = min(max(float(duration), 0.0), 60.0)  # cap the outage
        dropped = self.router.disconnect_all(duration)
        return {"dropped": dropped, "duration": duration}

    def dump_routines(self) -> Dict[str, Any]:
        """Per-thread stack traces — the goroutine-dump half of the
        reference's pprof endpoint (node.go pprof server; read-only)."""
        import sys
        import traceback

        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        routines = []
        for ident, frame in frames.items():
            routines.append(
                {
                    "thread": names.get(ident, str(ident)),
                    "stack": traceback.format_stack(frame),
                }
            )
        return {"count": len(routines), "routines": routines}

    # cProfile hooks the whole interpreter, so the session is process-
    # wide by nature; the lock serializes the check-then-set against
    # concurrent RPCs (and multiple in-process nodes).
    _profiler = None
    _profiler_mtx = threading.Lock()

    def unsafe_start_profiler(self) -> Dict[str, Any]:
        """Start a process-wide cProfile session (the CPU-profile half of
        the reference's pprof surface; unsafe opt-in)."""
        import cProfile

        with Environment._profiler_mtx:
            if Environment._profiler is not None:
                raise RPCError(INTERNAL_ERROR, "profiler already running")
            prof = cProfile.Profile()
            Environment._profiler = prof
            prof.enable()
        return {"started": True}

    def unsafe_stop_profiler(self, top: int = 40) -> Dict[str, Any]:
        import io
        import pstats

        with Environment._profiler_mtx:
            prof = Environment._profiler
            if prof is None:
                raise RPCError(INTERNAL_ERROR, "profiler not running")
            prof.disable()
            Environment._profiler = None
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(
            int(top)
        )
        return {"stats": buf.getvalue()}

    def genesis_route(self) -> Dict[str, Any]:
        g = self.genesis
        return {
            "genesis": {
                "genesis_time": enc.rfc3339(g.genesis_time),
                "chain_id": g.chain_id,
                "initial_height": str(g.initial_height),
                "app_hash": enc.hex_bytes(g.app_hash),
                "validators": [
                    {
                        "address": enc.hex_bytes(v.address),
                        "pub_key": {"type": v.pub_key.type, "value": enc.b64(v.pub_key.bytes())},
                        "power": str(v.power),
                        "name": "",
                    }
                    for v in g.validators
                ],
            }
        }

    # -- block routes ---------------------------------------------------------

    def _height_param(self, height, default_latest: bool = True) -> int:
        if height is None or height == "":
            if not default_latest:
                raise RPCError(INVALID_PARAMS, "height required")
            return self.block_store.height()
        h = int(height)
        if h <= 0:
            return self.block_store.height()
        return h

    def blockchain(self, minHeight=None, maxHeight=None, min_height=None, max_height=None) -> Dict[str, Any]:
        lo = int(minHeight if minHeight is not None else (min_height or 1))
        latest = self.block_store.height()
        hi = int(maxHeight if maxHeight is not None else (max_height or latest))
        hi = min(hi if hi > 0 else latest, latest)
        lo = max(lo, self.block_store.base(), hi - 19)
        metas = []
        for h in range(hi, lo - 1, -1):
            m = self.block_store.load_block_meta(h)
            if m is None:
                continue
            metas.append(
                {
                    "block_id": enc.block_id_json(m.block_id),
                    "block_size": str(m.block_size),
                    "header": enc.header_json(m.header),
                    "num_txs": str(m.num_txs),
                }
            )
        return {"last_height": str(latest), "block_metas": metas}

    def block(self, height=None) -> Dict[str, Any]:
        h = self._height_param(height)
        blk = self.block_store.load_block(h)
        meta = self.block_store.load_block_meta(h)
        if blk is None:
            raise RPCError(INVALID_PARAMS, f"no block at height {h}")
        return {
            "block_id": enc.block_id_json(meta.block_id),
            "block": enc.block_json(blk),
        }

    def block_by_hash(self, hash=None) -> Dict[str, Any]:
        blk = self.block_store.load_block_by_hash(_to_bytes_param(hash))
        if blk is None:
            return {"block_id": None, "block": None}
        return self.block(blk.header.height)

    def header(self, height=None) -> Dict[str, Any]:
        h = self._height_param(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(INVALID_PARAMS, f"no header at height {h}")
        return {"header": enc.header_json(meta.header)}

    def header_by_hash(self, hash=None) -> Dict[str, Any]:
        blk = self.block_store.load_block_by_hash(_to_bytes_param(hash))
        if blk is None:
            return {"header": None}
        return {"header": enc.header_json(blk.header)}

    def commit(self, height=None) -> Dict[str, Any]:
        h = self._height_param(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(INVALID_PARAMS, f"no block at height {h}")
        canonical = True
        c = self.block_store.load_block_commit(h)
        if c is None:
            c = self.block_store.load_seen_commit()
            canonical = False
            if c is None or c.height != h:
                raise RPCError(INVALID_PARAMS, f"no commit for height {h}")
        return {
            "signed_header": {
                "header": enc.header_json(meta.header),
                "commit": enc.commit_json(c),
            },
            "canonical": canonical,
        }

    def block_results(self, height=None) -> Dict[str, Any]:
        h = self._height_param(height)
        raw = self.state_store.load_finalize_block_response(h)
        if raw is None:
            raise RPCError(INVALID_PARAMS, f"no results for height {h}")
        from tendermint_tpu.state.execution import _unmarshal_finalize_response

        fres = _unmarshal_finalize_response(raw)
        return {
            "height": str(h),
            "txs_results": [enc.exec_tx_result_json(r) for r in fres.tx_results],
            "finalize_block_events": [enc.event_json(e) for e in fres.events],
            "validator_updates": [
                {"pub_key_type": u.pub_key_type, "power": str(u.power)}
                for u in fres.validator_updates
            ],
            "app_hash": enc.hex_bytes(fres.app_hash),
        }

    def validators(self, height=None, page=1, per_page=30) -> Dict[str, Any]:
        h = self._height_param(height)
        vset = self.state_store.load_validators(h)
        vals = vset.validators
        page = max(1, int(page))
        per_page = max(1, min(100, int(per_page)))
        start = (page - 1) * per_page
        sel = vals[start : start + per_page]
        return {
            "block_height": str(h),
            "validators": [enc.validator_json(v) for v in sel],
            "count": str(len(sel)),
            "total": str(len(vals)),
        }

    def consensus_params(self, height=None) -> Dict[str, Any]:
        h = self._height_param(height)
        params = self.state_store.load_consensus_params(h)
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {
                    "max_bytes": str(params.block.max_bytes),
                    "max_gas": str(params.block.max_gas),
                },
                "evidence": {
                    "max_age_num_blocks": str(params.evidence.max_age_num_blocks),
                    "max_age_duration": str(params.evidence.max_age_duration),
                    "max_bytes": str(params.evidence.max_bytes),
                },
                "validator": {
                    "pub_key_types": list(params.validator.pub_key_types)
                },
            },
        }

    def consensus_state(self) -> Dict[str, Any]:
        """internal/rpc/core/consensus.go GetConsensusState: the round
        state summary."""
        cs = self.consensus
        rs = getattr(cs, "rs", None) if cs is not None else None
        if rs is None:
            return {"round_state": None}
        return {
            "round_state": {
                "height/round/step": rs.height_round_step(),
                "height": str(rs.height),
                "round": rs.round,
                "step": rs.step.name,
                "start_time": str(rs.start_time.to_unix_ns()),
                "proposal_block_hash": enc.hex_bytes(
                    rs.proposal_block.hash() if rs.proposal_block is not None else b""
                ),
                "locked_block_hash": enc.hex_bytes(
                    rs.locked_block.hash() if rs.locked_block is not None else b""
                ),
                "valid_block_hash": enc.hex_bytes(
                    rs.valid_block.hash() if rs.valid_block is not None else b""
                ),
                "height_vote_set": self._height_vote_set_json(rs),
            }
        }

    @staticmethod
    def _bits(ba) -> str:
        if ba is None:
            return ""
        return "".join("x" if ba.get_index(i) else "_" for i in range(ba.size()))

    def _height_vote_set_json(self, rs) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        if rs.votes is None:
            return out
        for r in range(rs.round + 1):
            prevotes = rs.votes.prevotes(r)
            precommits = rs.votes.precommits(r)
            out.append(
                {
                    "round": r,
                    "prevotes_bit_array": self._bits(
                        prevotes.bit_array() if prevotes else None
                    ),
                    "precommits_bit_array": self._bits(
                        precommits.bit_array() if precommits else None
                    ),
                }
            )
        return out

    def dump_consensus_state(self) -> Dict[str, Any]:
        """internal/rpc/core/consensus.go DumpConsensusState: full round
        state + per-peer round states from the reactor's PeerStates."""
        base = self.consensus_state()
        cs = self.consensus
        rs = getattr(cs, "rs", None) if cs is not None else None
        if rs is not None:
            base["round_state"]["validators"] = {
                "proposer": enc.hex_bytes(
                    rs.validators.get_proposer().address
                    if rs.validators is not None and not rs.validators.is_nil_or_empty()
                    else b""
                ),
                "count": len(rs.validators) if rs.validators is not None else 0,
            }
            base["round_state"]["last_commit_bit_array"] = self._bits(
                rs.last_commit.bit_array() if rs.last_commit is not None else None
            )
        peers = []
        reactor = self.consensus_reactor
        if reactor is not None:
            with reactor._peers_mtx:
                peer_states = dict(reactor._peers)
            for pid, ps in sorted(peer_states.items()):
                height, round_, step, lcr = ps.snapshot()
                peers.append(
                    {
                        "node_address": pid,
                        "peer_state": {
                            "round_state": {
                                "height": str(height),
                                "round": round_,
                                "step": step,
                                "last_commit_round": lcr,
                                "has_proposal": ps.has_proposal,
                                "proposal_block_parts": self._bits(ps.parts),
                            }
                        },
                    }
                )
        base["peers"] = peers
        return base

    # -- mempool routes -------------------------------------------------------

    def unconfirmed_txs(self, page=1, per_page=30) -> Dict[str, Any]:
        txs = self.mempool.tx_list()
        page = max(1, int(page))
        per_page = max(1, min(100, int(per_page)))
        sel = txs[(page - 1) * per_page : (page - 1) * per_page + per_page]
        return {
            "n_txs": str(len(sel)),
            "total": str(len(txs)),
            "total_bytes": str(self.mempool.size_bytes()),
            "txs": [enc.b64(t) for t in sel],
        }

    def num_unconfirmed_txs(self) -> Dict[str, Any]:
        return {
            "n_txs": str(len(self.mempool)),
            "total": str(len(self.mempool)),
            "total_bytes": str(self.mempool.size_bytes()),
        }

    def check_tx(self, tx=None) -> Dict[str, Any]:
        raw = _to_bytes_param(tx)
        res = self.app.check_tx(abci.RequestCheckTx(tx=raw))
        return {"code": res.code, "codespace": res.codespace, "data": enc.b64(res.data)}

    def broadcast_tx_sync(self, tx=None) -> Dict[str, Any]:
        raw = _to_bytes_param(tx)
        res = self.mempool.check_tx(raw)
        return {
            "code": res.code,
            "data": enc.b64(res.data),
            "codespace": res.codespace,
            "hash": enc.hex_bytes(hashlib.sha256(raw).digest()),
        }

    def broadcast_tx_commit(self, tx=None, timeout: float = 30.0) -> Dict[str, Any]:
        """mempool.go DeliverTx flow: CheckTx, then wait for the tx event
        (internal/rpc/core/mempool.go:48-108)."""
        raw = _to_bytes_param(tx)
        tx_hash = hashlib.sha256(raw).hexdigest().upper()
        if self.event_bus is None:
            raise RPCError(INVALID_PARAMS, "event bus not configured")
        subscriber = f"tx-commit-{tx_hash[:16]}-{time.monotonic_ns()}"
        sub = self.event_bus.subscribe(
            subscriber, f"{eb.TX_HASH_KEY} = '{tx_hash}'", capacity=4
        )
        try:
            res = self.mempool.check_tx(raw)
            out: Dict[str, Any] = {
                "check_tx": {
                    "code": res.code,
                    "data": enc.b64(res.data),
                    "codespace": res.codespace,
                },
                "hash": tx_hash,
                "height": "0",
            }
            if res.code != abci.CODE_TYPE_OK:
                return out
            msg = sub.next(timeout=timeout)
            if msg is None:
                out["tx_result"] = None
                out["error"] = "timed out waiting for tx to be included in a block"
                return out
            data = msg.data
            out["tx_result"] = enc.exec_tx_result_json(data.result)
            out["height"] = str(data.height)
            return out
        finally:
            self.event_bus.unsubscribe_all(subscriber)

    # -- query routes ---------------------------------------------------------

    def tx(self, hash=None, prove=False) -> Dict[str, Any]:
        if self.indexer is None:
            raise RPCError(INVALID_PARAMS, "tx indexing disabled")
        h = _to_bytes_param(hash)
        tr = self.indexer.get_tx(h)
        if tr is None:
            raise RPCError(INVALID_PARAMS, f"tx not found: {h.hex()}")
        out = {
            "hash": enc.hex_bytes(h),
            "height": str(tr.height),
            "index": tr.index,
            "tx_result": enc.exec_tx_result_json(tr.result),
            "tx": enc.b64(tr.tx),
        }
        if prove:
            # types/tx.go Txs.Proof: merkle inclusion over per-tx hashes
            # (the leaves of Data.hash); rpc/core/tx.go:52.
            block = self.block_store.load_block(tr.height)
            if block is None:
                raise RPCError(
                    INVALID_PARAMS, f"block at height {tr.height} pruned"
                )
            from tendermint_tpu.types.block import tx_hash as _tx_hash

            leaves = [_tx_hash(t) for t in block.data.txs]
            root, proofs = merkle.proofs_from_byte_slices(leaves)
            if tr.index >= len(proofs):
                raise RPCError(INTERNAL_ERROR, "tx index out of range")
            p = proofs[tr.index]
            out["proof"] = {
                "root_hash": enc.hex_bytes(root),
                "data": enc.b64(tr.tx),
                "proof": {
                    "total": str(p.total),
                    "index": str(p.index),
                    "leaf_hash": enc.b64(p.leaf_hash),
                    "aunts": [enc.b64(a) for a in p.aunts],
                },
            }
        return out

    def genesis_chunked(self, chunk=0) -> Dict[str, Any]:
        """rpc/core/net.go GenesisChunked: the genesis doc in 16 MiB
        base64 chunks, for documents too large for one response.
        Chunks are computed once and cached — the doc is immutable and
        re-serializing a huge genesis per request defeats the point."""
        chunks = getattr(self, "_genesis_chunks", None)
        if chunks is None:
            data = self.genesis.to_json().encode()
            size = 16 * 1024 * 1024
            chunks = [
                data[i : i + size] for i in range(0, len(data), size)
            ] or [b""]
            self._genesis_chunks = chunks
        c = int(chunk)
        if c < 0 or c >= len(chunks):
            raise RPCError(
                INVALID_PARAMS,
                f"there are {len(chunks)} chunks, cannot fetch chunk {c}",
            )
        return {
            "chunk": str(c),
            "total": str(len(chunks)),
            "data": enc.b64(chunks[c]),
        }

    def remove_tx(self, tx_key=None) -> Dict[str, Any]:
        """rpc/core/mempool.go RemoveTx: evict by tx key (sha256 of tx)."""
        if tx_key is None:
            raise RPCError(INVALID_PARAMS, "tx_key required")
        key = _to_bytes_param(tx_key)
        if len(key) != 32:
            raise RPCError(INVALID_PARAMS, "tx_key must be 32 bytes")
        self.mempool.remove_tx_by_key(key)
        return {}

    def tx_search(self, query=None, page=1, per_page=30, order_by="asc") -> Dict[str, Any]:
        if self.indexer is None:
            raise RPCError(INVALID_PARAMS, "tx indexing disabled")
        try:
            q = Query.parse(query or "")
        except QueryError as e:
            raise RPCError(INVALID_PARAMS, str(e))
        # Paginate over index keys; only the selected page's records are
        # decoded (a query matching the whole chain stays O(page)).
        keys = self.indexer.search_tx_keys(q)
        if order_by == "desc":
            keys = keys[::-1]
        page = max(1, int(page))
        per_page = max(1, min(100, int(per_page)))
        sel_keys = keys[(page - 1) * per_page : (page - 1) * per_page + per_page]
        sel = [self.indexer.get_tx(h) for _, _, h in sel_keys]
        return {
            "txs": [
                {
                    "hash": enc.hex_bytes(t.hash()),
                    "height": str(t.height),
                    "index": t.index,
                    "tx_result": enc.exec_tx_result_json(t.result),
                    "tx": enc.b64(t.tx),
                }
                for t in sel
                if t is not None
            ],
            "total_count": str(len(keys)),
        }

    def block_search(self, query=None, page=1, per_page=30, order_by="asc") -> Dict[str, Any]:
        if self.indexer is None:
            raise RPCError(INVALID_PARAMS, "block indexing disabled")
        try:
            q = Query.parse(query or "")
        except QueryError as e:
            raise RPCError(INVALID_PARAMS, str(e))
        heights = self.indexer.search_block_heights(q, limit=10000)
        if order_by == "desc":
            heights = heights[::-1]
        page = max(1, int(page))
        per_page = max(1, min(100, int(per_page)))
        sel = heights[(page - 1) * per_page : (page - 1) * per_page + per_page]
        blocks = []
        for h in sel:
            meta = self.block_store.load_block_meta(h)
            blk = self.block_store.load_block(h)
            if meta is None or blk is None:
                continue
            blocks.append(
                {"block_id": enc.block_id_json(meta.block_id), "block": enc.block_json(blk)}
            )
        return {"blocks": blocks, "total_count": str(len(heights))}

    # -- ABCI routes ----------------------------------------------------------

    def abci_query(self, path="", data=None, height=0, prove=False) -> Dict[str, Any]:
        raw = _to_bytes_param(data) if data else b""
        res = self.app.query(
            abci.RequestQuery(data=raw, path=path, height=int(height), prove=bool(prove))
        )
        proof_ops = None
        if res.proof_ops:
            proof_ops = {
                "ops": [
                    {
                        "type": getattr(op, "type", ""),
                        "key": enc.b64(getattr(op, "key", b"")),
                        "data": enc.b64(getattr(op, "data", b"")),
                    }
                    for op in res.proof_ops
                ]
            }
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "info": res.info,
                "index": str(res.index),
                "key": enc.b64(res.key),
                "value": enc.b64(res.value),
                "proof_ops": proof_ops,
                "height": str(res.height),
                "codespace": res.codespace,
            }
        }

    def abci_info(self) -> Dict[str, Any]:
        res = self.app.info(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": enc.b64(res.last_block_app_hash),
            }
        }

    def broadcast_evidence(self, evidence=None) -> Dict[str, Any]:
        from tendermint_tpu.types.evidence import evidence_from_proto_bytes

        ev = evidence_from_proto_bytes(_to_bytes_param(evidence))
        self.evidence_pool.add_evidence(ev)
        return {"hash": enc.hex_bytes(ev.hash())}

    # -- event routes ---------------------------------------------------------

    def events(self, filter=None, maxItems=100, after=0, waitTime=5.0) -> Dict[str, Any]:
        """Long-poll over the sliding-window event log
        (internal/rpc/core/events.go:103, eventlog-backed /events)."""
        if self.event_bus is None:
            raise RPCError(INVALID_PARAMS, "event bus not configured")
        q = None
        if filter:
            fq = filter.get("query") if isinstance(filter, dict) else filter
            if fq:
                try:
                    q = Query.parse(fq)
                except QueryError as e:
                    raise RPCError(INVALID_PARAMS, str(e))
        items, more, resume = self.event_bus.eventlog.scan(
            query=q,
            after=int(after),
            max_items=min(int(maxItems), 500),
            wait=min(float(waitTime), 30.0),
        )
        return {
            "items": [
                {
                    "cursor": str(it.cursor),
                    "event": it.type,
                    "data": _event_data_json(it.data),
                }
                for it in items
            ],
            "more": more,
            "oldest": str(items[0].cursor) if items else "0",
            # resume cursor: pass back as `after` — never skips events
            # even when the response was truncated.
            "newest": str(resume),
        }

    def subscribe_poll(self, query=None, after=0, waitTime=5.0, maxItems=100) -> Dict[str, Any]:
        """Long-poll subscribe: same contract as /events but keyed by the
        caller's query (the reference's websocket subscribe is replaced
        by cursor-based polling; see server.py docstring)."""
        return self.events(
            filter={"query": query} if query else None,
            maxItems=maxItems,
            after=after,
            waitTime=waitTime,
        )


def _event_data_json(data: object) -> Dict[str, Any]:
    if isinstance(data, eb.EventDataNewBlock):
        return {
            "type": "new_block",
            "height": str(data.block.header.height),
            "block": enc.block_json(data.block),
        }
    if isinstance(data, eb.EventDataTx):
        return {
            "type": "tx",
            "height": str(data.height),
            "index": data.index,
            "tx": enc.b64(data.tx),
            "result": enc.exec_tx_result_json(data.result),
        }
    if isinstance(data, eb.EventDataNewBlockHeader):
        return {"type": "new_block_header", "header": enc.header_json(data.header)}
    if isinstance(data, eb.EventDataNewRound):
        return {
            "type": "new_round",
            "height": str(data.height),
            "round": data.round,
            "step": data.step,
        }
    if isinstance(data, eb.EventDataRoundState):
        return {
            "type": "round_state",
            "height": str(data.height),
            "round": data.round,
            "step": data.step,
        }
    if isinstance(data, eb.EventDataValidatorSetUpdates):
        return {"type": "validator_set_updates"}
    if isinstance(data, eb.EventDataVote):
        v = data.vote
        return {
            "type": "vote",
            "height": str(v.height),
            "round": v.round,
            "vote_type": v.type,
            "validator_address": enc.hex_bytes(v.validator_address),
            "validator_index": v.validator_index,
        }
    if isinstance(data, eb.EventDataCompleteProposal):
        return {
            "type": "complete_proposal",
            "height": str(data.height),
            "round": data.round,
            "step": data.step,
            "block_hash": enc.hex_bytes(data.block_id.hash)
            if data.block_id is not None
            else "",
        }
    if isinstance(data, eb.EventDataBlockSyncStatus):
        return {
            "type": "block_sync_status",
            "complete": data.complete,
            "height": str(data.height),
        }
    if isinstance(data, eb.EventDataStateSyncStatus):
        return {
            "type": "state_sync_status",
            "complete": data.complete,
            "height": str(data.height),
        }
    return {"type": type(data).__name__}
