"""Sharded batch verification over a device mesh.

The TPU analog of the reference's task-level concurrency inventory
(SURVEY.md §2.4): signature lanes are the data-parallel axis. All three
kernel entry points — ed25519 build-on-device (ops/ed25519_batch
.verify_kernel), the table-input cache-hit variant
(verify_kernel_tables, with the gathered ``(8, 4, 32, N)`` precompute
tensor sharded ``P(None, None, None, 'sig')`` so each device holds only
its own lanes' tables), and sr25519 (ops/sr25519_batch
.verify_kernel_sr) — are lane-local with no cross-signature
communication, so sharding the lane axis over an ICI mesh partitions
with zero collectives; XLA emits per-device slices and the only sync is
the final per-lane bool gather.

This module is the mechanism half of the mesh engine: compile-cached
sharded kernels, slab padding to a device multiple, the
dispatch-with-degradation loop (:func:`run_chunk_mesh`), and per-device
collection (:func:`collect_sharded`). Policy — which devices, per-device
health, COOLDOWN re-admission — lives in
:mod:`tendermint_tpu.parallel.mesh`; the engines (ops/ed25519_batch,
ops/sr25519_batch) call in here per chunk, so scheduler and verifyd
super-batches span devices without their callers changing at all.

Failure semantics: a dispatch failure attributable to one chip excludes
that chip and retries the chunk on a rebuilt smaller mesh (7-way, not
host); only when no usable mesh remains does :class:`MeshUnavailableError`
hand the chunk back to the engine's single-device path. Unattributed
failures propagate to the engine's ordinary per-chunk host fallback.
"""

from __future__ import annotations

import warnings
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.libs import tracing
from tendermint_tpu.ops import ed25519_batch, field32 as field
from tendermint_tpu.parallel import mesh as mesh_mod
from tendermint_tpu.parallel.mesh import SIG_AXIS


class MeshUnavailableError(RuntimeError):
    """No usable multi-device mesh remains for this chunk; the caller
    should take its single-device path (NOT the host oracle)."""


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SIG_AXIS,))


@lru_cache(maxsize=32)
def _sharded_kernel(mesh: Mesh, kind: str, mul_impl: str):
    """Jitted lane-sharded kernel per (mesh, entry point, field-mul
    impl). The mul impl is a trace-time switch on field32, pinned inside
    the traced fn (same rules as ops/ed25519_batch._compiled_kernel) and
    therefore part of the cache key."""
    rows = NamedSharding(mesh, P(SIG_AXIS, None))
    lane = NamedSharding(mesh, P(SIG_AXIS))
    if kind == "tables":
        # (8, 4, 32, N): lanes on the LAST axis — each device gathers
        # and holds only its own lanes' precompute tables.
        tab = NamedSharding(mesh, P(None, None, None, SIG_AXIS))

        def run_tables(t, ok, r, s, k):
            with field.pinned_mul_impl(mul_impl):
                return ed25519_batch.verify_kernel_tables(t, ok, r, s, k)

        return jax.jit(
            run_tables,
            in_shardings=(tab, lane, rows, rows, rows),
            out_shardings=lane,
        )
    if kind == "resident":
        # The resident store (8, 4, 32, K) is keyed by distinct pubkey,
        # not lane: replicate it (a committee is ~100 KiB) so the
        # per-lane take is device-local; the gathered tensor inside the
        # kernel comes out lane-sharded like the "tables" input.
        tab_rep = NamedSharding(mesh, P(None, None, None, None))

        def run_resident(t, idx, ok, r, s, k):
            with field.pinned_mul_impl(mul_impl):
                return ed25519_batch.verify_kernel_resident(t, idx, ok, r, s, k)

        return jax.jit(
            run_resident,
            in_shardings=(tab_rep, lane, lane, rows, rows, rows),
            out_shardings=lane,
        )
    if kind == "sr25519":
        from tendermint_tpu.ops import sr25519_batch

        def run(pk, r, s, k):
            with field.pinned_mul_impl(mul_impl):
                return sr25519_batch.verify_kernel_sr(pk, r, s, k)

    else:

        def run(pk, r, s, k):
            with field.pinned_mul_impl(mul_impl):
                return ed25519_batch.verify_kernel(pk, r, s, k)

    return jax.jit(run, in_shardings=(rows,) * 4, out_shardings=lane)


def sharded_verify_fn(mesh: Mesh):
    """Jitted ed25519 verify kernel with lane-axis sharding over
    ``mesh`` (back-compat entry point; see :func:`_sharded_kernel`)."""
    return _sharded_kernel(mesh, "ed25519", field.get_mul_impl())


# --- slab padding -------------------------------------------------------------


def _pad_for_mesh(kind: str, inputs: dict, n_dev: int) -> Tuple[dict, int]:
    """Pad a prepped chunk to a multiple of ``n_dev`` lanes so every
    device gets an identical slab. The engines already pad to
    ``_mesh_bucket`` multiples for the planned mesh; this re-pad covers
    dispatch on a DEGRADED mesh (8-way prep retried 7-way: 512 -> 518).
    Pad lanes verify true and are sliced off at collect."""
    m = int(inputs["r"].shape[0])
    target = -(-m // n_dev) * n_dev
    if target == m:
        return inputs, m
    extra = target - m
    out = dict(inputs)
    if kind == "sr25519":
        from tendermint_tpu.ops import sr25519_batch

        for key, row in zip(("pk", "r", "s", "k"), sr25519_batch._pad_entry()):
            out[key] = np.concatenate(
                [np.asarray(inputs[key]), np.tile(row.reshape(1, 32), (extra, 1))]
            )
        return out, target
    if kind == "resident":
        # the store tensor is untouched — pad lanes index column 0 (the
        # pad-key table reserved at upload)
        idx = np.asarray(inputs["idx"])
        out["idx"] = np.concatenate([idx, np.zeros(extra, dtype=idx.dtype)])
        ok = np.asarray(inputs["ok"])
        out["ok"] = np.concatenate([ok, np.ones(extra, dtype=ok.dtype)])
        for key, row in zip(("r", "s", "k"), ed25519_batch._pad_rows()[1:]):
            out[key] = np.concatenate(
                [np.asarray(inputs[key]), np.tile(row, (extra, 1))]
            )
        return out, target
    if kind == "tables":
        pad_tab = ed25519_batch._pad_table()  # (8, 4, 32) uint8
        out["tab"] = np.concatenate(
            [
                np.asarray(inputs["tab"]),
                np.broadcast_to(pad_tab[..., None], pad_tab.shape + (extra,)),
            ],
            axis=3,
        )
        ok = np.asarray(inputs["ok"])
        out["ok"] = np.concatenate([ok, np.ones(extra, dtype=ok.dtype)])
        keys = ("r", "s", "k")
        pad_rows = ed25519_batch._pad_rows()[1:]
    else:
        keys = ("pk", "r", "s", "k")
        pad_rows = ed25519_batch._pad_rows()
    for key, row in zip(keys, pad_rows):
        out[key] = np.concatenate([np.asarray(inputs[key]), np.tile(row, (extra, 1))])
    return out, target


def _kernel_args(kind: str, inputs: dict) -> tuple:
    if kind == "resident":
        return (
            inputs["store"],
            inputs["idx"],
            inputs["ok"],
            inputs["r"],
            inputs["s"],
            inputs["k"],
        )
    if kind == "tables":
        return (inputs["tab"], inputs["ok"], inputs["r"], inputs["s"], inputs["k"])
    return (inputs["pk"], inputs["r"], inputs["s"], inputs["k"])


# --- dispatch / collect -------------------------------------------------------


def run_chunk_mesh(
    kind: str,
    inputs: dict,
    mul_impl: str,
    plan: "mesh_mod.MeshPlan",
    fault_site: str,
):
    """Dispatch one prepped chunk lane-sharded across ``plan``'s mesh.

    Returns ``(device_result, plan_used)`` — ``plan_used`` may be a
    smaller rebuilt plan if a device was excluded mid-dispatch. A
    failure attributable to one chip excludes it (its DeviceHealth
    enters COOLDOWN), rebuilds an (n-1)-device mesh, and retries the
    chunk there: a sick chip degrades the mesh, never to host. Raises
    :class:`MeshUnavailableError` when no multi-device mesh remains,
    and re-raises unattributed failures for the engine's ordinary
    per-chunk handling.
    """
    from tendermint_tpu.ops import fault_injection

    mgr = mesh_mod.manager
    engine = "sr25519" if kind == "sr25519" else "ed25519"
    while True:
        padded, m = _pad_for_mesh(kind, inputs, plan.n_dev)
        fn = _sharded_kernel(plan.mesh, kind, mul_impl)
        try:
            with tracing.span(
                "mesh_dispatch",
                stage="mesh_dispatch",
                engine=engine,
                kind=kind,
                devices=plan.n_dev,
                lanes=m,
            ):
                fault_injection.fire(fault_site)
                out = fn(*_kernel_args(kind, padded))
        except Exception as exc:
            culprit = mgr.on_failure(plan, exc)
            if culprit is None:
                raise
            nxt = mgr.replan(plan)
            if nxt is None:
                raise MeshUnavailableError(
                    f"device {culprit} excluded and no usable mesh remains"
                ) from exc
            if kind == "resident":
                # the resident store tensor is committed to THIS mesh;
                # a rebuilt smaller mesh can't consume it — hand back so
                # the engine re-ships this chunk's columns explicitly
                raise MeshUnavailableError(
                    f"device {culprit} excluded; resident store is bound "
                    "to the dead mesh"
                ) from exc
            warnings.warn(
                f"sharded {kind} chunk failed on device {culprit} ({exc!r}); "
                f"retrying on a {nxt.n_dev}-device mesh"
            )
            plan = nxt
            continue
        mgr.note_dispatch(plan, m)
        per_dev = m // plan.n_dev
        for did in plan.device_ids:
            tracing.instant(
                "mesh_device_dispatch", device=did, engine=engine, lanes=per_dev
            )
        return out, plan


def collect_sharded(out, engine: str) -> np.ndarray:
    """Materialize a sharded lane result device by device, one
    ``collect_device`` span per shard so per-device D2H time lands in
    the trace ring. Shards are stitched in lane order."""
    shards = getattr(out, "addressable_shards", None)
    if not shards or len(shards) <= 1:
        return np.asarray(out)

    def lane_start(sh) -> int:
        idx = sh.index[0] if sh.index else slice(None)
        return idx.start or 0

    parts = []
    for sh in sorted(shards, key=lane_start):
        with tracing.span(
            "collect_device",
            stage="collect_device",
            engine=engine,
            device=str(getattr(sh.device, "id", "?")),
            lanes=int(sh.data.shape[0]),
        ):
            parts.append(np.asarray(sh.data))
    return np.concatenate(parts)


# --- whole-batch entry points -------------------------------------------------


def verify_batch_sharded(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    mesh: Optional[Mesh] = None,
    min_lanes: Optional[int] = None,
) -> List[bool]:
    """Like ops.verify_batch but lane-sharded across ``mesh``.

    Routes through the full ops pipeline — digest-keyed result cache,
    OpsMetrics, chunking, per-chunk fallback — with the mesh forced for
    the call's scope, so sharded verification is observable exactly
    like single-device verification. Batches below ``min_lanes``
    (default :data:`mesh.MIN_MESH_LANES`) take the single-device path:
    tiny batches lose more to ``n_dev``-way padding and dispatch fan-out
    than they gain (pass ``min_lanes=0`` to force sharding, e.g. for
    parity tests and warmup). With ``mesh=None`` the engines plan
    against the configured mesh themselves.
    """
    n = len(pubkeys)
    if n == 0:
        return []
    floor = mesh_mod.MIN_MESH_LANES if min_lanes is None else min_lanes
    if mesh is None or n < floor:
        return ed25519_batch.verify_batch(pubkeys, msgs, sigs)
    with mesh_mod.manager.forced(mesh):
        return ed25519_batch.verify_batch(pubkeys, msgs, sigs)


def verify_batch_sharded_sr(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    mesh: Optional[Mesh] = None,
    min_lanes: Optional[int] = None,
) -> List[bool]:
    """sr25519 counterpart of :func:`verify_batch_sharded`."""
    from tendermint_tpu.ops import sr25519_batch

    n = len(pubkeys)
    if n == 0:
        return []
    floor = mesh_mod.MIN_MESH_LANES if min_lanes is None else min_lanes
    if mesh is None or n < floor:
        return sr25519_batch.verify_batch_sr(pubkeys, msgs, sigs)
    with mesh_mod.manager.forced(mesh):
        return sr25519_batch.verify_batch_sr(pubkeys, msgs, sigs)
