"""Span tracer tests: nesting, concurrency, ring bounds, nop overhead,
and the end-to-end verify-pipeline acceptance capture.

The tracer under test is the process-global ``tendermint_tpu.libs.
tracing.tracer`` (instrumentation sites have no handle to pass one in),
so every test here configures it explicitly and restores ``off`` +
observer-free state on exit via the ``ring`` fixture.
"""

from __future__ import annotations

import json
import threading

import pytest

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.metrics import (
    ConsensusMetrics,
    OpsMetrics,
    Registry,
)

from tests.helpers import CHAIN_ID, make_block_id, make_commit, make_validators


@pytest.fixture
def ring(monkeypatch):
    """Global tracer in ring mode, restored to off/empty afterwards."""
    monkeypatch.delenv(tracing.CAP_ENV, raising=False)
    tracing.configure("ring")
    tracing.tracer.clear()
    tracing.tracer.set_metrics_observer(None)
    yield tracing.tracer
    tracing.tracer.set_metrics_observer(None)
    tracing.configure("off")
    tracing.tracer.clear()


def _complete_events(exported):
    return [e for e in exported["traceEvents"] if e.get("ph") == "X"]


# --- basic recording ---------------------------------------------------------


def test_nested_spans_record_parent_and_args(ring):
    with tracing.span("outer", height=7):
        with tracing.span("inner", stage="prep", engine="ed25519") as sp:
            sp.set(lanes=42)
    out = ring.export()
    events = {e["name"]: e for e in _complete_events(out)}
    assert set(events) == {"outer", "inner"}
    assert events["outer"]["args"]["height"] == 7
    assert "parent" not in events["outer"]["args"]
    assert events["inner"]["args"]["parent"] == "outer"
    assert events["inner"]["args"]["lanes"] == 42
    # inner completes first and sits inside outer's time window
    inner, outer = events["inner"], events["outer"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert out["displayTimeUnit"] == "ms"
    assert out["otherData"]["mode"] == "ring"


def test_instant_events(ring):
    tracing.instant("device_health_transition", from_state="healthy")
    (ev,) = ring.export()["traceEvents"][-1:]
    assert ev["ph"] == "i"
    assert ev["s"] == "p"
    assert ev["args"]["from_state"] == "healthy"


def test_export_is_valid_bounded_json(ring):
    for i in range(10):
        with tracing.span("s", i=i):
            pass
    out = ring.export(limit=4)
    assert len(_complete_events(out)) == 4
    # the wire form of /debug/traces round-trips through json
    assert json.loads(json.dumps(out)) == out


def test_export_clear_drains_ring(ring):
    with tracing.span("s"):
        pass
    assert len(ring) == 1
    ring.export(clear=True)
    assert len(ring) == 0


# --- concurrency -------------------------------------------------------------


def test_concurrent_threads_yield_well_nested_untorn_output(ring):
    """≥4 threads race nested spans; every event must carry intact args
    and per-thread parent attribution (no cross-thread tearing)."""
    n_threads, n_iters = 6, 25
    barrier = threading.Barrier(n_threads)
    errors = []

    def work(t):
        try:
            barrier.wait(timeout=10)
            for i in range(n_iters):
                with tracing.span(f"outer-{t}", t=t, i=i):
                    with tracing.span(f"inner-{t}", t=t, i=i):
                        pass
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [
        threading.Thread(target=work, args=(t,)) for t in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors
    out = ring.export()
    events = _complete_events(out)
    assert len(events) == n_threads * n_iters * 2
    # untorn: the JSON form parses back identical
    assert json.loads(json.dumps(out)) == out
    for ev in events:
        t = ev["args"]["t"]
        assert ev["name"] in (f"outer-{t}", f"inner-{t}")
        if ev["name"].startswith("inner"):
            # nesting never crosses threads: the parent is this
            # thread's own outer span, regardless of interleaving
            assert ev["args"]["parent"] == f"outer-{t}"
        else:
            assert "parent" not in ev["args"]
    # each thread's events landed under its own tid
    by_tid = {}
    for ev in events:
        by_tid.setdefault(ev["tid"], set()).add(ev["args"]["t"])
    assert all(len(owners) == 1 for owners in by_tid.values())


# --- ring bound --------------------------------------------------------------


def test_ring_bound_enforced(ring, monkeypatch):
    monkeypatch.setenv(tracing.CAP_ENV, "8")
    tracing.configure("ring")
    tracing.tracer.clear()
    for i in range(20):
        with tracing.span("s", i=i):
            pass
    assert len(tracing.tracer) == 8
    out = tracing.tracer.export()
    events = _complete_events(out)
    # most recent events survive
    assert [e["args"]["i"] for e in events] == list(range(12, 20))
    assert out["otherData"]["dropped"] == 12


# --- nop path ----------------------------------------------------------------


def test_nop_tracer_adds_no_spans():
    tracing.tracer.set_metrics_observer(None)
    tracing.configure("off")
    tracing.tracer.clear()
    before = tracing.tracer.recorded
    for _ in range(100):
        with tracing.span("hot", lanes=1) as sp:
            sp.set(x=1)
        tracing.instant("tick")
    # counter-asserted, not timing-asserted: nothing was recorded and
    # the disabled span is the one shared nop instance
    assert tracing.tracer.recorded == before
    assert len(tracing.tracer) == 0
    assert tracing.span("hot") is tracing.NOP_SPAN


def test_off_mode_with_observer_times_spans_without_storing():
    seen = []
    tracing.configure("off")
    tracing.tracer.clear()
    tracing.tracer.set_metrics_observer(
        lambda name, args, sec: seen.append((name, dict(args), sec))
    )
    try:
        with tracing.span("stage_span", stage="prep", engine="ed25519"):
            pass
        assert len(tracing.tracer) == 0  # ring stays empty in off mode
        assert len(seen) == 1
        name, args, sec = seen[0]
        assert name == "stage_span"
        assert args["stage"] == "prep"
        assert sec >= 0.0
    finally:
        tracing.tracer.set_metrics_observer(None)


def test_broken_observer_never_fails_the_traced_op(ring):
    def boom(name, args, sec):
        raise RuntimeError("broken metrics binding")

    ring.set_metrics_observer(boom)
    with tracing.span("s"):
        pass
    assert len(ring) == 1


# --- summary -----------------------------------------------------------------


def test_summary_groups_by_stage_tag(ring):
    for _ in range(3):
        with tracing.span("prep_chunk", stage="prep", engine="ed25519"):
            pass
    with tracing.span("verify_batch", engine="ed25519"):
        pass
    s = ring.summary()
    assert s["prep"]["count"] == 3
    assert s["verify_batch"]["count"] == 1
    for row in s.values():
        assert row["p50_ms"] <= row["p95_ms"] or row["count"] == 1
        assert row["total_ms"] >= row["p50_ms"] >= 0


# --- metrics observer bridge -------------------------------------------------


def test_metrics_observer_feeds_both_histograms():
    reg = Registry()
    ops = OpsMetrics(reg)
    consensus = ConsensusMetrics(reg)
    obs = tracing.metrics_observer(ops=ops, consensus=consensus)
    obs("prep_chunk", {"stage": "prep", "engine": "ed25519"}, 0.001)
    obs("propose", {"step": "propose", "height": 1}, 0.002)
    obs("verify_batch", {"engine": "ed25519"}, 0.003)  # no stage: skipped
    text = reg.expose()
    assert (
        'tendermint_ops_verify_stage_seconds_count'
        '{engine="ed25519",stage="prep"} 1' in text
    )
    assert (
        'tendermint_consensus_step_duration_seconds_count'
        '{step="propose"} 1' in text
    )


# --- end-to-end: verify_commit under ring tracing ----------------------------


def _stage_counts_from_events(events):
    counts = {}
    for ev in events:
        stage = ev["args"].get("stage")
        engine = ev["args"].get("engine")
        if stage and engine:
            counts[(stage, engine)] = counts.get((stage, engine), 0) + 1
    return counts


def _histogram_counts(ops):
    hist = ops.verify_stage_seconds
    with hist._lock:
        return {
            (dict(k)["stage"], dict(k)["engine"]): n
            for k, (_c, _t, n) in hist._values.items()
        }


def test_verify_commit_traced_end_to_end(ring, monkeypatch):
    """The acceptance capture: a 24-validator commit verified with
    TENDERMINT_TPU_TRACE=ring records the nested pipeline (consensus
    span -> batch verify -> cache lookup / per-chunk prep+dispatch),
    and the stage histogram counts equal the traced stage-span counts."""
    from tendermint_tpu.ops import precompute
    from tendermint_tpu.types import validation

    monkeypatch.setenv("TENDERMINT_TPU_TRACE", "ring")
    monkeypatch.setenv(precompute._RESULT_ENV, "1")  # conftest turns it off
    precompute.reset()
    reg = Registry()
    ops = OpsMetrics(reg)
    consensus = ConsensusMetrics(reg)
    ring.set_metrics_observer(
        tracing.metrics_observer(ops=ops, consensus=consensus)
    )

    privs, vset = make_validators(24)
    block_id = make_block_id()
    height, round_ = 5, 1
    commit = make_commit(block_id, height, round_, vset, privs)
    validation.verify_commit(CHAIN_ID, vset, block_id, height, commit)
    # second pass: the digest-keyed result cache answers every lane
    validation.verify_commit(CHAIN_ID, vset, block_id, height, commit)

    events = _complete_events(ring.export())
    by_name = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)

    # consensus span tagged with height/round
    vc = by_name["verify_commit"]
    assert len(vc) == 2
    for ev in vc:
        assert ev["args"]["height"] == height
        assert ev["args"]["round"] == round_
        assert ev["args"]["sigs"] == 24

    # nested under it: the engine batch, then the cache lookup
    assert all(
        ev["args"]["parent"] == "verify_commit"
        for ev in by_name["verify_batch"]
    )
    lookups = by_name["cache_lookup"]
    assert len(lookups) == 2
    assert all(ev["args"]["parent"] == "verify_batch" for ev in lookups)
    assert lookups[0]["args"]["hits"] == 0
    assert lookups[1]["args"]["hits"] == 24  # warm pass: all cached

    # per-chunk device stages ran only on the cold pass
    assert len(by_name["prep_chunk"]) >= 1
    for ev in by_name["prep_chunk"]:
        assert ev["args"]["stage"] == "prep"
        assert ev["args"]["engine"] == "ed25519"
        assert ev["args"]["parent"] == "verify_batch"
    dispatched = "dispatch_chunk" in by_name
    fell_back = "host_fallback" in by_name
    assert dispatched or fell_back  # every lane was answered somewhere

    # the histograms observed exactly the spans the trace recorded:
    # one clock, one count
    assert _histogram_counts(ops) == _stage_counts_from_events(events)

    ring.set_metrics_observer(None)


def test_scheduler_spans_nest_assembly_and_flush(ring):
    from tendermint_tpu.crypto.keys import Ed25519PrivKey
    from tendermint_tpu.crypto.scheduler import VerifyScheduler
    from tendermint_tpu.ops import ed25519_batch

    priv = Ed25519PrivKey.from_seed(b"\x07" * 32)
    pk = priv.pub_key().bytes()
    msg = b"sched-traced"
    sig = priv.sign(msg)
    sched = VerifyScheduler(ed25519_batch.verify_batch, max_delay=0.01)
    sched.start()
    try:
        assert sched.verify(pk, msg, sig)
    finally:
        sched.stop()
    events = _complete_events(ring.export())
    names = [e["name"] for e in events]
    assert "sched_assemble" in names
    assert "sched_flush" in names
    flush = next(e for e in events if e["name"] == "sched_flush")
    assert flush["args"]["lanes"] == 1
    # the engine's own spans nest under the scheduler flush
    vb = next(e for e in events if e["name"] == "verify_batch")
    assert vb["args"]["parent"] == "sched_flush"


def test_tracing_off_changes_no_verify_results(monkeypatch):
    from tendermint_tpu.types import validation

    privs, vset = make_validators(8)
    block_id = make_block_id(b"off-mode")
    commit = make_commit(block_id, 3, 0, vset, privs)

    tracing.tracer.set_metrics_observer(None)
    monkeypatch.setenv("TENDERMINT_TPU_TRACE", "off")
    tracing.configure("off")
    tracing.tracer.clear()
    validation.verify_commit(CHAIN_ID, vset, block_id, 3, commit)  # no raise
    assert len(tracing.tracer) == 0

    tracing.configure("ring")
    try:
        validation.verify_commit(CHAIN_ID, vset, block_id, 3, commit)
        assert len(tracing.tracer) > 0
    finally:
        tracing.configure("off")
        tracing.tracer.clear()


def test_file_mode_flush_writes_chrome_trace(tmp_path):
    path = tmp_path / "trace.json"
    tracing.configure(str(path))
    try:
        with tracing.span("flushed", k="v"):
            pass
        written = tracing.tracer.flush()
        assert written == str(path)
        doc = json.loads(path.read_text())
        assert any(
            e.get("name") == "flushed" for e in doc["traceEvents"]
        )
    finally:
        tracing.configure("off")
        tracing.tracer.clear()
