"""Zero-copy shm ingress (verifyd/shm.py): slab-header codec symmetry,
the ring state machine under concurrency (the tpusan hb + seeded-explore
target for the zero-copy PR), and transparent transport negotiation.

The chaos half of the contract — torn slabs, client death mid-write,
server restart with live rings, slow-consumer backpressure into
admission — lives in tests/test_verifyd_chaos.py.
"""

import json
import os
import struct
import threading
import time

import pytest

from tendermint_tpu.verifyd import protocol, shm
from tendermint_tpu.verifyd.client import VerifydClient
from tendermint_tpu.verifyd.server import VerifydServer


def noop_verify(pks, msgs, sigs):
    return [True] * len(pks)


def junk_lanes(n, seed=0):
    """Synthetic lanes for the noop verifier: distinct msgs keep the
    scheduler's coalescing keys distinct."""
    return (
        [bytes([seed % 251 + 1]) * 32] * n,
        [b"shm-%d-%d" % (seed, i) for i in range(n)],
        [b"\x07" * 64] * n,
    )


def make_request(n, seed=0, **kw):
    pks, msgs, sigs = junk_lanes(n, seed)
    return protocol.VerifyRequest(pks=pks, msgs=msgs, sigs=sigs, **kw)


def start_server(**kw):
    kw.setdefault("verify_fn", noop_verify)
    kw.setdefault("max_batch", 64)
    kw.setdefault("max_delay", 0.001)
    kw.setdefault("shm", "on")
    srv = VerifydServer(**kw)
    srv.start()
    return srv


# --- slab header codec -------------------------------------------------------


class TestSlabHeader:
    def _buf(self):
        return bytearray(shm.SLAB_HEADER_BYTES + 64)

    def test_round_trip_all_fields(self):
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=4, kind=protocol.KIND_COMMIT,
            klass=protocol.CLASS_LIGHT, deadline_ms=250,
            algo=protocol.ALGO_SR25519, lanes=17, tenant="chain-a",
            slo_ms=75, shard_id=3, route_epoch=9,
        )
        hdr = shm.unpack_header(buf, 0)
        assert hdr == {
            "gen": 4, "kind": protocol.KIND_COMMIT,
            "klass": protocol.CLASS_LIGHT, "deadline_ms": 250,
            "algo": protocol.ALGO_SR25519, "lanes": 17, "tenant": "chain-a",
            "trace": b"",  # omitted context decodes to the empty default
            "slo_ms": 75,
            "shard_id": 3, "route_epoch": 9,
        }

    def test_omitted_shard_decodes_to_unrouted(self):
        """A zeroed/old header carries no shard id or routing epoch —
        the same zero-omission defaults the omitted protocol fields
        9/10 decode to (-1 unrouted, epoch 0), and slab reuse must not
        leak the previous occupant's routing."""
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1, shard_id=2, route_epoch=5,
        )
        shm.pack_header(
            buf, 0, gen=4, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
        )
        hdr = shm.unpack_header(buf, 0)
        assert hdr["shard_id"] == -1
        assert hdr["route_epoch"] == 0

    def test_omitted_slo_decodes_to_zero(self):
        """A zeroed/old header carries no SLO — same zero-omission
        default as the omitted protocol field 8, and slab reuse must
        not leak the previous occupant's target."""
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1, slo_ms=75,
        )
        shm.pack_header(
            buf, 0, gen=4, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
        )
        assert shm.unpack_header(buf, 0)["slo_ms"] == 0

    def test_consensus_class_zero_survives(self):
        """CLASS_CONSENSUS is 0; it rides the slab +1 so a zeroed word
        cannot masquerade as it — the TCP codec's zero-omission rule."""
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_CONSENSUS, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
        )
        (stored,) = struct.unpack_from("<I", buf, shm.SLAB_OFF_KLASS)
        assert stored == 1  # on-slab encoding is +1
        assert shm.unpack_header(buf, 0)["klass"] == protocol.CLASS_CONSENSUS

    def test_zeroed_klass_word_decodes_to_rpc(self):
        """0 = absent -> CLASS_RPC, mirroring the omitted proto3 field."""
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_BLOCKSYNC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
        )
        struct.pack_into("<I", buf, shm.SLAB_OFF_KLASS, 0)
        assert shm.unpack_header(buf, 0)["klass"] == protocol.CLASS_RPC

    def test_default_tenant_omitted(self):
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
            tenant=protocol.DEFAULT_TENANT,
        )
        (tlen,) = struct.unpack_from("<I", buf, shm.SLAB_OFF_TENANT_LEN)
        assert tlen == 0  # stored as ABSENT, like the omitted field 6
        assert (
            shm.unpack_header(buf, 0)["tenant"] == protocol.DEFAULT_TENANT
        )

    def test_odd_generation_is_torn(self):
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
        )
        shm.stamp_begin(buf, 0, 4)  # writer died mid-fill of gen 4
        with pytest.raises(ValueError, match="torn"):
            shm.unpack_header(buf, 0)

    def test_generation_stamp_mismatch_is_torn(self):
        buf = self._buf()
        shm.pack_header(
            buf, 0, gen=2, kind=protocol.KIND_RAW,
            klass=protocol.CLASS_RPC, deadline_ms=0,
            algo=protocol.ALGO_ED25519, lanes=1,
        )
        struct.pack_into("<I", buf, shm.SLAB_OFF_GEN2, 6)
        with pytest.raises(ValueError, match="torn"):
            shm.unpack_header(buf, 0)

    def test_field_validation(self):
        for field_off, bad in (
            (shm.SLAB_OFF_KIND, 99),
            (shm.SLAB_OFF_ALGO, 99),
            (shm.SLAB_OFF_LANES, shm.SHM_MAX_LANES + 1),
            (shm.SLAB_OFF_TENANT_LEN, protocol.MAX_TENANT_LEN + 1),
            (shm.SLAB_OFF_SLO_MS, protocol.MAX_SLO_MS + 1),
            (shm.SLAB_OFF_DEADLINE_MS, protocol.MAX_DEADLINE_MS + 1),
        ):
            buf = self._buf()
            shm.pack_header(
                buf, 0, gen=2, kind=protocol.KIND_RAW,
                klass=protocol.CLASS_RPC, deadline_ms=0,
                algo=protocol.ALGO_ED25519, lanes=1,
            )
            struct.pack_into("<I", buf, field_off, bad)
            struct.pack_into("<I", buf, shm.SLAB_OFF_GEN2, 2)
            struct.pack_into("<I", buf, shm.SLAB_OFF_GEN, 2)
            with pytest.raises(ValueError):
                shm.unpack_header(buf, 0)

    def test_lane_payload_round_trip_zero_copy(self):
        pks, msgs, sigs = junk_lanes(5, seed=3)
        buf = bytearray(shm.slab_bytes_needed(msgs) + 16)
        shm.pack_lanes(buf, 0, pks, msgs, sigs)
        got_pks, got_msgs, got_sigs = shm.unpack_lanes(
            memoryview(buf), 0, 5, len(buf)
        )
        assert got_pks == pks and got_sigs == sigs
        assert all(type(m) is memoryview for m in got_msgs)
        assert [bytes(m) for m in got_msgs] == msgs

    def test_lane_table_walking_out_of_slab_rejected(self):
        pks, msgs, sigs = junk_lanes(2)
        buf = bytearray(shm.slab_bytes_needed(msgs) + 16)
        shm.pack_lanes(buf, 0, pks, msgs, sigs)
        # corrupt one msg_len so the payload claims to exceed the slab
        struct.pack_into("<I", buf, shm.SLAB_HEADER_BYTES, 1 << 20)
        with pytest.raises(ValueError):
            shm.unpack_lanes(memoryview(buf), 0, 2, len(buf))

    def test_lane_count_overflowing_table_is_valueerror_not_struct_error(self):
        """On the segment's LAST slab a garbage lane count whose table
        alone walks off the buffer must raise ValueError (answered
        STATUS_INVALID by the drain), not let struct.error escape the
        drain worker and wedge TAIL behind a forever-inflight seq."""
        slab = 256
        buf = bytearray(slab)  # exactly one slab: nothing after it
        lanes = (slab - shm.SLAB_HEADER_BYTES) // 4 + 1
        with pytest.raises(ValueError, match="lane table"):
            shm.unpack_lanes(memoryview(buf), 0, lanes, slab)


def test_encoded_request_size_matches_encoder():
    """``codec_bytes_avoided`` must report what the TCP wire would have
    cost — exactly, over every zero-omission branch of the encoder."""
    cases = [
        make_request(3),
        make_request(1, klass=protocol.CLASS_CONSENSUS),
        make_request(4, kind=protocol.KIND_COMMIT, deadline_ms=500),
        make_request(2, algo=protocol.ALGO_SR25519, tenant="chain-b"),
        protocol.VerifyRequest(
            pks=[b"\x01" * 32], msgs=[b""], sigs=[b"\x02" * 64]
        ),
        make_request(7, klass=protocol.CLASS_BLOCKSYNC, tenant="x" * 64),
    ]
    for req in cases:
        assert protocol.encoded_request_size(req) == len(
            protocol.encode_request(req)
        ), req


# --- ring state machine (tpusan hb + seeded-explore target) ------------------


class TestRingStateMachine:
    def test_sequential_calls_reuse_slots_past_ring_size(self):
        srv = start_server()
        try:
            t = shm.connect(srv.address[1])
            try:
                rounds = shm.DEFAULT_NSLABS * 3 + 1
                for i in range(rounds):
                    resp = t.call(make_request(2, seed=i), timeout=10.0)
                    assert resp.status == protocol.STATUS_OK
                    assert resp.verdicts == [True, True]
            finally:
                t.close()
            assert srv.stats()["shm_lanes"] == rounds * 2
            assert srv.stats()["shm_torn_slabs"] == 0
        finally:
            srv.stop()

    def test_concurrent_callers_share_one_ring(self):
        """Pool threads race acquire/fill/commit/wait on one transport;
        every call resolves with correct verdict counts and no slab is
        ever read torn. This is the schedule-exploration target."""
        srv = start_server(max_batch=32)
        try:
            t = shm.connect(srv.address[1])
            errors = []
            done = [0]
            mtx = threading.Lock()

            def caller(i):
                try:
                    for j in range(6):
                        n = 1 + (i + j) % 4
                        # consensus class: exercises the ring, never the
                        # shed path (serialized explore schedules inflate
                        # service-time EWMAs enough to shed rpc lanes)
                        resp = t.call(
                            make_request(
                                n,
                                seed=i * 100 + j,
                                klass=protocol.CLASS_CONSENSUS,
                            ),
                            timeout=30.0,
                        )
                        assert resp.status == protocol.STATUS_OK, resp
                        assert len(resp.verdicts) == n
                    with mtx:
                        done[0] += 1
                except Exception as exc:  # noqa: BLE001 - recorded
                    with mtx:
                        errors.append((i, repr(exc)))

            threads = [
                threading.Thread(target=caller, args=(i,)) for i in range(4)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=30)
            t.close()
            assert not errors, errors
            assert done[0] == 4
            assert srv.stats()["shm_torn_slabs"] == 0
        finally:
            srv.stop()

    def test_ring_full_raises_busy_and_recovers(self):
        """A wedged consumer fills the ring; the next acquire raises
        ShmBusy (the caller's cue to ride TCP) instead of blocking, and
        the ring drains normally once the consumer resumes."""
        gate = threading.Event()
        srv = start_server()
        shm._TEST_DRAIN_GATE = gate.wait
        try:
            t = shm.connect(srv.address[1], nslabs=2)
            results = []
            res_mtx = threading.Lock()

            def submit(i):
                resp = t.call(make_request(1, seed=i), timeout=15.0)
                with res_mtx:
                    results.append(resp.status)

            inflight = [
                threading.Thread(target=submit, args=(i,)) for i in range(2)
            ]
            for th in inflight:
                th.start()
            deadline = time.monotonic() + 5
            while t._ring.head() < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            with pytest.raises(shm.ShmBusy):
                t.call(make_request(1, seed=99), timeout=0.3)
            gate.set()
            for th in inflight:
                th.join(timeout=15)
            assert results == [protocol.STATUS_OK, protocol.STATUS_OK]
            # ring usable again after the stall
            resp = t.call(make_request(1, seed=100), timeout=10.0)
            assert resp.status == protocol.STATUS_OK
            t.close()
        finally:
            shm._TEST_DRAIN_GATE = None
            gate.set()
            srv.stop()

    def test_oversized_request_rides_tcp_session_stays_up(self):
        """> SHM_MAX_LANES exceeds the slab contract: that one request
        falls back to TCP (split at the codec's MAX_LANES), counted as a
        fallback, while the shm session keeps serving."""
        srv = start_server(
            max_batch=4096, admission_cap=4 * shm.SHM_MAX_LANES,
            max_pending=4 * shm.SHM_MAX_LANES,
        )
        try:
            h, p = srv.address
            # long timeout: the wire deadline derives from it, and the
            # 8200-lane TCP detour is slow under explore serialization
            c = VerifydClient(f"{h}:{p}", shm="on", fallback=False,
                              timeout=60.0)
            big = shm.SHM_MAX_LANES + 8
            pks, msgs, sigs = junk_lanes(big, seed=5)
            oks = c.verify(pks, msgs, sigs, klass=protocol.CLASS_CONSENSUS)
            assert oks == [True] * big
            stats = c.stats()
            assert stats["shm_fallbacks"] == 1
            assert stats["shm_calls"] == 0
            # the session survived the detour
            oks = c.verify(
                *junk_lanes(4, seed=6), klass=protocol.CLASS_CONSENSUS
            )
            assert oks == [True] * 4
            assert c.stats()["shm_calls"] == 1
            c.close()
        finally:
            srv.stop()

    def test_deadline_response_frees_slab_after_entries_resolve(self):
        """A deadline verdict can outrun lanes that still hold slab
        memoryviews: the server answers held, the janitor frees the slab
        once the flush resolves, and the ring stays fully reusable."""
        release = threading.Event()

        def gated(pks, msgs, sigs):
            release.wait(10)
            return [True] * len(pks)

        srv = start_server(verify_fn=gated, max_delay=0.001)
        try:
            t = shm.connect(srv.address[1])
            resp = t.call(
                make_request(2, seed=1, deadline_ms=80), timeout=10.0
            )
            assert resp.status == protocol.STATUS_DEADLINE_EXCEEDED
            release.set()
            # every slot cycles through post-janitor reclaim
            for i in range(shm.DEFAULT_NSLABS + 2):
                resp = t.call(make_request(1, seed=10 + i), timeout=10.0)
                assert resp.status == protocol.STATUS_OK
            t.close()
        finally:
            release.set()
            srv.stop()


# --- negotiation -------------------------------------------------------------


class TestNegotiation:
    def test_auto_negotiates_shm_when_colocated(self):
        srv = start_server()
        try:
            h, p = srv.address
            assert srv.shm_socket_path
            c = VerifydClient(f"{h}:{p}", shm="auto", fallback=False)
            assert c.transport == "tcp"  # nothing negotiated yet
            oks = c.verify(*junk_lanes(3, seed=1))
            assert oks == [True] * 3
            assert c.transport == "shm"
            stats = c.stats()
            assert stats["shm_calls"] == 1
            assert stats["shm_lanes"] == 3
            assert stats["shm_bytes_avoided"] > 0
            c.close()
        finally:
            srv.stop()

    def test_off_mode_restores_pure_tcp(self):
        srv = start_server()
        try:
            h, p = srv.address
            c = VerifydClient(f"{h}:{p}", shm="off", fallback=False)
            oks = c.verify(*junk_lanes(3, seed=2))
            assert oks == [True] * 3
            stats = c.stats()
            assert stats["transport"] == "tcp"
            assert stats["shm_calls"] == 0
            assert stats["shm_fallbacks"] == 0
            assert stats["shm_bytes_avoided"] == 0
            c.close()
        finally:
            srv.stop()

    def test_tcp_only_server_is_negotiation_not_fallback_in_auto(self):
        srv = start_server(shm="off")
        try:
            h, p = srv.address
            assert srv.shm_socket_path == ""
            c = VerifydClient(f"{h}:{p}", shm="auto", fallback=False)
            assert c.verify(*junk_lanes(2, seed=3)) == [True, True]
            assert c.transport == "tcp"
            assert c.stats()["shm_fallbacks"] == 0  # auto: working as designed
            c.close()
            # "on" is a demand: the missing endpoint counts
            c2 = VerifydClient(f"{h}:{p}", shm="on", fallback=False)
            assert c2.verify(*junk_lanes(2, seed=4)) == [True, True]
            assert c2.stats()["shm_fallbacks"] == 1
            c2.close()
        finally:
            srv.stop()

    def test_mode_resolution_order(self, monkeypatch):
        monkeypatch.delenv(shm.SHM_ENV, raising=False)
        assert shm.shm_mode() == "auto"
        monkeypatch.setenv(shm.SHM_ENV, "off")
        assert shm.shm_mode() == "off"
        monkeypatch.setenv(shm.SHM_ENV, "bogus")
        assert shm.shm_mode() == "auto"  # forgiving, like ops/ flags
        monkeypatch.setenv(shm.SHM_ENV, "off")
        shm.set_shm_mode("on")  # config file beats environment
        try:
            assert shm.shm_mode() == "on"
        finally:
            shm.set_shm_mode("")
        assert shm.shm_mode() == "off"
        with pytest.raises(ValueError):
            shm.set_shm_mode("sideways")

    def test_remote_host_never_attaches(self):
        srv = start_server()
        try:
            _, p = srv.address
            assert not shm.is_local("db3.example.com")
            assert shm.is_local("127.0.0.1") and shm.is_local("localhost")
            c = VerifydClient(f"127.0.0.1:{p}", fallback=False)
            c._shm_local = False  # as a cross-host addr would resolve
            assert c.verify(*junk_lanes(2, seed=5)) == [True, True]
            assert c.transport == "tcp"
            assert c.stats()["shm_calls"] == 0
            c.close()
        finally:
            srv.stop()

    def test_advertise_retract_is_token_scoped(self, tmp_path):
        port = 59999
        path = shm.advertise(port, "/tmp/sock-a", "token-a")
        try:
            assert shm.read_endpoint(port)["token"] == "token-a"
            assert (os.stat(path).st_mode & 0o777) == 0o600
            # a restarted server replaced the advert; the old instance's
            # retract must not tear the new advert down
            shm.advertise(port, "/tmp/sock-b", "token-b")
            shm.retract(port, "token-a")
            assert shm.read_endpoint(port)["token"] == "token-b"
            shm.retract(port, "token-b")
            assert shm.read_endpoint(port) is None
        finally:
            try:
                os.unlink(shm.endpoint_path(port))
            except OSError:
                pass

    def test_advert_lives_in_private_runtime_dir(self, tmp_path, monkeypatch):
        """The advert name is predictable, so it must live in a 0700
        per-user dir — never the world-writable temp dir where any local
        user could plant a verdict-forging endpoint for a known port."""
        monkeypatch.setenv("XDG_RUNTIME_DIR", str(tmp_path))
        port = 59901
        path = shm.advertise(port, "/tmp/sock-x", "tok")
        try:
            advert_dir = os.path.dirname(path)
            assert advert_dir == str(tmp_path / "tendermint-tpu")
            assert (os.stat(advert_dir).st_mode & 0o077) == 0
            assert os.stat(advert_dir).st_uid == os.geteuid()
            assert shm.read_endpoint(port)["token"] == "tok"
        finally:
            shm.retract(port, "tok")

    def test_spoofed_advert_rejected(self, tmp_path, monkeypatch):
        """Owner/mode/symlink checks on the advert itself: a file our
        euid did not write with 0600 is never trusted, even inside the
        runtime dir."""
        monkeypatch.setenv("XDG_RUNTIME_DIR", str(tmp_path))
        port = 59902
        path = shm.advertise(port, "/tmp/sock-y", "tok")
        try:
            # group/other-accessible advert: not trusted
            os.chmod(path, 0o644)
            assert shm.read_endpoint(port) is None
            os.chmod(path, 0o600)
            assert shm.read_endpoint(port)["token"] == "tok"
            # a symlink planted at the advert name is never followed
            os.unlink(path)
            real = tmp_path / "evil-endpoint"
            real.write_text(
                json.dumps(
                    {"v": shm.SHM_VERSION, "socket": "/tmp/evil", "token": "x"}
                )
            )
            os.chmod(real, 0o600)
            os.symlink(real, path)
            assert shm.read_endpoint(port) is None
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass

    def test_attach_with_bad_token_rejected(self):
        srv = start_server()
        try:
            _, p = srv.address
            ep = shm.read_endpoint(p)
            with pytest.raises(shm.ShmAttachError, match="token"):
                shm.ShmClientTransport(ep["socket"], "not-the-token")
            deadline = time.monotonic() + 5
            while (
                srv.stats()["shm_fallbacks"] < 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert srv.stats()["shm_fallbacks"] == 1
        finally:
            srv.stop()

    def test_server_stats_surface_shm_counters(self):
        srv = start_server()
        try:
            stats = srv.stats()
            for key in (
                "shm_lanes", "shm_torn_slabs", "shm_fallbacks",
                "shm_sessions",
            ):
                assert key in stats
            assert srv.shm_backlog() == 0
        finally:
            srv.stop()
