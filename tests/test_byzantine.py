"""Byzantine behavior: equivocating validators yield committed evidence.

The in-process analog of internal/consensus/byzantine_test.go and
invalid_test.go:

- an equivocating PREVOTER (double-signs conflicting prevotes) — honest
  peers detect the conflict in their vote sets (types/vote_set.go
  conflicting-vote tracking), turn it into DuplicateVoteEvidence
  (evidence pool reportConflictingVotes), gossip it, and a later
  proposer commits it into a block;
- an equivocating PROPOSER (byzantine_test.go:41): signs TWO different
  proposal blocks for the same height/round, sends each to a different
  half of its peers, and double-signs its own precommits to match —
  the network stays live and the double-sign lands on-chain as
  DuplicateVoteEvidence;
- malformed-vote injection (invalid_test.go): garbage signatures, bad
  indices, absurd heights — dropped without loss of liveness.
"""

import time

import pytest

from tendermint_tpu.consensus.reactor import (
    DATA_CHANNEL,
    VOTE_CHANNEL,
    encode_block_part,
    encode_proposal,
    encode_vote,
)
from tendermint_tpu.p2p.router import Envelope
from tendermint_tpu.types.block import BlockID, Proposal, Vote
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.part_set import PartSet

from tests.test_node import fast_genesis, make_node, wait_for, four_privs  # noqa: F401
from tendermint_tpu.p2p.transport import MemoryNetwork
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
)


def _make_equivocator(node, chain_id):
    """Wrap the reactor's broadcast_vote: every non-nil prevote is paired
    with a conflicting nil prevote signed by the same key (the
    double-sign byzantine_test.go injects)."""
    reactor = node.consensus_reactor
    pv = node.priv_validator
    orig = reactor.broadcast_vote

    def byzantine_broadcast(vote: Vote) -> None:
        orig(vote)
        if vote.type == SIGNED_MSG_TYPE_PREVOTE and not vote.block_id.is_nil():
            dup = Vote(
                type=vote.type,
                height=vote.height,
                round=vote.round,
                block_id=BlockID(),  # nil: conflicts with the real prevote
                timestamp=vote.timestamp,
                validator_address=vote.validator_address,
                validator_index=vote.validator_index,
            )
            # Sign directly with the key, bypassing FilePV's double-sign
            # guard — that guard is exactly what a byzantine node ignores.
            dup.signature = pv.priv_key.sign(dup.sign_bytes(chain_id))
            orig(dup)

    reactor.broadcast_vote = byzantine_broadcast


def _split_peers(reactor):
    """Deterministic halves of the byzantine node's live peer set."""
    peers = sorted(reactor._peers)
    return peers[: len(peers) // 2], peers[len(peers) // 2 :]


def _make_proposer_equivocator(node, chain_id):
    """byzantine_test.go:41 - the byzantine PROPOSER signs two different
    blocks for the same (height, round), sends block A to one half of
    its peers and block B to the other, and double-signs its own
    non-nil votes to match. Both signed artifacts are genuine — only
    FilePV's double-sign guard is bypassed, exactly what a byzantine
    signer would do."""
    from tendermint_tpu.types.block import BLOCK_PART_SIZE_BYTES

    reactor = node.consensus_reactor
    cs = node.consensus
    pv = node.priv_validator
    alt = {}  # (height, round) -> alternative proposal (for block B)

    orig_decide = cs.decide_proposal

    def split_decide(height, round_):
        orig_decide(height, round_)  # proposes + broadcasts block A
        block_b = cs._create_proposal_block()
        if block_b is None:
            return
        parts_b = PartSet.from_data(
            block_b.to_proto_bytes(), BLOCK_PART_SIZE_BYTES
        )
        prop_b = Proposal(
            height=height,
            round=round_,
            pol_round=-1,
            block_id=BlockID(block_b.hash(), parts_b.header()),
            timestamp=block_b.header.time,
        )
        prop_b.signature = pv.priv_key.sign(prop_b.sign_bytes(chain_id))
        alt[(height, round_)] = prop_b
        _, second_half = _split_peers(reactor)
        for pid in second_half:
            reactor.data_ch.send(
                Envelope(DATA_CHANNEL, encode_proposal(prop_b), to_peer=pid)
            )
            for i in range(parts_b.total):
                reactor.data_ch.send(
                    Envelope(
                        DATA_CHANNEL,
                        encode_block_part(height, round_, parts_b.get_part(i)),
                        to_peer=pid,
                    )
                )

    cs.decide_proposal = split_decide

    orig_bvote = reactor.broadcast_vote

    def split_vote(vote: Vote) -> None:
        prop_b = alt.get((vote.height, vote.round))
        first_half, second_half = _split_peers(reactor)
        if prop_b is None or vote.block_id.is_nil() or not second_half:
            orig_bvote(vote)
            return
        dup = Vote(
            type=vote.type,
            height=vote.height,
            round=vote.round,
            block_id=prop_b.block_id,
            timestamp=vote.timestamp,
            validator_address=vote.validator_address,
            validator_index=vote.validator_index,
        )
        dup.signature = pv.priv_key.sign(dup.sign_bytes(chain_id))
        for pid in first_half:
            reactor.vote_ch.send(
                Envelope(VOTE_CHANNEL, encode_vote(vote), to_peer=pid)
            )
        for pid in second_half:
            reactor.vote_ch.send(
                Envelope(VOTE_CHANNEL, encode_vote(dup), to_peer=pid)
            )

    reactor.broadcast_vote = split_vote


class TestByzantine:
    def test_equivocating_prevoter_gets_evidenced(self, tmp_path, four_privs):
        net = MemoryNetwork()
        nodes = []
        for i in range(4):
            node, _ = make_node(tmp_path, f"node{i}", four_privs, index=i, net=net)
            nodes.append(node)
        for i, node in enumerate(nodes):
            if i > 0:
                node.config.persistent_peers = [
                    f"{nodes[0].node_key.node_id}@node0"
                ]
        _make_equivocator(nodes[2], nodes[2].genesis.chain_id)
        for node in nodes:
            node.start()
        try:
            assert wait_for(
                lambda: all(len(n.router.connected_peers()) >= 1 for n in nodes),
                timeout=10,
            ), "peers failed to connect"

            byz_addr = four_privs[2].get_pub_key().address()

            def committed_duplicate_vote_evidence():
                for n in nodes:
                    for h in range(1, n.height + 1):
                        blk = n.block_store.load_block(h)
                        if blk is None:
                            continue
                        for ev in blk.evidence:
                            if (
                                isinstance(ev, DuplicateVoteEvidence)
                                and ev.vote_a.validator_address == byz_addr
                            ):
                                return True
                return False

            assert wait_for(committed_duplicate_vote_evidence, timeout=90), (
                f"no DuplicateVoteEvidence committed; heights: "
                f"{[n.height for n in nodes]}"
            )
        finally:
            for node in nodes:
                node.stop()

    def test_equivocating_proposer_gets_evidenced(self, tmp_path, four_privs):
        """The byzantine node is the hub so its split reaches every honest
        peer directly; its canonical votes relay through gossip, so the
        conflicting pair meets in some honest vote set, becomes
        DuplicateVoteEvidence, and is committed — while the network
        keeps producing blocks (byzantine_test.go:41)."""
        net = MemoryNetwork()
        nodes = []
        for i in range(4):
            node, _ = make_node(tmp_path, f"node{i}", four_privs, index=i, net=net)
            nodes.append(node)
        for i, node in enumerate(nodes):
            if i > 0:
                node.config.persistent_peers = [
                    f"{nodes[0].node_key.node_id}@node0"
                ]
        _make_proposer_equivocator(nodes[0], nodes[0].genesis.chain_id)
        for node in nodes:
            node.start()
        try:
            assert wait_for(
                lambda: all(len(n.router.connected_peers()) >= 1 for n in nodes),
                timeout=10,
            ), "peers failed to connect"

            byz_addr = four_privs[0].get_pub_key().address()

            def committed_byz_evidence():
                for n in nodes:
                    for h in range(1, n.height + 1):
                        blk = n.block_store.load_block(h)
                        if blk is None:
                            continue
                        for ev in blk.evidence:
                            if (
                                isinstance(ev, DuplicateVoteEvidence)
                                and ev.vote_a.validator_address == byz_addr
                            ):
                                return True
                return False

            assert wait_for(committed_byz_evidence, timeout=120), (
                f"no DuplicateVoteEvidence for the proposer committed; "
                f"heights: {[n.height for n in nodes]}"
            )
            # Liveness: the split did not halt the chain.
            assert wait_for(
                lambda: all(n.height >= 3 for n in nodes if n is not nodes[0]),
                timeout=60,
            ), f"liveness lost: {[n.height for n in nodes]}"
        finally:
            for node in nodes:
                node.stop()

    def test_invalid_vote_flood_preserves_liveness(self, tmp_path, four_privs):
        """invalid_test.go: one node floods peers with malformed votes —
        garbage signatures, out-of-range indices, absurd heights. Honest
        nodes drop them all and keep committing."""
        net = MemoryNetwork()
        nodes = []
        for i in range(4):
            node, _ = make_node(tmp_path, f"node{i}", four_privs, index=i, net=net)
            nodes.append(node)
        for i, node in enumerate(nodes):
            if i > 0:
                node.config.persistent_peers = [
                    f"{nodes[0].node_key.node_id}@node0"
                ]
        evil = nodes[1]
        reactor = evil.consensus_reactor
        orig = reactor.broadcast_vote

        def flooding_broadcast(vote: Vote) -> None:
            orig(vote)
            base = dict(
                type=vote.type,
                height=vote.height,
                round=vote.round,
                block_id=vote.block_id,
                timestamp=vote.timestamp,
                validator_address=vote.validator_address,
                validator_index=vote.validator_index,
            )
            garbage = [
                Vote(**{**base, "signature": b"\x01" * 64}),
                Vote(**{**base, "validator_index": 97,
                        "signature": b"\x02" * 64}),
                Vote(**{**base, "height": vote.height + 10_000,
                        "signature": b"\x03" * 64}),
            ]
            for g in garbage:
                reactor.vote_ch.broadcast(encode_vote(g))

        reactor.broadcast_vote = flooding_broadcast
        for node in nodes:
            node.start()
        try:
            assert wait_for(
                lambda: all(len(n.router.connected_peers()) >= 1 for n in nodes),
                timeout=10,
            ), "peers failed to connect"
            assert wait_for(
                lambda: all(n.height >= 3 for n in nodes), timeout=90
            ), f"liveness lost under invalid-vote flood: {[n.height for n in nodes]}"
        finally:
            for node in nodes:
                node.stop()
