"""Persistence: key-value abstraction, block store, state store
(reference: tm-db, internal/store/, internal/state/store.go)."""

from tendermint_tpu.storage.kv import Batch, KVStore, MemDB

__all__ = ["Batch", "KVStore", "MemDB"]
