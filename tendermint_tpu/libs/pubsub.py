"""Query-filtered publish/subscribe for the event bus.

The reference's pubsub (internal/pubsub/pubsub.go:92, query language in
internal/pubsub/query/) delivers every published message to each
subscription whose query matches the message's event attributes. This
is the in-process analog: a ``Server`` holds named subscriptions, each
with a bounded queue; ``publish`` fans out synchronously under a lock
(publishers are the consensus/execution threads, subscribers drain from
their own queues, mirroring the buffered-channel design).

Query syntax (internal/pubsub/query/syntax): conditions joined by AND,
each ``key op value`` with ops =, <, <=, >, >=, CONTAINS, EXISTS.
Values are single-quoted strings or bare numbers; string equality is
exact, numeric comparisons apply when both sides parse as numbers.
"""

from __future__ import annotations

import queue
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

Events = Dict[str, List[str]]  # composite key -> values, e.g. "tx.height" -> ["5"]


# --- query language ---------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<and>AND\b)
      | (?P<op><=|>=|=|<|>)
      | (?P<contains>CONTAINS\b)
      | (?P<exists>EXISTS\b)
      | (?P<str>'(?:[^'])*')
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


@dataclass(frozen=True)
class Condition:
    key: str
    op: str  # '=', '<', '<=', '>', '>=', 'CONTAINS', 'EXISTS'
    value: str = ""

    def matches(self, events: Events) -> bool:
        values = events.get(self.key)
        if values is None:
            return False
        if self.op == "EXISTS":
            return True
        if self.op == "CONTAINS":
            return any(self.value in v for v in values)
        if self.op == "=":
            num = _as_num(self.value)
            for v in values:
                if v == self.value:
                    return True
                if num is not None:
                    vn = _as_num(v)
                    if vn is not None and vn == num:
                        return True
            return False
        # numeric comparisons
        num = _as_num(self.value)
        if num is None:
            return False
        for v in values:
            vn = _as_num(v)
            if vn is None:
                continue
            if self.op == "<" and vn < num:
                return True
            if self.op == "<=" and vn <= num:
                return True
            if self.op == ">" and vn > num:
                return True
            if self.op == ">=" and vn >= num:
                return True
        return False


def _as_num(s: str) -> Optional[float]:
    try:
        return float(s)
    except ValueError:
        return None


class QueryError(ValueError):
    pass


@dataclass(frozen=True)
class Query:
    """A parsed query: AND of conditions (reference Query.Matches)."""

    conditions: Tuple[Condition, ...]
    source: str = ""

    @staticmethod
    def parse(s: str) -> "Query":
        tokens = _tokenize(s)
        conds: List[Condition] = []
        i = 0
        while i < len(tokens):
            kind, val = tokens[i]
            if kind != "key":
                raise QueryError(f"expected key at token {i} in {s!r}")
            if i + 1 >= len(tokens):
                raise QueryError(f"dangling key {val!r} in {s!r}")
            okind, oval = tokens[i + 1]
            if okind == "exists":
                conds.append(Condition(val, "EXISTS"))
                i += 2
            elif okind in ("op", "contains"):
                if i + 2 >= len(tokens):
                    raise QueryError(f"missing value in {s!r}")
                vkind, vval = tokens[i + 2]
                if vkind not in ("str", "num"):
                    raise QueryError(f"bad value {vval!r} in {s!r}")
                op = "CONTAINS" if okind == "contains" else oval
                conds.append(Condition(val, op, vval))
                i += 3
            else:
                raise QueryError(f"expected operator after {val!r} in {s!r}")
            if i < len(tokens):
                kind, _ = tokens[i]
                if kind != "and":
                    raise QueryError(f"expected AND at token {i} in {s!r}")
                i += 1
        if not conds:
            raise QueryError(f"empty query: {s!r}")
        return Query(tuple(conds), s)

    def matches(self, events: Events) -> bool:
        return all(c.matches(events) for c in self.conditions)

    def __str__(self) -> str:
        return self.source


def _tokenize(s: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise QueryError(f"bad token at {pos} in {s!r}")
        pos = m.end()
        for kind in ("and", "op", "contains", "exists", "str", "num", "key"):
            val = m.group(kind)
            if val is not None:
                if kind == "str":
                    val = val[1:-1]
                out.append((kind, val))
                break
    return out


# --- pubsub server ----------------------------------------------------------


@dataclass
class Message:
    """A delivered pubsub message (reference pubsub.Message)."""

    data: object
    events: Events
    subscription_id: str = ""


class Subscription:
    """A subscriber's bounded queue of matching messages."""

    def __init__(self, subscriber: str, query: Query, capacity: int = 100):
        self.subscriber = subscriber
        self.query = query
        self._q: "queue.Queue[Message]" = queue.Queue(maxsize=capacity)
        self.cancelled = threading.Event()

    def next(self, timeout: Optional[float] = None) -> Optional[Message]:
        """Blocking pop; None on timeout or cancellation."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self) -> List[Message]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def _offer(self, msg: Message) -> bool:
        try:
            self._q.put_nowait(msg)
            return True
        except queue.Full:
            return False


class PubSubServer:
    """Fan-out hub (pubsub.go:92). Slow subscribers are *dropped from*,
    not blocked on: a full queue loses the message for that subscriber
    (the reference terminates such subscriptions; callers that need
    lossless streams use the indexer/eventlog instead)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: Dict[Tuple[str, str], Subscription] = {}

    def subscribe(
        self, subscriber: str, query: str | Query, capacity: int = 100
    ) -> Subscription:
        q = Query.parse(query) if isinstance(query, str) else query
        sub = Subscription(subscriber, q, capacity)
        with self._lock:
            key = (subscriber, str(q))
            if key in self._subs:
                raise ValueError(f"already subscribed: {key}")
            self._subs[key] = sub
        return sub

    def unsubscribe(self, subscriber: str, query: str) -> None:
        with self._lock:
            sub = self._subs.pop((subscriber, query), None)
        if sub is not None:
            sub.cancelled.set()

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._lock:
            keys = [k for k in self._subs if k[0] == subscriber]
            for k in keys:
                self._subs.pop(k).cancelled.set()

    def publish(self, data: object, events: Events) -> None:
        with self._lock:
            subs = list(self._subs.values())
        for sub in subs:
            if sub.query.matches(events):
                sub._offer(Message(data, events, sub.subscriber))

    def num_clients(self) -> int:
        with self._lock:
            return len({k[0] for k in self._subs})

    def num_subscriptions(self) -> int:
        with self._lock:
            return len(self._subs)
