"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The build machine exposes one real TPU chip through the experimental
``axon`` platform; tests instead run on CPU with 8 virtual devices so
multi-chip sharding paths (shard_map over a Mesh) are exercised without
real hardware, per the reference test strategy of substituting in-memory
fakes for the real transport (SURVEY.md section 4).

This must run before anything imports jax and initializes a backend.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

# tpusan must patch threading BEFORE jax (and the package under test)
# create any locks, so this sits above the jax import. Activated only
# by TENDERMINT_TPU_SANITIZE=1|hb|explore:<seed> (ci_checks.sh);
# install() parses the mode from the env var itself.
from tendermint_tpu.libs import sanitizer as _sanitizer

if _sanitizer.enabled_from_env():
    _sanitizer.install()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_terminal_summary(terminalreporter):
    """With the sanitizer on, print its findings at the end of the run.
    ci_checks.sh greps the output for the LOCK-ORDER CYCLE and
    DATA RACE markers."""
    if _sanitizer.installed():
        class _Writer:
            def write(self, text):
                terminalreporter.write(text)

        terminalreporter.section("tpusan (concurrency sanitizer)")
        _sanitizer.print_report(_Writer())


@pytest.fixture(autouse=True)
def _tpusan_explore():
    """Under TENDERMINT_TPU_SANITIZE=explore:<seed>, serialize each
    test's threads through the seeded cooperative scheduler. Threads
    started outside the test (jax pools, leaked daemons) free-run; the
    per-test scope keeps the schedule a pure function of the seed."""
    if _sanitizer.active_mode() == "explore":
        with _sanitizer.explore_scope():
            yield
    else:
        yield


@pytest.fixture(autouse=True)
def _fresh_verify_caches(monkeypatch):
    """Pin the verify caches to a known state per test.

    The result cache defaults ON in production; under pytest the suite
    reuses identical (pk, msg, sig) triples across tests, so a default-on
    cache would short-circuit device paths other tests assert on
    (fallback counters, kernel dispatch warnings). Tests that exercise
    the caches opt back in with monkeypatch (tests/test_precompute.py).
    """
    from tendermint_tpu.ops import precompute
    from tendermint_tpu.parallel import mesh

    monkeypatch.setenv(precompute._RESULT_ENV, "0")
    precompute.reset()
    # Pin the sharded verify engine OFF for the general suite: with the
    # virtual 8-mesh above, any ≥256-lane verify would otherwise shard
    # and recompile per shape, blowing the tier-1 time budget. Mesh
    # tests (tests/test_mesh.py) opt back in with monkeypatch.
    monkeypatch.setenv(mesh.MESH_ENV, "1")
    mesh.manager.reset()
    yield
    mesh.manager.reset()
