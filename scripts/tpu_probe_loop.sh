#!/bin/bash
# Probe the axon TPU tunnel until it answers with a FRESH H2D transfer
# (cached-buffer re-execution lies — see scripts/TPU_PROBE_LOG.md), then
# immediately run bench.py to capture a real-chip number for the round.
# Keeps probing after a success so later-built impls (mxu) get measured too.
LOG=/root/repo/scripts/TPU_PROBE_LOG.md
for i in $(seq 1 200); do
  if timeout 90 python -u -c "
import numpy as np, jax
x = np.random.randint(0,255,(1024,32),dtype=np.uint8)
d = jax.device_put(x); d.block_until_ready()
plat = list(d.devices())[0].platform
assert plat not in ('cpu',), plat
print('H2D ok on', plat)
" >/tmp/tpu_probe.log 2>&1; then
    echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ) — probe loop: chip ALIVE (fresh H2D ok), attempt $i; launching bench" >> "$LOG"
    # BENCH_TIMEOUT=700 keeps primary attempt + CPU fallback under the
    # outer 1800s kill; a CPU-fallback result must NOT be published as a
    # live-chip number, so gate the copy on the backend field.
    ( cd /root/repo && timeout 1800 env BENCH_TIMEOUT=700 python bench.py > /tmp/bench_live.json 2>/tmp/bench_live.err
      rc=$?
      if [ $rc -eq 0 ] && grep -q '"backend": *"\(tpu\|axon\)"' /tmp/bench_live.json; then
        cp /tmp/bench_live.json /root/repo/BENCH_live.json
        echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ) — probe-loop bench SUCCEEDED on chip: $(tail -1 /tmp/bench_live.json)" >> "$LOG"
        # Also measure the int8-MXU formulation on the live chip.
        timeout 1800 env BENCH_SKIP_COMMIT=1 BENCH_TIMEOUT=700 python bench.py --impl=mxu > /tmp/bench_mxu.json 2>/tmp/bench_mxu.err
        if [ $? -eq 0 ] && grep -q '"backend": *"\(tpu\|axon\)"' /tmp/bench_mxu.json; then
          cp /tmp/bench_mxu.json /root/repo/BENCH_live_mxu.json
          echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ) — probe-loop bench --impl=mxu on chip: $(tail -1 /tmp/bench_mxu.json)" >> "$LOG"
        else
          echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ) — probe-loop bench --impl=mxu failed or fell back to cpu" >> "$LOG"
        fi
      else
        echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ) — probe-loop bench rc=$rc (failed or cpu fallback; not published)" >> "$LOG"
      fi )
    sleep 600
  else
    sleep 150
  fi
done
