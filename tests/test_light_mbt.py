"""Model-based light-client conformance tests.

Drives the TLA+-model-generated traces the reference ships
(light/mbt/json/*.json, vendored unchanged into tests/mbt_json/ as test
VECTORS — they carry real ed25519 signatures over canonical sign bytes,
so passing them proves byte-exact wire compatibility of header hashing,
vote sign bytes, commit verification, and the skipping-verification
trust logic all at once). The driver mirrors light/mbt/driver_test.go:
for each input block, light.verify must yield the trace's verdict —
SUCCESS, NOT_ENOUGH_TRUST (trust-level shortfall on a non-adjacent
jump), or INVALID (bad header or expired trusted header) — and advance
the trusted state only on success.
"""

import base64
import glob
import json
import os

import pytest

from tendermint_tpu.light import verifier
from tendermint_tpu.light.verifier import (
    HeaderExpiredError,
    InvalidHeaderError,
    NewValSetCantBeTrustedError,
)
from tendermint_tpu.rpc.encoding import parse_rfc3339
from tendermint_tpu.crypto.keys import Ed25519PubKey
from tendermint_tpu.types import Validator, ValidatorSet
from tendermint_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    Consensus,
    Header,
    PartSetHeader,
)
from tendermint_tpu.types.light import SignedHeader

JSON_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mbt_json")

MAX_CLOCK_DRIFT = 1.0  # driver_test.go:57


def _b(hex_or_none):
    return bytes.fromhex(hex_or_none) if hex_or_none else b""


def _header(d) -> Header:
    return Header(
        version=Consensus(
            block=int(d["version"]["block"]), app=int(d["version"].get("app", 0))
        ),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time=parse_rfc3339(d["time"]),
        last_block_id=_block_id(d.get("last_block_id")),
        last_commit_hash=_b(d.get("last_commit_hash")),
        data_hash=_b(d.get("data_hash")),
        validators_hash=_b(d["validators_hash"]),
        next_validators_hash=_b(d["next_validators_hash"]),
        consensus_hash=_b(d.get("consensus_hash")),
        app_hash=_b(d.get("app_hash")),
        last_results_hash=_b(d.get("last_results_hash")),
        evidence_hash=_b(d.get("evidence_hash")),
        proposer_address=_b(d.get("proposer_address")),
    )


def _block_id(d) -> BlockID:
    if not d:
        return BlockID()
    parts = d.get("parts") or {}
    return BlockID(
        _b(d.get("hash")),
        PartSetHeader(int(parts.get("total", 0)), _b(parts.get("hash"))),
    )


def _commit(d) -> Commit:
    sigs = []
    for s in d["signatures"]:
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=_b(s.get("validator_address")),
                timestamp=parse_rfc3339(s["timestamp"])
                if s.get("timestamp")
                else parse_rfc3339("1970-01-01T00:00:00"),
                signature=base64.b64decode(s["signature"])
                if s.get("signature")
                else b"",
            )
        )
    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=_block_id(d.get("block_id")),
        signatures=sigs,
    )


def _valset(d) -> ValidatorSet:
    vals = []
    for v in d.get("validators") or []:
        pub = Ed25519PubKey(base64.b64decode(v["pub_key"]["value"]))
        vals.append(
            Validator(
                pub,
                int(v["voting_power"]),
                proposer_priority=int(v["proposer_priority"] or 0),
            )
        )
    vset = ValidatorSet()
    vset.validators = vals
    if vals:
        vset.get_proposer()
    return vset


def _signed_header(d) -> SignedHeader:
    return SignedHeader(header=_header(d["header"]), commit=_commit(d["commit"]))


def _trace_files():
    return sorted(glob.glob(os.path.join(JSON_DIR, "*.json")))


@pytest.mark.parametrize(
    "path", _trace_files(), ids=[os.path.basename(p) for p in _trace_files()]
)
def test_mbt_trace(path):
    with open(path) as fh:
        tc = json.load(fh)
    trusted_sh = _signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = _valset(tc["initial"]["next_validator_set"])
    trusting_period = int(tc["initial"]["trusting_period"]) / 1e9  # ns -> s

    for step, inp in enumerate(tc["input"]):
        new_sh = _signed_header(inp["block"]["signed_header"])
        new_vals = _valset(inp["block"]["validator_set"])
        now = parse_rfc3339(inp["now"])
        err = None
        try:
            verifier.verify(
                trusted_sh,
                trusted_next_vals,
                new_sh,
                new_vals,
                trusting_period,
                now,
                MAX_CLOCK_DRIFT,
            )
        except Exception as e:  # classified below
            err = e

        verdict = inp["verdict"]
        ctx = f"{os.path.basename(path)} step {step}"
        if verdict == "SUCCESS":
            assert err is None, f"{ctx}: expected SUCCESS, got {err!r}"
        elif verdict == "NOT_ENOUGH_TRUST":
            assert isinstance(err, NewValSetCantBeTrustedError), (
                f"{ctx}: expected NOT_ENOUGH_TRUST, got {err!r}"
            )
        elif verdict == "INVALID":
            assert isinstance(
                err, (InvalidHeaderError, HeaderExpiredError)
            ), f"{ctx}: expected INVALID, got {err!r}"
        else:
            pytest.fail(f"{ctx}: unknown verdict {verdict!r}")

        if err is None:  # advance, as the reference driver does
            trusted_sh = new_sh
            trusted_next_vals = _valset(inp["block"]["next_validator_set"])


def test_traces_present():
    assert len(_trace_files()) == 9


def test_expired_trust_root_rejected():
    """verifier.go:47/116: expiry gates on the TRUSTED header's age — an
    expired trust root must not anchor new updates (long-range-attack
    window). The MBT traces cannot distinguish this (their header times
    differ by seconds against a 1400s period), so pin it directly:
    trusted header at t=1s, 1400s period, now just past expiry -> reject,
    regardless of how fresh the new header is."""
    path = os.path.join(JSON_DIR, "MC4_4_faulty_TestSuccess.json")
    with open(path) as fh:
        tc = json.load(fh)
    trusted_sh = _signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = _valset(tc["initial"]["next_validator_set"])
    trusting_period = int(tc["initial"]["trusting_period"]) / 1e9
    inp = next(i for i in tc["input"] if i["verdict"] == "SUCCESS")
    new_sh = _signed_header(inp["block"]["signed_header"])
    new_vals = _valset(inp["block"]["validator_set"])
    expired_now = parse_rfc3339("1970-01-01T00:23:22Z")  # 1s past expiry
    with pytest.raises(HeaderExpiredError):
        verifier.verify(
            trusted_sh,
            trusted_next_vals,
            new_sh,
            new_vals,
            trusting_period,
            expired_now,
            MAX_CLOCK_DRIFT,
        )


def test_harness_not_vacuous():
    """Negative control: corrupting one commit signature in a SUCCESS
    step must flip the verdict — proving the traces actually exercise
    signature verification, not just error-shape matching."""
    path = os.path.join(JSON_DIR, "MC4_4_faulty_TestSuccess.json")
    with open(path) as fh:
        tc = json.load(fh)
    trusted_sh = _signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = _valset(tc["initial"]["next_validator_set"])
    trusting_period = int(tc["initial"]["trusting_period"]) / 1e9
    inp = next(i for i in tc["input"] if i["verdict"] == "SUCCESS")
    new_sh = _signed_header(inp["block"]["signed_header"])
    new_vals = _valset(inp["block"]["validator_set"])
    # flip one byte in the first real signature
    for cs in new_sh.commit.signatures:
        if cs.signature:
            cs.signature = bytes([cs.signature[0] ^ 1]) + cs.signature[1:]
            break
    with pytest.raises(Exception):
        verifier.verify(
            trusted_sh,
            trusted_next_vals,
            new_sh,
            new_vals,
            trusting_period,
            parse_rfc3339(inp["now"]),
            MAX_CLOCK_DRIFT,
        )
