"""Accumulate-with-deadline batch verification scheduler.

The latency/throughput duality (SURVEY §7 "Hard parts"): consensus votes
arrive one at a time and need ~100µs-class answers, while the device
verifier only pays off in batches. This scheduler is the seam between
them: concurrent callers submit single (pubkey, msg, sig) verifies and
block on a future; an accumulator thread flushes the pending set to ONE
batch verification when either

- the batch reaches ``max_batch`` entries (throughput bound), or
- the OLDEST pending entry has waited ``max_delay`` (latency bound) —
  the deadline is per-entry, so a lone vote is answered within
  ``max_delay`` even when nothing else arrives.

Per-entry verdicts come from the batch verifier's attribution (the
reference's BatchVerifier.Verify bool slice, crypto/crypto.go:58-76), so
one bad signature fails only its own future.

Continuous batching (the serving-tier analog of the ops engines' chunk
double-buffering): by default the accumulator does NOT run ``verify_fn``
itself. It hands selected batches to a small pool of dispatch workers
(``pipeline_depth`` of them) and immediately goes back to accumulating —
newly-arrived lanes are admitted into the NEXT device dispatch while the
current kernel is in flight, so host-side prep overlaps device work at
the service level and tail latency under mixed load stops being
quantized by super-batch boundaries. At most ``pipeline_depth`` batches
are outstanding (queued + in flight); past that the accumulator holds
lanes, which is the natural backpressure. ``TENDERMINT_TPU_CONT_BATCH=off``
(or ``continuous=False``) restores the historical flush-barrier path
where the accumulator verifies inline — kept for A/B benchmarking.

Deadline-aware dynamic batching (crypto/adaptive.py): with
``dyn_batch=True`` the accumulator resolves ``max_batch``/``max_delay``
through a :class:`~tendermint_tpu.crypto.adaptive.DynBatchController`
each iteration — a per-batch-bucket EWMA cost model fed from the flush
path grows the knobs while the marginal device cost is cheap relative
to the tightest in-flight ``flush_by`` slack and shrinks them when the
caller-observed queue wait (``note_queue_wait``) says queueing
dominates, with hard floors/ceilings and hysteresis on every step.
Bare schedulers default to static; verifyd resolves its default from
``TENDERMINT_TPU_DYN_BATCH`` (off = today's static behavior,
byte-identical flush boundaries).

Serving extensions (used by verifyd, available to any caller):

- per-entry ``priority`` — when more work is pending than one batch
  holds, the dequeue is priority-ordered (lower value first, FIFO
  within a class) so consensus lanes never queue behind rpc floods;
- per-entry ``flush_by`` — an absolute monotonic deadline that pulls
  the flush earlier than ``max_delay`` when a wire deadline would
  otherwise expire while the lane sits in the accumulator;
- per-entry ``tenant`` — opaque namespace label carried through to the
  ``on_flush`` observer so a multi-tenant front-end can attribute
  flush composition per tenant;
- ``max_pending`` backpressure — ``submit`` raises
  ``SchedulerSaturatedError`` past the cap instead of growing the
  queue unboundedly (callers surface this as RESOURCE_EXHAUSTED);
- ``flush_reasons`` counters (``size``/``deadline``/``shutdown``), an
  ``on_flush(reason, batch, seconds)`` callback invoked BEFORE the
  futures resolve, and an ``on_dispatch(depth, lanes, reason)``
  callback fired at hand-off time with the outstanding-dispatch depth
  (the continuous-batching occupancy signal).

Wiring: callers that ingest signatures from many concurrent sources
(per-peer vote floods, RPC broadcast storms) submit here instead of
calling ``pub_key.verify_signature`` inline; the single-threaded
consensus loop keeps its inline host verify, which is already
latency-optimal for one caller.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from tendermint_tpu.crypto.adaptive import DynBatchController
from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.sanitizer import instrument_attrs

DEFAULT_MAX_BATCH = 256
DEFAULT_MAX_DELAY = 0.002  # 2ms: well under a vote round-trip

# continuous-batching knob: "off"/"0"/"false"/"no" restores the
# flush-barrier path (accumulator verifies inline); anything else — and
# unset — runs the dispatch-worker pipeline.
CONT_BATCH_ENV = "TENDERMINT_TPU_CONT_BATCH"
DEFAULT_PIPELINE_DEPTH = 2  # batches outstanding: one in flight, one next


def continuous_default() -> bool:
    """Env-resolved default for the continuous dispatch pipeline."""
    val = os.environ.get(CONT_BATCH_ENV, "on").strip().lower()
    return val not in ("off", "0", "false", "no")


def default_max_batch() -> int:
    """Size-flush threshold scaled to the verify mesh: with the sharded
    engine spanning k devices, a super-batch k× the single-device
    default keeps every chip's slab at the same occupancy one chip saw
    before. Falls back to the single-device default when the mesh (or
    its discovery) is unavailable."""
    try:
        from tendermint_tpu.parallel import mesh

        return DEFAULT_MAX_BATCH * max(1, mesh.manager.device_count())
    except Exception:  # discovery trouble must not break scheduler setup
        return DEFAULT_MAX_BATCH


def resolved_default_knobs() -> dict:
    """What a scheduler built with default config resolves to *right
    now*: the mesh-aware batch default plus the env-resolved pipeline
    and dyn-batch states. The bench child stamps this into every
    section fragment so A/B artifacts record the config they ran
    under, not the static constants."""
    from tendermint_tpu.crypto.adaptive import dyn_batch_default

    return {
        "max_batch": default_max_batch(),
        "max_delay": DEFAULT_MAX_DELAY,
        "pipeline_depth": DEFAULT_PIPELINE_DEPTH,
        "continuous": continuous_default(),
        "dyn_batch": dyn_batch_default(),
    }


def _mesh_config_gen() -> Optional[int]:
    """The mesh manager's config generation, None when the mesh (or its
    import) is unavailable. The scheduler caches its mesh-aware
    ``max_batch`` default against this, so a ``configure()`` that lands
    AFTER the scheduler was built still takes effect at the next flush
    decision instead of baking the pre-configuration device count in
    forever (the stale-default bug pinned by tests/test_adaptive.py)."""
    try:
        from tendermint_tpu.parallel import mesh

        return mesh.manager.config_gen()
    except Exception:
        return None


class SchedulerSaturatedError(RuntimeError):
    """Pending queue is at ``max_pending``; shed load explicitly."""


@dataclass
class _Pending:
    pubkey: bytes
    msg: bytes
    sig: bytes
    submitted: float
    done: threading.Event = field(default_factory=threading.Event)
    ok: bool = False
    priority: int = 0  # lower flushes first when over-subscribed
    flush_by: Optional[float] = None  # absolute monotonic wire deadline
    tag: Optional[object] = None  # submitter identity (e.g. connection)
    tenant: Optional[str] = None  # namespace label (multi-tenant verifyd)
    # cross-process causality (ISSUE 15): the submitter's TraceContext;
    # the dispatch span links under it (first distinct ctx) and every
    # further distinct ctx gets a sched_trace_link instant — including
    # a waiter whose lane coalesced into another entry's slot.
    trace: Optional[tracing.TraceContext] = None
    # stage-attribution timestamps (monotonic), written by _flush_one:
    # batch residency = t_dispatch - submitted, device = t_done -
    # t_dispatch, collect = respond time - t_done (server-side).
    t_dispatch: float = 0.0
    t_done: float = 0.0

    def due(self, max_delay: float) -> float:
        """Absolute monotonic time this entry must be flushed by."""
        due = self.submitted + max_delay
        if self.flush_by is not None and self.flush_by < due:
            due = self.flush_by
        return due


@instrument_attrs
class VerifyScheduler:
    """Batches concurrent single-signature verifies onto one verifier call.

    ``verify_fn(pks, msgs, sigs) -> List[bool]`` is the flush target —
    ``ops.verify_batch`` on a device backend, or any host batch verifier.

    ``fallback_fn`` (optional, same signature) is tried when
    ``verify_fn`` raises — the seam that keeps the scheduler draining
    under device degradation instead of failing whole flushes closed.
    Without a fallback, a raising flush still fails closed.
    """

    def __init__(
        self,
        verify_fn: Callable[
            [Sequence[bytes], Sequence[bytes], Sequence[bytes]], List[bool]
        ],
        max_batch: Optional[int] = None,
        max_delay: float = DEFAULT_MAX_DELAY,
        fallback_fn: Optional[
            Callable[
                [Sequence[bytes], Sequence[bytes], Sequence[bytes]], List[bool]
            ]
        ] = None,
        max_pending: int = 0,
        on_flush: Optional[
            Callable[[str, List[_Pending], float], None]
        ] = None,
        continuous: Optional[bool] = None,
        pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
        on_dispatch: Optional[Callable[[int, int, str], None]] = None,
        dyn_batch: Optional[bool] = None,
        dyn_controller: Optional[DynBatchController] = None,
    ):
        self._verify_fn = verify_fn
        self._fallback_fn = fallback_fn
        # Lazy mesh-aware default: None resolves 256 lanes per device
        # the sharded engine can span, re-resolved whenever the mesh
        # config generation moves — a scheduler built before
        # MeshManager.configure() no longer bakes the pre-config device
        # count in. The cache rides its own lock because the resolver
        # runs both bare (stats callers) and under _mtx (the
        # accumulator); _knob_mtx nests strictly inside _mtx.
        self._knob_mtx = threading.Lock()
        self._mb_cache = DEFAULT_MAX_BATCH  # guarded-by: _knob_mtx
        self._mb_gen: Optional[int] = None  # guarded-by: _knob_mtx
        self.max_batch = max_batch
        self.max_delay = max_delay
        # None = static scheduler (the historical behavior, and what
        # every in-process caller gets); serving front-ends (verifyd)
        # opt in by passing adaptive.dyn_batch_default() so the
        # TENDERMINT_TPU_DYN_BATCH env knob governs the service. When
        # off, no controller exists at all — the flush boundaries are
        # byte-identical to the static path (pinned by
        # tests/test_adaptive.py).
        self.dyn_batch = False if dyn_batch is None else bool(dyn_batch)
        self._dyn: Optional[DynBatchController] = (
            (dyn_controller if dyn_controller is not None else DynBatchController())
            if self.dyn_batch
            else None
        )
        # 0 = unbounded (the historical in-process behavior); a serving
        # front-end sets a cap and maps SchedulerSaturatedError to an
        # explicit wire rejection.
        self.max_pending = max_pending
        self._on_flush = on_flush
        self._on_dispatch = on_dispatch
        # None = env default (on unless TENDERMINT_TPU_CONT_BATCH=off)
        self.continuous = (
            continuous_default() if continuous is None else bool(continuous)
        )
        self.pipeline_depth = max(1, pipeline_depth)
        self._pending: List[_Pending] = []  # guarded-by: _mtx
        self._mtx = threading.Lock()
        self._wake = threading.Condition(self._mtx)
        # the dispatch stage: the accumulator appends (reason, batch)
        # here and workers pop; bounded at pipeline_depth outstanding
        # (queued + in flight) so a slow device backs pressure up into
        # the accumulator instead of an unbounded hand-off queue.
        self._dispatch_q: List[Tuple[str, List[_Pending]]] = []  # guarded-by: _mtx
        self._dispatch_wake = threading.Condition(self._mtx)
        self._inflight = 0  # dispatches inside verify_fn  # guarded-by: _mtx
        self._inflight_lanes = 0  # lanes handed off, unresolved  # guarded-by: _mtx
        self._stop = False  # guarded-by: _mtx
        self._thread: Optional[threading.Thread] = None  # guarded-by: _mtx
        self._workers: List[threading.Thread] = []  # guarded-by: _mtx
        # observability — flush-side counters are written by every
        # dispatch worker (plus the accumulator on the barrier path and
        # stop()), so they all ride _mtx now.
        self.flushes = 0  # guarded-by: _mtx
        self.entries_verified = 0  # guarded-by: _mtx
        self.entries_coalesced = 0  # guarded-by: _mtx
        self.flush_errors = 0  # guarded-by: _mtx
        self.fallback_flushes = 0  # guarded-by: _mtx
        self.submit_rejections = 0  # guarded-by: _mtx
        self.dispatch_handoffs = 0  # guarded-by: _mtx
        self.inflight_admissions = 0  # lanes admitted mid-dispatch  # guarded-by: _mtx
        self.flush_reasons = {"size": 0, "deadline": 0, "shutdown": 0}  # guarded-by: _mtx

    # --- knob resolution -----------------------------------------------------

    @property
    def max_batch(self) -> int:
        """The static size-flush threshold. Explicit config wins;
        otherwise the mesh-aware default, cached against the mesh
        config generation so a post-construction ``configure()`` is
        picked up at the next read instead of never."""
        if self._max_batch_cfg is not None:
            return self._max_batch_cfg
        gen = _mesh_config_gen()
        if gen is None:  # mesh unavailable: single-device default
            return DEFAULT_MAX_BATCH
        with self._knob_mtx:
            if gen != self._mb_gen:
                self._mb_cache = default_max_batch()
                self._mb_gen = gen
            return self._mb_cache

    @max_batch.setter
    def max_batch(self, value: Optional[int]) -> None:
        self._max_batch_cfg = None if value is None else int(value)

    def _limits(self) -> Tuple[int, float]:
        """The knobs the accumulator actually runs with this iteration:
        the static config when dyn-batch is off (byte-identical to the
        historical path), the controller-scaled resolution otherwise."""
        mb, md = self.max_batch, self.max_delay
        if self._dyn is not None:
            return self._dyn.limits(mb, md)
        return mb, md

    def note_queue_wait(self, seconds: float) -> None:
        """Feed the adaptive controller a caller-observed queue wait
        (verifyd's wire_wait stage — the shrink signal). No-op when
        dyn-batch is off."""
        if self._dyn is not None:
            self._dyn.note_queue_wait(seconds)

    def resolved_knobs(self) -> dict:
        """The config actually under test right now — what stats(),
        the CLI banner, and every bench fragment record so A/B runs
        are attributable to real knob values, not the static ones."""
        mb, md = self._limits()
        out = {
            "max_batch": mb,
            "max_delay": md,
            "static_max_batch": self.max_batch,
            "static_max_delay": self.max_delay,
            "pipeline_depth": self.pipeline_depth,
            "continuous": self.continuous,
            "dyn_batch": self.dyn_batch,
        }
        if self._dyn is not None:
            out["dyn"] = self._dyn.snapshot()
        return out

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            if self._thread is not None:
                return
            self._stop = False
            # assign under the lock: a concurrent start() must see it
            self._thread = threading.Thread(
                target=self._run, name="verify-scheduler", daemon=True
            )
            self._thread.start()
            if self.continuous:
                for i in range(self.pipeline_depth):
                    w = threading.Thread(
                        target=self._dispatch_run,
                        name=f"verify-dispatch-{i}",
                        daemon=True,
                    )
                    w.start()
                    self._workers.append(w)

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
            self._dispatch_wake.notify_all()
            # snapshot under the lock (a concurrent start() may race us);
            # join OUTSIDE it — the accumulator needs _mtx to drain.
            thread, self._thread = self._thread, None
            workers, self._workers = list(self._workers), []
        if thread is not None:
            thread.join(timeout=5)
        for w in workers:
            w.join(timeout=5)
        # fail any stragglers closed rather than hanging their callers:
        # both the accumulator's pending set and batches stuck in the
        # hand-off queue (a worker that died mid-join keeps its popped
        # batch; it resolves those itself when the flush returns).
        with self._mtx:
            leftovers, self._pending = self._pending, []
            for _reason, batch in self._dispatch_q:
                leftovers.extend(batch)
            self._dispatch_q = []
            if leftovers:
                self.flush_reasons["shutdown"] += 1
        if leftovers:
            self._notify_flush("shutdown", leftovers, 0.0)
        for p in leftovers:
            p.ok = False
            p.done.set()

    # --- submission ----------------------------------------------------------

    def submit(
        self,
        pubkey: bytes,
        msg: bytes,
        sig: bytes,
        *,
        priority: int = 0,
        flush_by: Optional[float] = None,
        tag: Optional[object] = None,
        tenant: Optional[str] = None,
        trace: Optional[tracing.TraceContext] = None,
    ) -> _Pending:
        """Enqueue one signature; returns a handle for ``wait``. Callers
        with several signatures submit all first so one flush covers
        them, instead of paying the deadline once per signature."""
        if trace is None:
            trace = tracing.current_context()
        entry = _Pending(
            pubkey,
            msg,
            sig,
            time.monotonic(),
            priority=priority,
            flush_by=flush_by,
            tag=tag,
            tenant=tenant,
            trace=trace,
        )
        with self._wake:
            if self._stop or self._thread is None:
                raise RuntimeError("scheduler not running")
            if self.max_pending and len(self._pending) >= self.max_pending:
                self.submit_rejections += 1
                raise SchedulerSaturatedError(
                    f"verify queue full ({self.max_pending} pending)"
                )
            self._pending.append(entry)
            inflight = self._inflight
            if inflight:
                self.inflight_admissions += 1
            self._wake.notify_all()
        if inflight:
            # the continuous-batching proof point: this lane joined the
            # NEXT dispatch while a kernel was already in flight
            tracing.instant(
                "scheduler_admit_inflight", lanes=1, inflight=inflight
            )
        return entry

    def submit_many(
        self,
        lanes: Sequence[Tuple[bytes, bytes, bytes]],
        *,
        priority: int = 0,
        flush_by: Optional[float] = None,
        tag: Optional[object] = None,
        tenant: Optional[str] = None,
        trace: Optional[tracing.TraceContext] = None,
    ) -> List[_Pending]:
        """Atomically enqueue a whole lane group under ONE lock round and
        ONE accumulator wake-up. This is the super-batch entry point for
        callers that assemble many signatures at once (the light client's
        bisection ladder): all-or-nothing against ``max_pending``, so a
        half-admitted group can never split across two flushes on the
        admission boundary. Pair with ``flush_by=time.monotonic()`` to
        pull the flush immediately and spend exactly one device call on
        the group."""
        now = time.monotonic()
        if trace is None:
            trace = tracing.current_context()
        entries = [
            _Pending(pk, msg, sig, now, priority=priority,
                     flush_by=flush_by, tag=tag, tenant=tenant, trace=trace)
            for pk, msg, sig in lanes
        ]
        with self._wake:
            if self._stop or self._thread is None:
                raise RuntimeError("scheduler not running")
            if self.max_pending and (
                len(self._pending) + len(entries) > self.max_pending
            ):
                self.submit_rejections += 1
                raise SchedulerSaturatedError(
                    f"verify queue full ({self.max_pending} pending)"
                )
            self._pending.extend(entries)
            inflight = self._inflight
            if inflight:
                self.inflight_admissions += len(entries)
            self._wake.notify_all()
        if inflight and entries:
            tracing.instant(
                "scheduler_admit_inflight",
                lanes=len(entries),
                inflight=inflight,
            )
        return entries

    def wait_many(
        self, entries: Sequence[_Pending], timeout: float = 10.0
    ) -> List[bool]:
        """Block until every entry's batch flushed; per-entry verdicts,
        fail-closed on timeout (same contract as ``wait``). The deadline
        is shared across the group, not per entry."""
        deadline = time.monotonic() + timeout
        out: List[bool] = []
        for e in entries:
            left = deadline - time.monotonic()
            if left <= 0 or not e.done.wait(timeout=left):
                out.append(False)
            else:
                out.append(e.ok)
        return out

    def pending_depth(self) -> int:
        """Entries accumulated but not yet handed to a flush."""
        with self._mtx:
            return len(self._pending)

    def load_depth(self) -> int:
        """Total unresolved lanes: accumulated + handed off + in flight.
        The admission-control signal — on the continuous path lanes
        leave ``pending_depth`` the moment a dispatch slot frees, but
        they still consume service time until their flush returns."""
        with self._mtx:
            return len(self._pending) + self._inflight_lanes

    def dispatch_depth(self) -> int:
        """Outstanding dispatches (queued + inside verify_fn)."""
        with self._mtx:
            return self._inflight + len(self._dispatch_q)

    def stats(self) -> dict:
        """Locked snapshot of the observability counters. Monitors and
        tests must read through this, not the raw attributes — every
        counter is written under ``_mtx`` by the dispatch workers, so an
        unlocked read races the hand-off path (tpusan flags it)."""
        with self._mtx:
            return {
                "flushes": self.flushes,
                "entries_verified": self.entries_verified,
                "entries_coalesced": self.entries_coalesced,
                "flush_errors": self.flush_errors,
                "fallback_flushes": self.fallback_flushes,
                "submit_rejections": self.submit_rejections,
                "dispatch_handoffs": self.dispatch_handoffs,
                "inflight_admissions": self.inflight_admissions,
                "flush_reasons": dict(self.flush_reasons),
            }

    def wait(self, entry: _Pending, timeout: float = 10.0) -> bool:
        """Block until the entry's batch flushed; False on timeout (fail
        closed: an unverified signature is an invalid signature)."""
        if not entry.done.wait(timeout=timeout):
            return False
        return entry.ok

    def verify(
        self, pubkey: bytes, msg: bytes, sig: bytes, timeout: float = 10.0
    ) -> bool:
        """Submit one signature and block until its batch flushes."""
        return self.wait(self.submit(pubkey, msg, sig), timeout=timeout)

    # --- accumulator ---------------------------------------------------------

    def _notify_flush(
        self, reason: str, batch: List[_Pending], seconds: float
    ) -> None:
        if self._on_flush is None:
            return
        try:
            self._on_flush(reason, batch, seconds)
        except Exception:
            pass  # observers never break the drain loop

    def _notify_dispatch(self, depth: int, lanes: int, reason: str) -> None:
        if self._on_dispatch is None:
            return
        try:
            self._on_dispatch(depth, lanes, reason)
        except Exception:
            pass  # observers never break the dispatch loop

    def _run(self) -> None:
        while True:
            reason = "size"
            with self._wake:
                # resolved once per wake-up: with dyn-batch on the
                # controller's latest scale applies to the very next
                # flush decision; off, these ARE the static attributes.
                limit, delay = self._limits()
                while not self._stop:
                    if self.continuous and (
                        self._inflight + len(self._dispatch_q)
                        >= self.pipeline_depth
                    ):
                        # every dispatch slot is taken: keep accumulating
                        # (that IS the backpressure); a slot release
                        # notifies _dispatch_wake and we re-evaluate
                        self._dispatch_wake.wait(timeout=0.05)
                        limit, delay = self._limits()
                        continue
                    if len(self._pending) >= limit:
                        reason = "size"
                        break
                    if self._pending:
                        # earliest obligation across max_delay AND any
                        # per-entry wire deadline (flush_by)
                        due = min(p.due(delay) for p in self._pending)
                        wait = due - time.monotonic()
                        if wait <= 0:
                            reason = "deadline"
                            break
                        self._wake.wait(timeout=wait)
                    else:
                        self._wake.wait(timeout=0.1)
                    limit, delay = self._limits()
                if self._stop:
                    return
                if len(self._pending) > limit:
                    # over-subscribed: highest-priority (lowest value)
                    # lanes flush first, FIFO within a class
                    order = sorted(
                        self._pending,
                        key=lambda p: (p.priority, p.submitted),
                    )
                    batch = order[:limit]
                    taken = {id(p) for p in batch}
                    self._pending = [
                        p for p in self._pending if id(p) not in taken
                    ]
                else:
                    batch, self._pending = self._pending, []
                if batch and self.continuous:
                    # hand off and go straight back to accumulating:
                    # lanes arriving now join the NEXT dispatch while
                    # this one runs (continuous batching)
                    self._dispatch_q.append((reason, batch))
                    self._inflight_lanes += len(batch)
                    self.dispatch_handoffs += 1
                    depth = self._inflight + len(self._dispatch_q)
                    self._dispatch_wake.notify_all()
            if not batch:
                continue
            if self.continuous:
                self._notify_dispatch(depth, len(batch), reason)
            else:
                # barrier path (A/B baseline): verify inline, blocking
                # accumulation until the kernel returns
                self._notify_dispatch(1, len(batch), reason)
                self._flush_one(reason, batch, depth=1)

    # --- dispatch workers ----------------------------------------------------

    def _dispatch_run(self) -> None:
        while True:
            with self._mtx:
                while not self._stop and not self._dispatch_q:
                    self._dispatch_wake.wait(timeout=0.1)
                if self._stop:
                    return
                reason, batch = self._dispatch_q.pop(0)
                self._inflight += 1
                depth = self._inflight + len(self._dispatch_q)
            try:
                self._flush_one(reason, batch, depth)
            finally:
                with self._mtx:
                    self._inflight -= 1
                    self._inflight_lanes -= len(batch)
                    # a freed slot is what the accumulator (and any
                    # other worker) waits on
                    self._dispatch_wake.notify_all()

    # --- flush ---------------------------------------------------------------

    def _flush_one(
        self, reason: str, batch: List[_Pending], depth: int
    ) -> None:
        # Coalesce duplicate (pubkey, msg, sig) submissions: a vote
        # gossiped by k peers lands k times inside one deadline
        # window but costs one verifier lane; the verdict fans out
        # to every waiting future.
        pks: List[bytes] = []
        msgs: List[bytes] = []
        sigs: List[bytes] = []
        index: dict = {}
        slots: List[int] = []
        had_error = used_fallback = False
        # Distinct submitter trace contexts in batch order.  The first
        # becomes the dispatch span's remote parent; every other distinct
        # context — including a waiter whose lane coalesces into another
        # entry's slot — is linked via a sched_trace_link instant so the
        # merged fleet timeline still reaches its client span.
        t_dispatch = time.monotonic()
        traces: List[tracing.TraceContext] = []
        seen_tids: set = set()
        for p in batch:
            p.t_dispatch = t_dispatch
            ctx = p.trace
            if ctx is not None and ctx.trace_id not in seen_tids:
                seen_tids.add(ctx.trace_id)
                traces.append(ctx)
        with tracing.span(
            "scheduler_dispatch",
            parent_ctx=traces[0] if traces else None,
            lanes=len(batch),
            reason=reason,
            depth=depth,
        ):
            for ctx in traces[1:16]:
                tracing.instant(
                    "sched_trace_link",
                    link_trace_id=ctx.trace_id,
                    link_span_id=ctx.span_id,
                )
            with tracing.span("sched_assemble", lanes=len(batch)) as asp:
                for p in batch:
                    # Zero-copy ingress (verifyd/shm.py) submits lanes as
                    # memoryviews into a client-owned slab; they stay
                    # views while queued (no copy on the ingest path) and
                    # materialise exactly once here, where coalescing
                    # needs hashable keys and the verify backends expect
                    # bytes. After this point the slab may be reused.
                    if type(p.msg) is memoryview:
                        p.msg = p.msg.tobytes()
                    if type(p.pubkey) is memoryview:
                        p.pubkey = p.pubkey.tobytes()
                    if type(p.sig) is memoryview:
                        p.sig = p.sig.tobytes()
                    key = (p.pubkey, p.msg, p.sig)
                    idx = index.get(key)
                    if idx is None:
                        idx = index[key] = len(pks)
                        pks.append(p.pubkey)
                        msgs.append(p.msg)
                        sigs.append(p.sig)
                    slots.append(idx)
                asp.set(unique=len(pks), coalesced=len(batch) - len(pks))
            t0 = time.monotonic()
            with tracing.span("sched_flush", lanes=len(pks), reason=reason):
                try:
                    oks = self._verify_fn(pks, msgs, sigs)
                except Exception:
                    had_error = True
                    oks = None
                    if self._fallback_fn is not None:
                        try:
                            oks = self._fallback_fn(pks, msgs, sigs)
                            used_fallback = True
                        except Exception:
                            oks = None
                    if oks is None:
                        # fail closed, never hang callers
                        oks = [False] * len(pks)
        if len(oks) != len(pks):  # misbehaving verifier: fail closed
            oks = [False] * len(pks)
        dev_s = time.monotonic() - t0
        if self._dyn is not None and batch:
            # the controller's flush feed (same site the on_flush
            # observer fires from): batch residency = dispatch minus
            # oldest submit, slack = tightest wire-deadline headroom
            # still unspent at dispatch (None when no lane carried one)
            residency = max(
                0.0, t_dispatch - min(p.submitted for p in batch)
            )
            slack: Optional[float] = None
            for p in batch:
                if p.flush_by is not None:
                    s = p.flush_by - t_dispatch
                    slack = s if slack is None else min(slack, s)
            self._dyn.observe_flush(
                len(batch), residency, dev_s, slack, self.max_delay
            )
        with self._mtx:
            self.flushes += 1
            self.flush_reasons[reason] += 1
            self.entries_verified += len(batch)
            self.entries_coalesced += len(batch) - len(pks)
            if had_error:
                self.flush_errors += 1
            if used_fallback:
                self.fallback_flushes += 1
        # observers run strictly-before the futures resolve, so a
        # waiter that wakes can already see its flush accounted for
        self._notify_flush(reason, batch, time.monotonic() - t0)
        t_done = time.monotonic()
        for p, idx in zip(batch, slots):
            p.ok = bool(oks[idx])
            p.t_done = t_done
            p.done.set()
