"""JSON-RPC 2.0 server over HTTP.

The reference serves ~35 routes over HTTP POST (JSON-RPC envelope), GET
(URI params), and websocket (rpc/jsonrpc/server/). This server covers
the POST/GET surface and replaces the websocket stream with the
reference's own newer alternative: the ``/events`` long-poll endpoint
backed by the sliding-window eventlog (internal/eventlog/eventlog.go:25,
internal/rpc/core/events.go:103) — same data, no custom framing
protocol.

Serving modes: the default multiplexes every connection on one
selector event loop (libs/evloop) with a bounded worker pool for the
route handlers, so the light-client serving tier can hold 10k+ idle
keep-alive sockets without 10k threads. ``TENDERMINT_TPU_EVLOOP=off``
(or ``evloop=False``) restores the historical ``ThreadingHTTPServer``.
Both modes answer through the same dispatch/encoding core, so the HTTP
surface is identical. Websocket upgrades detach from the loop onto a
dedicated thread (long-lived, rarely-used sessions — the same trade
the reference makes for its ws handlers).

Handlers come from an rpc.core.Environment-bound route table; params
arrive as JSON object/array (POST) or query strings (GET).
"""

from __future__ import annotations

import json
import socket
import threading
import traceback
from email.utils import formatdate
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qsl, urlparse


class RPCError(Exception):
    """JSON-RPC error with code (rpc/jsonrpc/types/types.go)."""

    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603

# sentinel returned by _get_response for GET /websocket: the driver owns
# the upgrade (it needs the raw connection, not a body)
_WS_UPGRADE = object()

_STATUS_PHRASES = {
    200: "OK",
    400: "Bad Request",
    501: "Not Implemented",
}
_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 64 << 20


class _HttpProtocol:
    """libs/evloop connection state machine for the HTTP/1.1 surface.

    The loop thread feeds raw bytes; a minimal parser assembles one
    request at a time (requests on one connection are served in order —
    same as the per-connection handler thread it replaces) and defers
    the route handler to the worker pool, which queues the response
    through the transport. Keep-alive is the HTTP/1.1 default;
    ``Connection: close`` and HTTP/1.0 behave as usual."""

    def __init__(self, server: "RPCServer", transport):
        self._server = server
        self._t = transport
        self._mtx = threading.Lock()
        self._buf = bytearray()  # guarded-by: _mtx
        self._busy = False  # a request is in flight  # guarded-by: _mtx
        self._detached = False  # guarded-by: _mtx

    # --- loop-thread callbacks ----------------------------------------------

    def data_received(self, data: bytes) -> None:
        with self._mtx:
            if self._detached:
                return
            self._buf += data
        self._pump()

    def eof_received(self) -> None:
        pass  # loop drops the connection after this

    def connection_lost(self, exc) -> None:
        pass

    # --- request assembly ----------------------------------------------------

    def _pump(self) -> None:
        with self._mtx:
            if self._busy or self._detached:
                return
            req = self._parse_locked()
            if req is None:
                return
            self._busy = True
        self._t.defer(lambda: self._run(req))

    def _parse_locked(self):
        idx = self._buf.find(b"\r\n\r\n")
        if idx < 0:
            if len(self._buf) > _MAX_HEADER_BYTES:
                raise ValueError("HTTP header block too large")
            return None
        head = bytes(self._buf[:idx]).decode("latin-1")
        lines = head.split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3:
            raise ValueError("malformed HTTP request line")
        method, target, version = parts
        headers: Dict[str, str] = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            blen = int(headers.get("content-length", "0") or "0")
        except ValueError:
            raise ValueError("malformed Content-Length")
        if blen > _MAX_BODY_BYTES:
            raise ValueError("HTTP body too large")
        total = idx + 4 + blen
        if len(self._buf) < total:
            return None
        body = bytes(self._buf[idx + 4 : total])
        del self._buf[:total]
        return (method, target, version, headers, body)

    # --- worker-side handling -------------------------------------------------

    def _run(self, req) -> None:
        method, target, version, headers, body = req
        try:
            conn_hdr = headers.get("connection", "").lower()
            close = "close" in conn_hdr or (
                version == "HTTP/1.0" and "keep-alive" not in conn_hdr
            )
            if method == "POST":
                status, ctype, out = 200, "application/json", (
                    self._server._post_body(body)
                )
            elif method == "GET":
                got = self._server._get_response(target)
                if got is _WS_UPGRADE:
                    self._upgrade(headers)
                    return
                status, ctype, out = got
            else:
                status, ctype, out = (
                    501, "application/json",
                    b'{"error": "unsupported method"}',
                )
            self._t.write(_http_head(status, ctype, len(out), close) + out)
            if close:
                self._t.close()
                return
        except Exception:
            # handler-layer failure with the response half-planned:
            # drop the connection, never a half-written payload
            self._t.abort()
            return
        with self._mtx:
            self._busy = False
        self._pump()  # serve the next pipelined request, if buffered

    def _upgrade(self, headers: Dict[str, str]) -> None:
        from tendermint_tpu.rpc import websocket as ws

        shaped = {
            "Upgrade": headers.get("upgrade", ""),
            "Connection": headers.get("connection", ""),
            "Sec-WebSocket-Key": headers.get("sec-websocket-key"),
        }
        if not ws.is_upgrade_request(shaped):
            out = b'{"error": "websocket upgrade required"}'
            self._t.write(
                _http_head(400, "application/json", len(out), True) + out
            )
            self._t.close()
            return
        with self._mtx:
            self._detached = True
        sock = self._t.detach()  # loop hands the raw socket over
        server = self._server

        def session():
            try:
                sock.sendall(
                    b"HTTP/1.1 101 Switching Protocols\r\n"
                    b"Upgrade: websocket\r\n"
                    b"Connection: Upgrade\r\n"
                    b"Sec-WebSocket-Accept: "
                    + ws.accept_key(shaped["Sec-WebSocket-Key"]).encode()
                    + b"\r\n\r\n"
                )
                rfile = sock.makefile("rb")
                wfile = sock.makefile("wb")
                conn = ws.WSConn(rfile, wfile)
                ws.WSSession(conn, server.routes, server.event_bus).run()
            except OSError:
                pass  # peer vanished mid-session
            finally:
                try:
                    sock.close()
                except OSError:
                    pass  # best-effort close; session is over regardless

        # dedicated thread, not a pool worker: sessions live for the
        # client's lifetime and would pin the bounded pool
        threading.Thread(
            target=session, name="rpc-ws-session", daemon=True
        ).start()


def _http_head(status: int, ctype: str, length: int, close: bool) -> bytes:
    phrase = _STATUS_PHRASES.get(status, "OK")
    head = (
        f"HTTP/1.1 {status} {phrase}\r\n"
        f"Server: {BaseHTTPRequestHandler.server_version}\r\n"
        f"Date: {formatdate(usegmt=True)}\r\n"
        f"Content-Type: {ctype}\r\n"
        f"Content-Length: {length}\r\n"
    )
    if close:
        head += "Connection: close\r\n"
    return (head + "\r\n").encode("latin-1")


class RPCServer:
    """HTTP JSON-RPC server bound to a route table."""

    def __init__(
        self,
        routes: Dict[str, Callable],
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_registry=None,
        event_bus=None,
        evloop: Optional[bool] = None,
        evloop_metrics=None,
        workers: Optional[int] = None,
    ):
        self.routes = routes
        # Prometheus text exposition at GET /metrics (the reference serves
        # this on a dedicated instrumentation port, node/node.go:575-605;
        # here the RPC listener is the one operator-facing HTTP surface).
        self.metrics_registry = metrics_registry
        # event bus backing websocket subscribe/unsubscribe (routes.go:31-34)
        self.event_bus = event_bus
        from tendermint_tpu.libs.grpc import evloop_enabled

        self._evloop_enabled = evloop_enabled() if evloop is None else evloop
        self._evloop_metrics = evloop_metrics
        self._workers = workers
        self._ev = None
        self._lsock: Optional[socket.socket] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        if self._evloop_enabled:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, port))
            s.listen(128)
            self._lsock = s
            return
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                # same bound the evloop transport enforces: a declared
                # Content-Length is peer data, not an allocation size
                if length > _MAX_BODY_BYTES:
                    self._send(413, b'{"error": "request body too large"}')
                    return
                body = self.rfile.read(length) if length else b""
                self._send(200, server._post_body(body))

            def do_GET(self):
                got = server._get_response(self.path)
                if got is _WS_UPGRADE:
                    from tendermint_tpu.rpc import websocket as ws

                    if not ws.is_upgrade_request(self.headers):
                        self._send(
                            400, b'{"error": "websocket upgrade required"}'
                        )
                        return
                    key = self.headers["Sec-WebSocket-Key"]
                    self.send_response_only(101)
                    self.send_header("Upgrade", "websocket")
                    self.send_header("Connection", "Upgrade")
                    self.send_header(
                        "Sec-WebSocket-Accept", ws.accept_key(key)
                    )
                    self.end_headers()
                    conn = ws.WSConn(self.rfile, self.wfile)
                    ws.WSSession(
                        conn, server.routes, server.event_bus
                    ).run()
                    self.close_connection = True
                    return
                status, ctype, body = got
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client hung up mid-response; nothing to answer

            def _send(self, status: int, body: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client hung up mid-response; nothing to answer

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True

    @property
    def address(self) -> Tuple[str, int]:
        if self._httpd is not None:
            return self._httpd.server_address[:2]
        assert self._lsock is not None
        return self._lsock.getsockname()[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> None:
        if self._evloop_enabled:
            from tendermint_tpu.libs.evloop import EvloopServer

            kwargs = {}
            if self._evloop_metrics is not None:
                kwargs["metrics"] = self._evloop_metrics
            if self._workers is not None:
                kwargs["workers"] = self._workers
            self._ev = EvloopServer(
                lambda t: _HttpProtocol(self, t),
                listener_ref=lambda: self._lsock,
                name="rpc",
                **kwargs,
            )
            self._ev.start()
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="rpc-server"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._ev is not None:
            self._ev.stop()
            self._ev = None
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass  # listener may already be closed; stop() is idempotent
            self._lsock = None
        if self._httpd is not None:
            # shutdown() blocks forever unless serve_forever is running
            # (BaseServer.__is_shut_down is only set by the serve loop), so
            # a never-started server gets only server_close().
            if self._thread is not None:
                self._httpd.shutdown()
            self._httpd.server_close()
            if self._thread is not None:
                self._thread.join(timeout=2)

    # -- shared request core ---------------------------------------------------

    def _post_body(self, body: bytes) -> bytes:
        """POST surface: JSON-RPC envelope (single or batch) -> response
        body bytes. Always HTTP 200 + application/json."""
        try:
            req = json.loads(body or b"{}")
        except (json.JSONDecodeError, UnicodeDecodeError):
            return json.dumps(
                _error_envelope(PARSE_ERROR, "parse error")
            ).encode()
        if isinstance(req, list):
            if not req:
                # JSON-RPC 2.0: empty batch is a single invalid request
                # error, not an empty array
                return json.dumps(
                    _error_envelope(INVALID_REQUEST, "empty batch")
                ).encode()
            return json.dumps([self._dispatch(r) for r in req]).encode()
        return json.dumps(self._dispatch(req)).encode()

    def _get_response(self, target: str):
        """GET surface: target (path?query) -> (status, content-type,
        body) — or the ``_WS_UPGRADE`` sentinel, which the calling
        driver turns into a connection upgrade."""
        parsed = urlparse(target)
        method = parsed.path.strip("/")
        if method == "websocket":
            return _WS_UPGRADE
        if method == "":
            return 200, "application/json", self._index().encode()
        if method == "debug/traces":
            # Chrome-trace JSON export of the global span tracer; bounded
            # by the tracer's ring capacity. ?limit=N caps the event
            # count, ?clear=1 drains the ring after read, ?format=chrome
            # yields the bare trace_events shape trace_merge consumes.
            # The tracer lock is held only for the ring snapshot; JSON
            # serialization streams in bounded chunks OUTSIDE it, so a
            # big ring can't stall every traced hot path mid-dump.
            from tendermint_tpu.libs import tracing

            q = dict(parse_qsl(parsed.query))
            try:
                limit = int(q["limit"]) if "limit" in q else None
            except ValueError:
                limit = None
            clear = q.get("clear") in ("1", "true")
            fmt = "chrome" if q.get("format") == "chrome" else "full"
            body = b"".join(
                tracing.tracer.export_chunks(
                    limit=limit, clear=clear, fmt=fmt
                )
            )
            return 200, "application/json", body
        if method == "debug/memstats":
            # Device-tier snapshot (ops/introspect.py): resident-table /
            # slab-ring bytes by owner, compile events, exec-cache
            # entries, and the rolling kernel-profile digests.
            from tendermint_tpu.ops import introspect

            return 200, "application/json", introspect.memstats_json().encode()
        if method == "metrics" and self.metrics_registry is not None:
            return (
                200,
                "text/plain; version=0.0.4",
                self.metrics_registry.expose().encode(),
            )
        params: Dict[str, Any] = {}
        for k, v in parse_qsl(parsed.query):
            # heuristics matching the reference's URI param decoding:
            # quoted strings, 0x-hex, numbers, bools
            if v.startswith('"') and v.endswith('"') and len(v) >= 2:
                params[k] = v[1:-1]
            elif v in ("true", "false"):
                params[k] = v == "true"
            else:
                try:
                    params[k] = int(v)
                except ValueError:
                    params[k] = v
        req = {"jsonrpc": "2.0", "id": -1, "method": method, "params": params}
        return 200, "application/json", json.dumps(self._dispatch(req)).encode()

    # -- dispatch -------------------------------------------------------------

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if not isinstance(req, dict):
            # JSON-RPC: a request must be an object; a valid-JSON scalar
            # or string body is an invalid request, not a server error
            return _error_envelope(
                INVALID_REQUEST, "request must be a JSON object"
            )
        id_ = req.get("id")
        resp: Dict[str, Any] = {"jsonrpc": "2.0", "id": id_}
        method = req.get("method")
        fn = self.routes.get(method or "")
        if fn is None:
            resp["error"] = {
                "code": METHOD_NOT_FOUND,
                "message": f"method not found: {method}",
            }
            return resp
        params = req.get("params") or {}
        # optional cross-process trace context: a caller that is itself
        # traced (lightd, bench drivers) adds a top-level "trace" member
        # ("<trace_id>-<span_id>-<flags>"); every span this handler
        # opens then links under the caller's span in the merged fleet
        # timeline. Absent/malformed members change nothing.
        from tendermint_tpu.libs import tracing

        raw_trace = req.get("trace")
        ctx = (
            tracing.TraceContext.from_header(raw_trace)
            if isinstance(raw_trace, str)
            else None
        )
        try:
            with tracing.attach(ctx):
                if ctx is not None:
                    with tracing.span("rpc_dispatch", method=method or ""):
                        result = _invoke(fn, params)
                else:
                    result = _invoke(fn, params)
            resp["result"] = result
        except RPCError as e:
            resp["error"] = {"code": e.code, "message": e.message, "data": e.data}
        except TypeError as e:
            resp["error"] = {"code": INVALID_PARAMS, "message": str(e)}
        except Exception as e:  # internal
            resp["error"] = {
                "code": INTERNAL_ERROR,
                "message": str(e),
                "data": traceback.format_exc(limit=5),
            }
        return resp

    def _index(self) -> str:
        lines = ["Available endpoints:"]
        lines += sorted(f"  /{name}" for name in self.routes)
        return "\n".join(lines)


def _invoke(fn: Callable, params: Any) -> Any:
    if isinstance(params, dict):
        return fn(**params)
    if isinstance(params, list):
        return fn(*params)
    raise RPCError(INVALID_PARAMS, "params must be object or array")


def _error_envelope(code: int, message: str, data: str = "") -> Dict[str, Any]:
    return {
        "jsonrpc": "2.0",
        "id": None,
        "error": {"code": code, "message": message, "data": data},
    }
