"""Oracle self-tests: pure-Python ZIP-215 ed25519 vs the cryptography lib."""

import os

import pytest

from tendermint_tpu.crypto import ed25519_ref as ref


def test_sign_verify_roundtrip():
    priv, pub = ref.keypair_from_seed(b"\x01" * 32)
    msg = b"hello tendermint tpu"
    sig = ref.sign(priv, msg)
    assert ref.verify_zip215_slow(pub, msg, sig)
    assert ref.verify_zip215(pub, msg, sig)


def test_reject_bad_sig():
    priv, pub = ref.keypair_from_seed(b"\x02" * 32)
    sig = bytearray(ref.sign(priv, b"msg"))
    sig[0] ^= 1
    assert not ref.verify_zip215_slow(pub, b"msg", bytes(sig))
    assert not ref.verify_zip215(pub, b"msg", bytes(sig))
    good = ref.sign(priv, b"msg")
    assert not ref.verify_zip215(pub, b"other msg", good)


def test_matches_cryptography_lib_signing():
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
    from cryptography.hazmat.primitives import serialization

    for i in range(8):
        seed = os.urandom(32)
        lib_priv = Ed25519PrivateKey.from_private_bytes(seed)
        lib_pub = lib_priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        priv, pub = ref.keypair_from_seed(seed)
        assert pub == lib_pub
        msg = os.urandom(40)
        assert ref.sign(priv, msg) == lib_priv.sign(msg)


def test_reject_noncanonical_s():
    priv, pub = ref.keypair_from_seed(b"\x03" * 32)
    sig = ref.sign(priv, b"m")
    s = int.from_bytes(sig[32:], "little")
    bad = sig[:32] + int.to_bytes(s + ref.L, 32, "little")
    assert not ref.verify_zip215_slow(pub, b"m", bad)


def test_accepts_noncanonical_encodings():
    # Non-canonical encodings (y >= p) only exist for y in [p, 2^255), i.e.
    # points whose canonical y is 0..18. y=0 (x=sqrt(-1), small order) and
    # y=1 (identity) are both on-curve; their y+p encodings must decompress
    # liberally to the same point and be rejected canonically.
    for y in (0, 1):
        canon = int.to_bytes(y, 32, "little")
        noncanon = int.to_bytes(y + ref.P, 32, "little")
        pt_c = ref.pt_decompress_liberal(canon)
        pt_nc = ref.pt_decompress_liberal(noncanon)
        assert pt_c is not None and pt_nc is not None
        assert ref.pt_equal(pt_c, pt_nc)
        assert ref.pt_decompress_canonical(noncanon) is None
        assert ref.pt_decompress_canonical(canon) is not None


def test_small_order_points_accepted_zip215():
    # The all-zero pubkey encodes the point (0, 0)? y=0: x^2 = (0-1)/(0+1) = -1,
    # x = sqrt(-1) exists => on-curve small-order point. ZIP-215 accepts it as
    # a key; signatures verify against the cofactored equation.
    small = int.to_bytes(0, 32, "little")
    assert ref.pt_decompress_liberal(small) is not None
    # identity encoding y=1
    ident = int.to_bytes(1, 32, "little")
    pt = ref.pt_decompress_liberal(ident)
    assert pt is not None and ref.pt_is_identity(pt)
    # With A = identity, any s < L with R = [s]B and k arbitrary verifies:
    s = 12345
    r_bytes = ref.pt_compress(ref.pt_mul(s, ref.B_POINT))
    sig = r_bytes + int.to_bytes(s, 32, "little")
    assert ref.verify_zip215_slow(ident, b"anything", sig)


def test_point_arith_consistency():
    pt = ref.pt_mul(7, ref.B_POINT)
    lhs = ref.pt_add(pt, pt)
    rhs = ref.pt_double(pt)
    assert ref.pt_equal(lhs, rhs)
    assert ref.pt_equal(ref.pt_mul(8 + 5, ref.B_POINT),
                        ref.pt_add(ref.pt_mul(8, ref.B_POINT), ref.pt_mul(5, ref.B_POINT)))
    assert ref.pt_is_identity(ref.pt_mul(ref.L, ref.B_POINT))
