"""tpuflow (TPT): interprocedural taint analysis for untrusted wire input.

Every past wire-parsing bug in this repo was the same shape: an
*untrusted decoded integer reached a dangerous sink without a bounds
guard* — the garbage lane count that could run ``struct.unpack_from``
off the segment, the advert-spoof verdict-forgery vector, the slab
bookkeeping corruption. The five existing checker families are
syntactic; none of them track dataflow, so none of them can see that
class. This checker does.

Taint SOURCES are the repo's decode surfaces (``SURFACE_SUFFIXES``):
the varint/field readers of ``encoding/proto.py`` used by
``verifyd/protocol.py``, ``struct.unpack``/``unpack_from`` and frame
reads in ``verifyd/shm.py`` and ``libs/grpc.py`` (plus
``int.from_bytes`` length fields), ``json.loads`` bodies in
``rpc/server.py``, and the gossip ``server_stats`` snapshots
``verifyd/federation.py`` merges. Any value produced by one of those
calls inside a surface module is tainted.

Taint PROPAGATES through assignments, arithmetic, tuple unpacking,
f-strings, container literals, dataclass/attribute stores (including
``self.X`` — a method that stores tainted data into an attribute
taints that attribute for every method of the class), and across
function calls: summaries record whether a function *returns* tainted
data (per attribute / per constant dict key, so ``decode_request``'s
guarded fields come back clean while unguarded ones stay hot) and
which *parameters* are tainted at any call site, iterated to a fixed
point over the same import-alias call-graph machinery jaxpurity uses.

Taint is CLEARED only by:

- a dominating range guard — a comparison of the tainted name (or its
  ``len()``) against an untainted bound inside an ``if``/``assert``
  whose failing branch raises/returns, or membership tests like
  ``if kind not in KIND_NAMES: raise``;
- a clamp — ``x = min(x, LIMIT)``, ``x % N``, ``x & MASK``;
- an explicit ``# tpuflow: sanitized=<reason>`` annotation on the
  statement line, for bounds the analysis cannot see (e.g. enforced
  inside a helper). Annotations are themselves audited: one that never
  clears any taint is reported stale (TPT004).

Report codes:

- TPT001 — unguarded tainted length/size/index at a sink: allocation
  sizes (``bytearray(n)``, ``recv(n)``, ``b"x" * n``), slice/index
  bounds, ``struct.unpack``/``unpack_from`` offsets and tainted format
  counts, ``pack_into`` offsets.
- TPT002 — tainted value used as a loop/blocking bound: ``range(n)``,
  ``while`` tests, ``.wait(timeout=n)``, ``time.sleep(n)``,
  ``settimeout(n)`` — the "huge deadline pins a worker forever" class.
- TPT003 — tainted key grows an unbounded mapping (tenant/shard label
  maps): ``d[tainted] = v`` / ``d.setdefault(tainted, ...)`` on a
  known dict.
- TPT004 — stale ``tpuflow`` annotation: the annotated statement
  carries no taint to clear (the code changed under the comment), or
  the annotation is malformed (no ``=<reason>``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from scripts.analysis.core import (
    Checker,
    Finding,
    Module,
    Project,
    dotted_name,
)

# Decode-surface modules: taint originates here and only here. Other
# modules still participate in propagation (a tainted return value or
# argument carries into them), but their own unpack/json calls operate
# on trusted, locally-produced data and stay clean.
SURFACE_SUFFIXES = (
    "tendermint_tpu/encoding/proto.py",
    "tendermint_tpu/verifyd/protocol.py",
    "tendermint_tpu/verifyd/shm.py",
    "tendermint_tpu/verifyd/client.py",
    "tendermint_tpu/verifyd/federation.py",
    "tendermint_tpu/libs/grpc.py",
    "tendermint_tpu/rpc/server.py",
)

# terminal attribute names whose CALL result is tainted in a surface
# module: the proto Reader cursor methods, struct unpacking, network
# length fields, JSON bodies, and gossip snapshots
_READ_CALLS = {
    "read_varint", "read_svarint", "read_bytes",
    "read_fixed32", "read_fixed64", "read_sfixed64",
}
_UNPACK_CALLS = {"unpack", "unpack_from"}
_SOURCE_ATTR_CALLS = _READ_CALLS | _UNPACK_CALLS | {"server_stats"}

# allocation-ish callees: a tainted size argument is TPT001
_ALLOC_CALLS = {
    "bytearray", "recv", "recv_into", "read", "readexactly", "zeros",
    "empty",
}
# blocking-ish callees: a tainted timeout/count argument is TPT002
_BLOCK_CALLS = {"wait", "sleep", "settimeout", "acquire", "join"}

# builtins that launder taint away (result is host-controlled)
_CLEAN_CALLS = {
    "len", "bool", "isinstance", "hasattr", "id", "type", "repr",
    "format", "hash", "callable", "time", "monotonic", "perf_counter",
}
# builtins/conversions that pass taint through unchanged
_PASS_CALLS = {
    "int", "float", "str", "bytes", "abs", "round", "sum", "max",
    "sorted", "reversed", "list", "tuple", "set", "frozenset", "zip",
    "enumerate", "iter", "next", "bytearray", "memoryview", "dict",
}

_ANNOT_RE = re.compile(r"tpuflow:\s*sanitized\s*=\s*(\S.*)")
_ANNOT_ANY_RE = re.compile(r"tpuflow:")

#: the value-itself taint marker inside a slot set (other members are
#: tainted attribute / constant-key names of the bound object)
SELF_TAINT = ""

_MAX_ITERATIONS = 10


def _is_surface(rel: str) -> bool:
    return any(rel.endswith(suf) for suf in SURFACE_SUFFIXES)


class _FnInfo:
    """One analyzable function/method."""

    __slots__ = ("module", "node", "qualname", "cls", "params")

    def __init__(self, module: Module, node: ast.AST, qualname: str,
                 cls: Optional[str]):
        self.module = module
        self.node = node
        self.qualname = qualname
        self.cls = cls  # enclosing class name or None
        args = node.args
        self.params: List[str] = [
            a.arg for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
            )
        ]
        if args.vararg:
            self.params.append(args.vararg.arg)
        if args.kwarg:
            self.params.append(args.kwarg.arg)


class _Summary:
    """Cross-call facts about one function, grown monotonically."""

    __slots__ = ("param_taint", "returns", "return_attrs")

    def __init__(self):
        self.param_taint: Dict[str, Set[str]] = {}  # param name -> slots
        self.returns = False  # return value itself tainted
        self.return_attrs: Set[str] = set()  # tainted attrs/keys of return

    def merge_param(self, name: str, slots: Set[str]) -> bool:
        if not slots:
            return False
        cur = self.param_taint.setdefault(name, set())
        before = len(cur)
        cur |= slots
        return len(cur) != before


class TaintChecker(Checker):
    name = "taint"
    codes = {
        "TPT001": "unguarded tainted length/size/index reaches an "
                  "allocation, slice, or struct-offset sink",
        "TPT002": "tainted value used as a loop or blocking bound",
        "TPT003": "tainted key grows an unbounded mapping",
        "TPT004": "stale or malformed 'tpuflow: sanitized=' annotation",
    }

    # --- project pass ---------------------------------------------------------

    def check_project(self, project: Project) -> Iterator[Finding]:
        if not any(_is_surface(m.rel) for m in project.modules):
            return
        self._fns: Dict[Tuple[str, str], _FnInfo] = {}
        self._by_name: Dict[str, List[Tuple[str, str]]] = {}
        self._aliases: Dict[str, Dict[str, str]] = {}
        self._from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self._ext_imports: Dict[str, Set[str]] = {}
        self._dataclasses: Set[str] = set()
        self._class_attr_taint: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
        self._class_dict_attrs: Dict[Tuple[str, str], Set[str]] = {}
        self._summaries: Dict[Tuple[str, str], _Summary] = {}
        self._used_annotations: Set[Tuple[str, int]] = set()
        self._index(project)

        # fixed point: param taint and return summaries grow monotonically
        for _ in range(_MAX_ITERATIONS):
            self._changed = False
            for key in sorted(self._fns):
                self._analyze(key, emit=None)
            if not self._changed:
                break

        findings: List[Finding] = []
        for key in sorted(self._fns):
            self._analyze(key, emit=findings)
        findings.extend(self._annotation_findings(project))
        seen = set()
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.code,
                                                 f.message)):
            k = (f.path, f.line, f.code, f.message)
            if k not in seen:
                seen.add(k)
                yield f

    # --- indexing -------------------------------------------------------------

    def _index(self, project: Project) -> None:
        stems = {
            m.rel.rsplit("/", 1)[-1][:-3]: m.rel for m in project.modules
        }
        for mod in project.modules:
            self._aliases[mod.rel] = {}
            self._from_imports[mod.rel] = {}
            ext = self._ext_imports.setdefault(mod.rel, set())
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    tail = node.module.rsplit(".", 1)[-1]
                    for alias in node.names:
                        if alias.name in stems:
                            # from pkg import module [as alias]
                            self._aliases[mod.rel][
                                alias.asname or alias.name
                            ] = stems[alias.name]
                        elif tail in stems:
                            # from pkg.module import name [as alias]
                            self._from_imports[mod.rel][
                                alias.asname or alias.name
                            ] = (stems[tail], alias.name)
                        else:
                            ext.add(alias.asname or alias.name)
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        stem = alias.name.rsplit(".", 1)[-1]
                        if stem in stems:
                            self._aliases[mod.rel][
                                alias.asname or stem
                            ] = stems[stem]
                        else:
                            ext.add(
                                (alias.asname or alias.name).split(".")[0]
                            )
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_fn(mod, node, node.name, None)
                elif isinstance(node, ast.ClassDef):
                    decs = {
                        (dotted_name(d.func if isinstance(d, ast.Call) else d)
                         or "").rsplit(".", 1)[-1]
                        for d in node.decorator_list
                    }
                    if "dataclass" in decs:
                        self._dataclasses.add(node.name)
                    ckey = (mod.rel, node.name)
                    self._class_attr_taint.setdefault(ckey, {})
                    dict_attrs = self._class_dict_attrs.setdefault(ckey, set())
                    for sub in node.body:
                        if isinstance(sub,
                                      (ast.FunctionDef, ast.AsyncFunctionDef)):
                            self._add_fn(
                                mod, sub, f"{node.name}.{sub.name}", node.name
                            )
                            for st in ast.walk(sub):
                                tgt = None
                                if isinstance(st, ast.Assign) and st.targets:
                                    tgt = st.targets[0]
                                elif isinstance(st, ast.AnnAssign):
                                    tgt = st.target
                                if (
                                    tgt is not None
                                    and isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"
                                    and self._is_dict_expr(
                                        getattr(st, "value", None))
                                ):
                                    dict_attrs.add(tgt.attr)

    def _add_fn(self, mod: Module, node, qualname: str,
                cls: Optional[str]) -> None:
        key = (mod.rel, qualname)
        self._fns[key] = _FnInfo(mod, node, qualname, cls)
        self._summaries[key] = _Summary()
        self._by_name.setdefault(qualname.rsplit(".", 1)[-1], []).append(key)

    @staticmethod
    def _is_dict_expr(expr) -> bool:
        if isinstance(expr, ast.Dict):
            return True
        if isinstance(expr, ast.Call):
            callee = dotted_name(expr.func) or ""
            return callee.rsplit(".", 1)[-1] in (
                "dict", "defaultdict", "OrderedDict", "Counter"
            )
        return False

    # --- call resolution ------------------------------------------------------

    def _resolve_call(self, mod_rel: str, cls: Optional[str],
                      call: ast.Call) -> Optional[Tuple[str, str]]:
        fn = call.func
        if isinstance(fn, ast.Name):
            key = (mod_rel, fn.id)
            if key in self._fns:
                return key
            imp = self._from_imports.get(mod_rel, {}).get(fn.id)
            if imp and imp in self._fns:
                return imp
            return None
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name):
                base = fn.value.id
                if base == "self" and cls:
                    key = (mod_rel, f"{cls}.{fn.attr}")
                    if key in self._fns:
                        return key
                target_mod = self._aliases.get(mod_rel, {}).get(base)
                if target_mod:
                    key = (target_mod, fn.attr)
                    if key in self._fns:
                        return key
                    # module-level alias to a class method never resolves
                    return None
                if base in self._ext_imports.get(mod_rel, ()):
                    # a call through an external module (dataclasses.
                    # fields, struct.unpack, ...) must never
                    # unique-resolve to a same-named repo method
                    return None
            # method call on an arbitrary object: resolve only when the
            # method name is globally unique (same trade jaxpurity makes
            # for simple-name calls — precision bounded by honesty)
            candidates = [
                k for k in self._by_name.get(fn.attr, ())
                if "." in k[1]
            ] or self._by_name.get(fn.attr, [])
            if len(candidates) == 1:
                return candidates[0]
        return None

    # --- per-function analysis ------------------------------------------------

    def _analyze(self, key: Tuple[str, str], emit) -> None:
        info = self._fns[key]
        summary = self._summaries[key]
        state: Dict[str, Set[str]] = {}
        for p, slots in summary.param_taint.items():
            state[p] = set(slots)
        walker = _FnWalker(self, info, state, emit)
        walker.run()
        if walker.returns_taint and not summary.returns:
            summary.returns = True
            self._changed = True
        new_attrs = walker.return_attrs - summary.return_attrs
        if new_attrs:
            summary.return_attrs |= new_attrs
            self._changed = True

    # --- annotation audit -----------------------------------------------------

    def _annotation_findings(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            for line, text in sorted(mod.comments.items()):
                if not _ANNOT_ANY_RE.search(text):
                    continue
                m = _ANNOT_RE.search(text)
                if not m:
                    out.append(Finding(
                        mod.rel, line, "TPT004",
                        "malformed tpuflow annotation (expected "
                        "'# tpuflow: sanitized=<reason>')",
                    ))
                elif (mod.rel, line) not in self._used_annotations:
                    out.append(Finding(
                        mod.rel, line, "TPT004",
                        "stale tpuflow annotation: no tainted value "
                        "reaches this statement (drop the comment or "
                        "restore the guard it documented)",
                    ))
        return out


class _FnWalker:
    """Statement-ordered abstract interpretation of one function body.

    ``state`` maps a name (``"x"`` or one-level dotted ``"req.kind"``)
    to its tainted *slots*: ``SELF_TAINT`` ("") means the value itself,
    other members are tainted attribute/constant-key names of the bound
    object. Emits findings when ``emit`` is a list (final pass), and
    always feeds callee param taint + class-attr taint back into the
    checker for the fixed point.
    """

    def __init__(self, checker: TaintChecker, info: _FnInfo,
                 state: Dict[str, Set[str]], emit):
        self.c = checker
        self.info = info
        self.mod = info.module
        self.state = state
        self.emit = emit
        self.returns_taint = False
        self.return_attrs: Set[str] = set()
        self.dict_names: Set[str] = set()
        self.capped_dicts: Set[str] = set()
        self._nested: Set[ast.AST] = set()
        for sub in ast.walk(info.node):
            if sub is not info.node and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self._nested.add(sub)
                self._nested.update(ast.walk(sub))

    # -- driver ---------------------------------------------------------------

    def run(self) -> None:
        for stmt in self.info.node.body:
            self._exec(stmt)

    def _report(self, node: ast.AST, code: str, message: str) -> None:
        annot = self._annotated(node)
        if annot is not None:
            # the sink is annotated as sanitized-elsewhere: suppress,
            # and record the annotation as live (not TPT004-stale)
            self._use_annotation(annot)
            return
        if self.emit is not None:
            self.emit.append(Finding(
                self.mod.rel, getattr(node, "lineno", 1), code, message
            ))

    # -- taint state helpers --------------------------------------------------

    def _slots(self, name: str) -> Set[str]:
        return self.state.get(name, set())

    def _set(self, name: str, slots: Set[str]) -> None:
        if slots:
            self.state[name] = set(slots)
        else:
            self.state.pop(name, None)

    def _clear(self, name: str) -> None:
        self.state.pop(name, None)
        if "." in name:
            # clearing req.kind removes the slot from req as well
            base, attr = name.split(".", 1)
            slots = self.state.get(base)
            if slots:
                slots.discard(attr)
                if not slots:
                    del self.state[base]

    def _annotated(self, stmt: ast.AST) -> Optional[int]:
        """Line of the ``tpuflow: sanitized=`` annotation covering this
        statement: trailing on the same line, or in the contiguous
        comment block immediately above it. None when unannotated."""
        line = getattr(stmt, "lineno", -1)
        if _ANNOT_RE.search(self.mod.comment_on(line)):
            return line
        prev = line - 1
        while prev > 0 and prev in self.mod.comments:
            if _ANNOT_RE.search(self.mod.comments[prev]):
                return prev
            prev -= 1
        return None

    def _use_annotation(self, annot_line: int) -> None:
        self.c._used_annotations.add((self.mod.rel, annot_line))

    # -- expression evaluation ------------------------------------------------

    def _eval(self, expr) -> Set[str]:
        """Tainted slots of an expression's value (findings emitted for
        sinks encountered along the way)."""
        if expr is None or isinstance(expr, ast.Constant):
            return set()
        if isinstance(expr, ast.Name):
            return set(self._slots(expr.id))
        if isinstance(expr, ast.Attribute):
            return self._eval_attribute(expr)
        if isinstance(expr, ast.Subscript):
            return self._eval_subscript(expr, store=False)
        if isinstance(expr, ast.Call):
            return self._eval_call(expr)
        if isinstance(expr, ast.BinOp):
            self._check_mult_alloc(expr)
            out = self._eval(expr.left) | self._eval(expr.right)
            if isinstance(expr.op, (ast.Mod, ast.BitAnd)) and not self._eval(
                expr.right
            ):
                return set()  # x % N / x & MASK clamps to a host bound
            return out
        if isinstance(expr, ast.UnaryOp):
            return self._eval(expr.operand)
        if isinstance(expr, ast.BoolOp):
            out: Set[str] = set()
            for v in expr.values:
                out |= self._eval(v)
            return out
        if isinstance(expr, ast.Compare):
            self._eval(expr.left)
            for comp in expr.comparators:
                self._eval(comp)
            return set()  # a bool is never a size
        if isinstance(expr, ast.IfExp):
            self._eval(expr.test)
            return self._eval(expr.body) | self._eval(expr.orelse)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for el in expr.elts:
                if isinstance(el, ast.Starred):
                    el = el.value
                if self._eval(el):
                    out.add(SELF_TAINT)
            return out
        if isinstance(expr, ast.Dict):
            out = set()
            for k, v in zip(expr.keys, expr.values):
                vt = self._eval(v)
                if k is not None:
                    self._eval(k)
                if vt:
                    if (
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    ):
                        out.add(k.value)
                    else:
                        out.add(SELF_TAINT)
            return out
        if isinstance(expr, ast.JoinedStr):
            out = set()
            for v in expr.values:
                if isinstance(v, ast.FormattedValue) and self._eval(v.value):
                    out.add(SELF_TAINT)
            return out
        if isinstance(expr, ast.FormattedValue):
            return self._eval(expr.value)
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._eval_comprehension(expr)
        if isinstance(expr, ast.Lambda):
            self._eval(expr.body)
            return set()
        if isinstance(expr, ast.Slice):
            out = set()
            for part in (expr.lower, expr.upper, expr.step):
                if part is not None:
                    out |= self._eval(part)
            return out
        if isinstance(expr, ast.Await):
            return self._eval(expr.value)
        if isinstance(expr, (ast.Yield, ast.YieldFrom)):
            slots = self._eval(expr.value) if expr.value else set()
            if slots:
                # a generator whose yields are tainted taints every
                # loop that iterates it (Reader.fields and friends)
                self.returns_taint = True
            return slots
        if isinstance(expr, ast.NamedExpr):
            slots = self._eval(expr.value)
            self._set(expr.target.id, slots)
            return slots
        return set()

    def _eval_attribute(self, expr: ast.Attribute) -> Set[str]:
        dotted = dotted_name(expr)
        if dotted:
            direct = self._slots(dotted)
            if direct:
                return set(direct)
            base = dotted.rsplit(".", 1)[0]
            base_slots = self._slots(base)
            if SELF_TAINT in base_slots or expr.attr in base_slots:
                return {SELF_TAINT}
            if base == "self" and self.info.cls:
                cat = self.c._class_attr_taint.get(
                    (self.mod.rel, self.info.cls), {}
                )
                slots = cat.get(expr.attr)
                if slots:
                    return set(slots)
            return set()
        inner = self._eval(expr.value)
        return {SELF_TAINT} if SELF_TAINT in inner or expr.attr in inner \
            else set()

    def _eval_subscript(self, expr: ast.Subscript, store: bool) -> Set[str]:
        recv = dotted_name(expr.value) or ""
        recv_slots = self._eval(expr.value)
        idx = expr.slice
        idx_slots = self._eval(idx)
        is_dict = self._is_known_dict(recv)
        if idx_slots:
            if is_dict:
                if store and not self._dict_capped(recv):
                    self._report(
                        expr, "TPT003",
                        f"tainted key grows mapping '{recv or '<expr>'}' "
                        "with no cardinality guard (cap entries or guard "
                        "the key before insertion)",
                    )
            else:
                what = "index/slice bound" if not isinstance(idx, ast.Slice) \
                    else "slice bound"
                self._report(
                    expr, "TPT001",
                    f"tainted {what} into '{recv or '<expr>'}' without a "
                    "dominating range guard",
                )
        if SELF_TAINT in recv_slots:
            return {SELF_TAINT}
        if (
            isinstance(idx, ast.Constant) and isinstance(idx.value, str)
            and idx.value in recv_slots
        ):
            return {SELF_TAINT}
        return set()

    def _eval_comprehension(self, expr) -> Set[str]:
        saved = {}
        for gen in expr.generators:
            it = self._eval(gen.iter)
            for name in _target_names(gen.target):
                saved.setdefault(name, self.state.get(name))
                self._set(name, {SELF_TAINT} if it else set())
            for cond in gen.ifs:
                self._eval(cond)
            self._check_range_loop(gen.iter, expr)
        if isinstance(expr, ast.DictComp):
            kt = self._eval(expr.key)
            vt = self._eval(expr.value)
            out = {SELF_TAINT} if (kt or vt) else set()
        else:
            out = {SELF_TAINT} if self._eval(expr.elt) else set()
        for name, old in saved.items():
            if old is None:
                self.state.pop(name, None)
            else:
                self.state[name] = old
        return out

    # -- calls ----------------------------------------------------------------

    def _eval_call(self, call: ast.Call) -> Set[str]:
        callee = dotted_name(call.func) or ""
        terminal = callee.rsplit(".", 1)[-1]
        arg_slots = [self._eval(a) for a in call.args]
        kw_slots = {
            kw.arg: self._eval(kw.value) for kw in call.keywords
        }
        any_taint = any(arg_slots) or any(kw_slots.values())

        self._check_call_sinks(call, terminal, arg_slots, kw_slots)

        # sources (surface modules only)
        if _is_surface(self.mod.rel):
            if (
                isinstance(call.func, ast.Attribute)
                and terminal in _SOURCE_ATTR_CALLS
            ):
                return {SELF_TAINT}
            if callee in ("int.from_bytes", "json.loads"):
                return {SELF_TAINT}

        # interprocedural: push arg taint into the callee, pull summary

        target = self.c._resolve_call(self.mod.rel, self.info.cls, call)
        if target is not None:
            self._push_args(target, call, arg_slots, kw_slots)
            summ = self.c._summaries[target]
            out: Set[str] = set()
            if summ.returns:
                out.add(SELF_TAINT)
            out |= summ.return_attrs
            if out:
                return out

        # dataclass construction with tainted kwargs -> per-attr taint
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in self.c._dataclasses
        ):
            return {
                kw.arg for kw in call.keywords
                if kw.arg and kw_slots.get(kw.arg)
            }

        if terminal == "min" and len(call.args) > 1:
            # min(x, LIMIT) bounds the result iff any operand is clean
            if not all(arg_slots):
                return set()
            return {SELF_TAINT}
        if terminal in _CLEAN_CALLS:
            return set()
        if terminal in _PASS_CALLS or terminal in ("get", "pop", "copy",
                                                   "items", "values", "keys",
                                                   "setdefault", "decode",
                                                   "encode", "split",
                                                   "strip", "join"):
            return {SELF_TAINT} if any_taint or self._recv_taint(call) \
                else set()
        return set()

    def _recv_taint(self, call: ast.Call) -> bool:
        """d.get("k") on a tainted container yields tainted data."""
        if isinstance(call.func, ast.Attribute):
            return bool(self._eval(call.func.value))
        return False

    def _push_args(self, target: Tuple[str, str], call: ast.Call,
                   arg_slots, kw_slots) -> None:
        callee = self.c._fns[target]
        summ = self.c._summaries[target]
        params = list(callee.params)
        if params and params[0] == "self" and isinstance(
            call.func, ast.Attribute
        ):
            params = params[1:]
        for i, slots in enumerate(arg_slots):
            if slots and i < len(params):
                if summ.merge_param(params[i], slots):
                    self.c._changed = True
        for name, slots in kw_slots.items():
            if slots and name in callee.params:
                if summ.merge_param(name, slots):
                    self.c._changed = True

    def _check_call_sinks(self, call: ast.Call, terminal: str,
                          arg_slots, kw_slots) -> None:
        tainted_pos = [i for i, s in enumerate(arg_slots) if s]
        tainted_kw = [k for k, s in kw_slots.items() if s]
        if terminal in _UNPACK_CALLS or terminal == "pack_into":
            # only a tainted OFFSET or a tainted format COUNT walks the
            # cursor off the buffer — a tainted packed value or a
            # tainted source buffer is the normal decode shape. The
            # module functions take (fmt, buf, offset); a precompiled
            # ``Struct`` method drops the fmt arg.
            callee = dotted_name(call.func) or ""
            struct_mod = callee.startswith("struct.")
            fmt_idx = 0 if struct_mod else None
            if terminal == "unpack":
                off_idx = None
            else:
                off_idx = 2 if struct_mod else 1
            hot = (
                (fmt_idx is not None and fmt_idx in tainted_pos)
                or (off_idx is not None and off_idx in tainted_pos)
                or kw_slots.get("offset")
            )
            if hot:
                self._report(
                    call, "TPT001",
                    f"tainted offset/count reaches 'struct.{terminal}' "
                    "without a dominating range guard",
                )
            return
        if not tainted_pos and not tainted_kw:
            return
        if terminal in _ALLOC_CALLS:
            self._report(
                call, "TPT001",
                f"tainted size reaches allocation/read '{terminal}()' "
                "without a dominating range guard",
            )
        elif terminal in _BLOCK_CALLS:
            self._report(
                call, "TPT002",
                f"tainted value bounds blocking call '{terminal}()' "
                "(a hostile peer controls how long this blocks)",
            )
        elif terminal == "range":
            self._report(
                call, "TPT002",
                "tainted value bounds 'range()' without a dominating "
                "range guard",
            )
        elif terminal == "setdefault" and isinstance(
            call.func, ast.Attribute
        ):
            if arg_slots and arg_slots[0]:
                recv = dotted_name(call.func.value) or ""
                if self._is_known_dict(recv) and not self._dict_capped(recv):
                    self._report(
                        call, "TPT003",
                        f"tainted key grows mapping '{recv or '<expr>'}' "
                        "with no cardinality guard (cap entries or guard "
                        "the key before insertion)",
                    )

    def _check_mult_alloc(self, expr: ast.BinOp) -> None:
        if not isinstance(expr.op, ast.Mult):
            return
        for lit, size in ((expr.left, expr.right), (expr.right, expr.left)):
            if (
                isinstance(lit, (ast.Constant, ast.List, ast.Tuple))
                and (not isinstance(lit, ast.Constant)
                     or isinstance(lit.value, (str, bytes)))
                and self._eval(size)
            ):
                self._report(
                    expr, "TPT001",
                    "tainted repeat count allocates 'literal * n' "
                    "without a dominating range guard",
                )
                return

    def _check_range_loop(self, iter_expr, ctx) -> None:
        if (
            isinstance(iter_expr, ast.Call)
            and (dotted_name(iter_expr.func) or "").rsplit(".", 1)[-1]
            == "range"
        ):
            return  # range() args already checked in _eval_call
        return

    # -- dict receivers -------------------------------------------------------

    def _is_known_dict(self, recv: str) -> bool:
        if not recv:
            return False
        if recv in self.dict_names:
            return True
        if recv.startswith("self.") and self.info.cls:
            attrs = self.c._class_dict_attrs.get(
                (self.mod.rel, self.info.cls), set()
            )
            return recv.split(".", 1)[1] in attrs
        return False

    def _dict_capped(self, recv: str) -> bool:
        return recv in self.capped_dicts

    # -- statements -----------------------------------------------------------

    def _exec(self, stmt) -> None:
        if stmt in self._nested:
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom,
                             ast.Global, ast.Nonlocal, ast.Pass)):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._exec_assign(stmt)
        elif isinstance(stmt, ast.Expr):
            had = self._annotated(stmt)
            slots = self._eval(stmt.value)
            if had is not None and slots:
                self._use_annotation(had)
        elif isinstance(stmt, ast.Return):
            slots = self._eval(stmt.value) if stmt.value else set()
            if stmt.value is not None:
                if isinstance(stmt.value, ast.Name):
                    slots = self._slots(stmt.value.id)
                if SELF_TAINT in slots:
                    self.returns_taint = True
                self.return_attrs |= slots - {SELF_TAINT}
        elif isinstance(stmt, ast.If):
            self._exec_if(stmt)
        elif isinstance(stmt, ast.While):
            self._exec_while(stmt)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exec_for(stmt)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._exec(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._exec(s)
            for s in stmt.orelse:
                self._exec(s)
            for s in stmt.finalbody:
                self._exec(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval(item.context_expr)
            for s in stmt.body:
                self._exec(s)
        elif isinstance(stmt, ast.Assert):
            self._apply_guard(self._guard_names(stmt.test))
            self._eval(stmt.test)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._eval(stmt.exc)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    self._eval_subscript(t, store=False)

    def _exec_assign(self, stmt) -> None:
        annotated = self._annotated(stmt)
        value = stmt.value
        slots = self._eval(value) if value is not None else set()
        if isinstance(stmt, ast.AugAssign):
            targets = [stmt.target]
            # x += tainted keeps x's own taint too
            tname = _target_name(stmt.target)
            if tname:
                slots |= self._slots(tname)
        else:
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
        if annotated is not None:
            if slots:
                self._use_annotation(annotated)
            slots = set()
        for target in targets:
            self._assign(target, slots, value)

        # dict-literal locals are growth-trackable receivers
        if (
            not isinstance(stmt, ast.AugAssign)
            and self.c._is_dict_expr(value)
        ):
            for target in targets:
                name = _target_name(target)
                if name:
                    self.dict_names.add(name)

    def _assign(self, target, slots: Set[str], value) -> None:
        if isinstance(target, ast.Name):
            self._set(target.id, slots)
        elif isinstance(target, ast.Attribute):
            dotted = dotted_name(target)
            if dotted:
                if slots:
                    self.state[dotted] = set(slots)
                    base = dotted.rsplit(".", 1)[0]
                    self.state.setdefault(base, set()).add(target.attr)
                else:
                    self._clear(dotted)
                if (
                    isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and self.info.cls
                ):
                    cat = self.c._class_attr_taint.setdefault(
                        (self.mod.rel, self.info.cls), {}
                    )
                    if slots:
                        cur = cat.setdefault(target.attr, set())
                        if not slots <= cur:
                            cur |= slots
                            self.c._changed = True
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                if isinstance(el, ast.Starred):
                    el = el.value
                self._assign(el, set(slots), value)
        elif isinstance(target, ast.Subscript):
            self._eval_subscript(target, store=True)
            recv = dotted_name(target.value) or ""
            if slots and recv:
                self.state.setdefault(recv, set()).add(SELF_TAINT)

    # -- control flow + guards ------------------------------------------------

    def _guard_names(self, test) -> Set[str]:
        """Names a raise/return-guarded comparison in ``test`` bounds:
        each tainted name (or ``len(name)``) compared against at least
        one untainted side."""
        out: Set[str] = set()
        for node in ast.walk(test):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            names: Set[str] = set()
            clean = False
            for side in sides:
                sn = self._side_names(side)
                if sn:
                    names |= sn
                if _is_len_call(side) or (not sn and not self._eval(side)):
                    # an untainted side bounds the others; so does
                    # len(anything) — a buffer's measured length is a
                    # host-trusted integer even when its bytes are not
                    clean = True
            if clean:
                out |= names
        return out

    def _side_names(self, side) -> Set[str]:
        """Tainted names referenced by one comparison side (unwrapping
        ``len()``/arithmetic). Recursion stops at an Attribute chain (a
        guard on ``req.kind`` bounds only that field, not all of
        ``req``) and skips ``x % N`` / ``x & MASK`` clamp subtrees —
        those sides are already host-bounded comparators."""
        out: Set[str] = set()

        def visit(node) -> None:
            name = None
            if isinstance(node, ast.Attribute):
                name = dotted_name(node)
            elif isinstance(node, ast.Name):
                name = node.id
            if name is not None:
                if self._slots(name) or (
                    isinstance(node, ast.Attribute)
                    and self._eval_attribute(node)
                ):
                    out.add(name)
                return
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, (ast.Mod, ast.BitAnd))
                and not self._eval(node.right)
            ):
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(side)
        return out

    @staticmethod
    def _aborts(body: Sequence[ast.stmt]) -> bool:
        return any(
            isinstance(s, (ast.Raise, ast.Return, ast.Continue, ast.Break))
            for s in body
        )

    def _apply_guard(self, names: Set[str]) -> None:
        for name in names:
            self._clear(name)

    def _exec_if(self, stmt: ast.If) -> None:
        guards = self._guard_names(stmt.test)
        self._eval(stmt.test)
        # len(d)-cap guards mark the dict as bounded for this function
        for node in ast.walk(stmt.test):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "len"
                and node.args
            ):
                recv = dotted_name(node.args[0])
                if recv and self._is_known_dict(recv):
                    self.capped_dicts.add(recv)
        saved = {k: set(v) for k, v in self.state.items()}
        self._apply_guard(guards)  # inside either branch the bound is known
        for s in stmt.body:
            self._exec(s)
        body_state = self.state
        self.state = {k: set(v) for k, v in saved.items()}
        self._apply_guard(guards)
        for s in stmt.orelse:
            self._exec(s)
        if self._aborts(stmt.body):
            # the guarded names survive only bounded past this point
            self._apply_guard(guards)
            return
        # merge: union of taint from both branches
        for k, v in body_state.items():
            self.state.setdefault(k, set()).update(v)
        self._apply_guard(guards)

    def _exec_while(self, stmt: ast.While) -> None:
        tainted = self._side_names(stmt.test)
        if tainted:
            self._report(
                stmt, "TPT002",
                "tainted value bounds 'while' loop "
                f"({', '.join(sorted(tainted))}) without a dominating "
                "range guard",
            )
        self._eval(stmt.test)
        for s in stmt.body:
            self._exec(s)
        for s in stmt.orelse:
            self._exec(s)

    def _exec_for(self, stmt) -> None:
        it_slots = self._eval(stmt.iter)
        elem = {SELF_TAINT} if SELF_TAINT in it_slots else set()
        if (
            isinstance(stmt.iter, ast.Call)
            and isinstance(stmt.iter.func, ast.Name)
            and stmt.iter.func.id in ("items",)
        ):
            pass
        self._assign(stmt.target, elem, stmt.iter)
        for s in stmt.body:
            self._exec(s)
        for s in stmt.orelse:
            self._exec(s)
        # per-element guard heuristic: a loop whose body raise-guards
        # the loop variable bounds every element of the iterated names
        target_names = _target_names(stmt.target)
        if target_names and self._loop_guards_target(stmt, target_names):
            for name in _ref_names(stmt.iter):
                self._clear(name)
        for name in target_names:
            self.state.pop(name, None)

    def _loop_guards_target(self, stmt, target_names: Set[str]) -> bool:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.If) and self._aborts(sub.body):
                for node in ast.walk(sub.test):
                    if isinstance(node, ast.Compare):
                        for side in [node.left] + list(node.comparators):
                            for n in ast.walk(side):
                                if (
                                    isinstance(n, ast.Name)
                                    and n.id in target_names
                                ):
                                    return True
        return False


def _target_name(target) -> Optional[str]:
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return dotted_name(target)
    return None


def _is_len_call(node) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "len"
    )


def _ref_names(node) -> Set[str]:
    """Names referenced by an expression, stopping at Attribute chains
    (``req.pks`` contributes "req.pks", never bare "req")."""
    out: Set[str] = set()

    def visit(n) -> None:
        if isinstance(n, ast.Attribute):
            dotted = dotted_name(n)
            if dotted:
                out.add(dotted)
                return
        elif isinstance(n, ast.Name):
            out.add(n.id)
            return
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def _target_names(target) -> Set[str]:
    out: Set[str] = set()
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            out |= _target_names(el)
    else:
        n = _target_name(target)
        if n:
            out.add(n)
    return out
