"""Accumulate-with-deadline batch verification scheduler.

The latency/throughput duality (SURVEY §7 "Hard parts"): consensus votes
arrive one at a time and need ~100µs-class answers, while the device
verifier only pays off in batches. This scheduler is the seam between
them: concurrent callers submit single (pubkey, msg, sig) verifies and
block on a future; an accumulator thread flushes the pending set to ONE
batch verification when either

- the batch reaches ``max_batch`` entries (throughput bound), or
- the OLDEST pending entry has waited ``max_delay`` (latency bound) —
  the deadline is per-entry, so a lone vote is answered within
  ``max_delay`` even when nothing else arrives.

Per-entry verdicts come from the batch verifier's attribution (the
reference's BatchVerifier.Verify bool slice, crypto/crypto.go:58-76), so
one bad signature fails only its own future.

Serving extensions (used by verifyd, available to any caller):

- per-entry ``priority`` — when more work is pending than one batch
  holds, the dequeue is priority-ordered (lower value first, FIFO
  within a class) so consensus lanes never queue behind rpc floods;
- per-entry ``flush_by`` — an absolute monotonic deadline that pulls
  the flush earlier than ``max_delay`` when a wire deadline would
  otherwise expire while the lane sits in the accumulator;
- ``max_pending`` backpressure — ``submit`` raises
  ``SchedulerSaturatedError`` past the cap instead of growing the
  queue unboundedly (callers surface this as RESOURCE_EXHAUSTED);
- ``flush_reasons`` counters (``size``/``deadline``/``shutdown``) and
  an ``on_flush(reason, batch, seconds)`` callback, invoked BEFORE the
  futures resolve so observers see the flush strictly-before any
  waiter wakes.

Wiring: callers that ingest signatures from many concurrent sources
(per-peer vote floods, RPC broadcast storms) submit here instead of
calling ``pub_key.verify_signature`` inline; the single-threaded
consensus loop keeps its inline host verify, which is already
latency-optimal for one caller.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from tendermint_tpu.libs import tracing

DEFAULT_MAX_BATCH = 256
DEFAULT_MAX_DELAY = 0.002  # 2ms: well under a vote round-trip


def default_max_batch() -> int:
    """Size-flush threshold scaled to the verify mesh: with the sharded
    engine spanning k devices, a super-batch k× the single-device
    default keeps every chip's slab at the same occupancy one chip saw
    before. Falls back to the single-device default when the mesh (or
    its discovery) is unavailable."""
    try:
        from tendermint_tpu.parallel import mesh

        return DEFAULT_MAX_BATCH * max(1, mesh.manager.device_count())
    except Exception:  # discovery trouble must not break scheduler setup
        return DEFAULT_MAX_BATCH


class SchedulerSaturatedError(RuntimeError):
    """Pending queue is at ``max_pending``; shed load explicitly."""


@dataclass
class _Pending:
    pubkey: bytes
    msg: bytes
    sig: bytes
    submitted: float
    done: threading.Event = field(default_factory=threading.Event)
    ok: bool = False
    priority: int = 0  # lower flushes first when over-subscribed
    flush_by: Optional[float] = None  # absolute monotonic wire deadline
    tag: Optional[object] = None  # submitter identity (e.g. connection)

    def due(self, max_delay: float) -> float:
        """Absolute monotonic time this entry must be flushed by."""
        due = self.submitted + max_delay
        if self.flush_by is not None and self.flush_by < due:
            due = self.flush_by
        return due


class VerifyScheduler:
    """Batches concurrent single-signature verifies onto one verifier call.

    ``verify_fn(pks, msgs, sigs) -> List[bool]`` is the flush target —
    ``ops.verify_batch`` on a device backend, or any host batch verifier.

    ``fallback_fn`` (optional, same signature) is tried when
    ``verify_fn`` raises — the seam that keeps the scheduler draining
    under device degradation instead of failing whole flushes closed.
    Without a fallback, a raising flush still fails closed.
    """

    def __init__(
        self,
        verify_fn: Callable[
            [Sequence[bytes], Sequence[bytes], Sequence[bytes]], List[bool]
        ],
        max_batch: Optional[int] = None,
        max_delay: float = DEFAULT_MAX_DELAY,
        fallback_fn: Optional[
            Callable[
                [Sequence[bytes], Sequence[bytes], Sequence[bytes]], List[bool]
            ]
        ] = None,
        max_pending: int = 0,
        on_flush: Optional[
            Callable[[str, List[_Pending], float], None]
        ] = None,
    ):
        self._verify_fn = verify_fn
        self._fallback_fn = fallback_fn
        # None = mesh-aware default: 256 lanes per device the sharded
        # engine can span, so cross-client super-batches fill the mesh.
        self.max_batch = default_max_batch() if max_batch is None else max_batch
        self.max_delay = max_delay
        # 0 = unbounded (the historical in-process behavior); a serving
        # front-end sets a cap and maps SchedulerSaturatedError to an
        # explicit wire rejection.
        self.max_pending = max_pending
        self._on_flush = on_flush
        self._pending: List[_Pending] = []  # guarded-by: _mtx
        self._mtx = threading.Lock()
        self._wake = threading.Condition(self._mtx)
        self._stop = False  # guarded-by: _mtx
        self._thread: Optional[threading.Thread] = None  # guarded-by: _mtx
        # observability — single-writer: only the accumulator thread (and
        # post-join stop()) mutate these; racy reads are stats-grade.
        self.flushes = 0  # guarded-by: none(single-writer stats)
        self.entries_verified = 0  # guarded-by: none(single-writer stats)
        self.entries_coalesced = 0  # guarded-by: none(single-writer stats)
        self.flush_errors = 0  # guarded-by: none(single-writer stats)
        self.fallback_flushes = 0  # guarded-by: none(single-writer stats)
        self.submit_rejections = 0  # guarded-by: none(single-writer stats)
        self.flush_reasons = {"size": 0, "deadline": 0, "shutdown": 0}  # guarded-by: none(single-writer stats)

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            if self._thread is not None:
                return
            self._stop = False
            # assign under the lock: a concurrent start() must see it
            self._thread = threading.Thread(
                target=self._run, name="verify-scheduler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
            # snapshot under the lock (a concurrent start() may race us);
            # join OUTSIDE it — the accumulator needs _mtx to drain.
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
        # fail any stragglers closed rather than hanging their callers
        with self._mtx:
            leftovers, self._pending = self._pending, []
        if leftovers:
            self.flush_reasons["shutdown"] += 1
            self._notify_flush("shutdown", leftovers, 0.0)
        for p in leftovers:
            p.ok = False
            p.done.set()

    # --- submission ----------------------------------------------------------

    def submit(
        self,
        pubkey: bytes,
        msg: bytes,
        sig: bytes,
        *,
        priority: int = 0,
        flush_by: Optional[float] = None,
        tag: Optional[object] = None,
    ) -> _Pending:
        """Enqueue one signature; returns a handle for ``wait``. Callers
        with several signatures submit all first so one flush covers
        them, instead of paying the deadline once per signature."""
        entry = _Pending(
            pubkey,
            msg,
            sig,
            time.monotonic(),
            priority=priority,
            flush_by=flush_by,
            tag=tag,
        )
        with self._wake:
            if self._stop or self._thread is None:
                raise RuntimeError("scheduler not running")
            if self.max_pending and len(self._pending) >= self.max_pending:
                self.submit_rejections += 1
                raise SchedulerSaturatedError(
                    f"verify queue full ({self.max_pending} pending)"
                )
            self._pending.append(entry)
            self._wake.notify_all()
        return entry

    def submit_many(
        self,
        lanes: Sequence[Tuple[bytes, bytes, bytes]],
        *,
        priority: int = 0,
        flush_by: Optional[float] = None,
        tag: Optional[object] = None,
    ) -> List[_Pending]:
        """Atomically enqueue a whole lane group under ONE lock round and
        ONE accumulator wake-up. This is the super-batch entry point for
        callers that assemble many signatures at once (the light client's
        bisection ladder): all-or-nothing against ``max_pending``, so a
        half-admitted group can never split across two flushes on the
        admission boundary. Pair with ``flush_by=time.monotonic()`` to
        pull the flush immediately and spend exactly one device call on
        the group."""
        now = time.monotonic()
        entries = [
            _Pending(pk, msg, sig, now, priority=priority,
                     flush_by=flush_by, tag=tag)
            for pk, msg, sig in lanes
        ]
        with self._wake:
            if self._stop or self._thread is None:
                raise RuntimeError("scheduler not running")
            if self.max_pending and (
                len(self._pending) + len(entries) > self.max_pending
            ):
                self.submit_rejections += 1
                raise SchedulerSaturatedError(
                    f"verify queue full ({self.max_pending} pending)"
                )
            self._pending.extend(entries)
            self._wake.notify_all()
        return entries

    def wait_many(
        self, entries: Sequence[_Pending], timeout: float = 10.0
    ) -> List[bool]:
        """Block until every entry's batch flushed; per-entry verdicts,
        fail-closed on timeout (same contract as ``wait``). The deadline
        is shared across the group, not per entry."""
        deadline = time.monotonic() + timeout
        out: List[bool] = []
        for e in entries:
            left = deadline - time.monotonic()
            if left <= 0 or not e.done.wait(timeout=left):
                out.append(False)
            else:
                out.append(e.ok)
        return out

    def pending_depth(self) -> int:
        """Entries accumulated but not yet handed to a flush."""
        with self._mtx:
            return len(self._pending)

    def wait(self, entry: _Pending, timeout: float = 10.0) -> bool:
        """Block until the entry's batch flushed; False on timeout (fail
        closed: an unverified signature is an invalid signature)."""
        if not entry.done.wait(timeout=timeout):
            return False
        return entry.ok

    def verify(
        self, pubkey: bytes, msg: bytes, sig: bytes, timeout: float = 10.0
    ) -> bool:
        """Submit one signature and block until its batch flushes."""
        return self.wait(self.submit(pubkey, msg, sig), timeout=timeout)

    # --- accumulator ---------------------------------------------------------

    def _notify_flush(
        self, reason: str, batch: List[_Pending], seconds: float
    ) -> None:
        if self._on_flush is None:
            return
        try:
            self._on_flush(reason, batch, seconds)
        except Exception:
            pass  # observers never break the drain loop

    def _run(self) -> None:
        while True:
            reason = "size"
            with self._wake:
                while not self._stop:
                    if len(self._pending) >= self.max_batch:
                        reason = "size"
                        break
                    if self._pending:
                        # earliest obligation across max_delay AND any
                        # per-entry wire deadline (flush_by)
                        due = min(
                            p.due(self.max_delay) for p in self._pending
                        )
                        wait = due - time.monotonic()
                        if wait <= 0:
                            reason = "deadline"
                            break
                        self._wake.wait(timeout=wait)
                    else:
                        self._wake.wait(timeout=0.1)
                if self._stop:
                    return
                if len(self._pending) > self.max_batch:
                    # over-subscribed: highest-priority (lowest value)
                    # lanes flush first, FIFO within a class
                    order = sorted(
                        self._pending,
                        key=lambda p: (p.priority, p.submitted),
                    )
                    batch = order[: self.max_batch]
                    taken = {id(p) for p in batch}
                    self._pending = [
                        p for p in self._pending if id(p) not in taken
                    ]
                else:
                    batch, self._pending = self._pending, []
            if not batch:
                continue
            # Coalesce duplicate (pubkey, msg, sig) submissions: a vote
            # gossiped by k peers lands k times inside one deadline
            # window but costs one verifier lane; the verdict fans out
            # to every waiting future.
            pks: List[bytes] = []
            msgs: List[bytes] = []
            sigs: List[bytes] = []
            index: dict = {}
            slots: List[int] = []
            with tracing.span("sched_assemble", lanes=len(batch)) as asp:
                for p in batch:
                    key = (p.pubkey, p.msg, p.sig)
                    idx = index.get(key)
                    if idx is None:
                        idx = index[key] = len(pks)
                        pks.append(p.pubkey)
                        msgs.append(p.msg)
                        sigs.append(p.sig)
                    slots.append(idx)
                asp.set(unique=len(pks), coalesced=len(batch) - len(pks))
            self.entries_coalesced += len(batch) - len(pks)
            t0 = time.monotonic()
            with tracing.span("sched_flush", lanes=len(pks), reason=reason):
                try:
                    oks = self._verify_fn(pks, msgs, sigs)
                except Exception:
                    self.flush_errors += 1
                    oks = None
                    if self._fallback_fn is not None:
                        try:
                            oks = self._fallback_fn(pks, msgs, sigs)
                            self.fallback_flushes += 1
                        except Exception:
                            oks = None
                    if oks is None:
                        # fail closed, never hang callers
                        oks = [False] * len(pks)
            if len(oks) != len(pks):  # misbehaving verifier: fail closed
                oks = [False] * len(pks)
            self.flushes += 1
            self.flush_reasons[reason] += 1
            self.entries_verified += len(batch)
            # observers run strictly-before the futures resolve, so a
            # waiter that wakes can already see its flush accounted for
            self._notify_flush(reason, batch, time.monotonic() - t0)
            for p, idx in zip(batch, slots):
                p.ok = bool(oks[idx])
                p.done.set()
