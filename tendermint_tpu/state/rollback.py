"""Rollback: reconstruct and persist the state one height back.

The operator escape hatch for an app-hash divergence: roll the
consensus state from height H to H-1 so the block at H is re-processed
(internal/state/rollback.go:109). The rolled-back state is rebuilt from
the stores — validator sets and consensus params from the state store's
per-height records, AppHash/LastResultsHash from block H's header
(header.AppHash is the app state AFTER height H-1, exactly what state
H-1 carries).

``hard=True`` additionally deletes block H from the block store so a
restarted node re-runs consensus for H instead of replaying the stored
block into the app.
"""

from __future__ import annotations

from typing import Tuple

from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.storage.blockstore import BlockStore


def rollback_state(
    state_store: StateStore, block_store: BlockStore, hard: bool = False
) -> Tuple[int, bytes]:
    """Roll the latest state back one height; returns (height, app_hash)
    of the state now current. Raises if there is nothing to roll back to."""
    invalid_state = state_store.load()
    if invalid_state is None or invalid_state.is_empty():
        raise ValueError("no state found to roll back")
    height = invalid_state.last_block_height
    if height <= invalid_state.initial_height:
        raise ValueError(f"cannot roll back from initial height {height}")
    if block_store.height() != height:
        raise ValueError(
            f"block store height {block_store.height()} != state height "
            f"{height}; cannot roll back"
        )

    rollback_meta = block_store.load_block_meta(height)
    if rollback_meta is None:
        raise ValueError(f"block at height {height} not found")
    prev_meta = block_store.load_block_meta(height - 1)
    if prev_meta is None:
        raise ValueError(f"block at height {height - 1} not found")
    header = rollback_meta.header

    validators = state_store.load_validators(height)
    next_validators = state_store.load_validators(height + 1)
    last_validators = state_store.load_validators(height - 1)
    params = state_store.load_consensus_params(height)

    vals_changed = invalid_state.last_height_validators_changed
    if vals_changed > height:
        vals_changed = height
    params_changed = invalid_state.last_height_consensus_params_changed
    if params_changed > height:
        params_changed = height

    rolled = State(
        version=invalid_state.version,
        chain_id=invalid_state.chain_id,
        initial_height=invalid_state.initial_height,
        last_block_height=height - 1,
        last_block_id=header.last_block_id,
        last_block_time=prev_meta.header.time,
        next_validators=next_validators,
        validators=validators,
        last_validators=last_validators,
        last_height_validators_changed=vals_changed,
        consensus_params=params,
        last_height_consensus_params_changed=params_changed,
        last_results_hash=header.last_results_hash,
        app_hash=header.app_hash,
    )
    state_store.save(rolled)
    if hard:
        block_store.delete_latest_block()
    return rolled.last_block_height, rolled.app_hash
