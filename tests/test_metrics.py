"""Metrics + structured logging tests (metricsgen/libs-log analogs).

Instrument semantics, Prometheus text exposition conformance, the
logger's level and field behavior, the ``/debug/traces`` endpoint, and a
live node serving real consensus metrics over ``GET /metrics``.
"""

import io
import json
import re
import urllib.request

import pytest

from tendermint_tpu.libs.log import Logger, NOP_LOGGER
from tendermint_tpu.libs.metrics import (
    ConsensusMetrics,
    Counter,
    Gauge,
    Histogram,
    MempoolMetrics,
    OpsMetrics,
    P2PMetrics,
    Registry,
    StateMetrics,
)


class TestInstruments:
    def test_counter(self):
        c = Counter("test_total", "help")
        c.inc()
        c.inc(2.5)
        assert c.collect() == ["test_total 3.5"]
        with pytest.raises(ValueError):
            c.labels().inc(-1)

    def test_counter_labels(self):
        c = Counter("reqs_total", "help", ("code",))
        c.labels(code="200").inc()
        c.labels(code="200").inc()
        c.labels(code="500").inc()
        assert c.collect() == [
            'reqs_total{code="200"} 2',
            'reqs_total{code="500"} 1',
        ]

    def test_gauge(self):
        g = Gauge("height", "help")
        g.set(10)
        g.inc()
        g.dec(3)
        assert g.collect() == ["height 8"]

    def test_histogram(self):
        h = Histogram("lat", "help", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        lines = h.collect()
        assert 'lat_bucket{le="0.1"} 1' in lines
        assert 'lat_bucket{le="1"} 3' in lines
        assert 'lat_bucket{le="10"} 4' in lines
        assert 'lat_bucket{le="+Inf"} 5' in lines
        assert "lat_count 5" in lines
        assert any(line.startswith("lat_sum ") for line in lines)

    def test_registry_exposition_and_duplicates(self):
        reg = Registry()
        reg.counter("a_total", "first")
        reg.gauge("b", "second")
        with pytest.raises(ValueError):
            reg.counter("a_total", "again")
        text = reg.expose()
        assert "# HELP a_total first" in text
        assert "# TYPE a_total counter" in text
        assert "# TYPE b gauge" in text
        assert text.endswith("\n")

    def test_subsystem_structs_register(self):
        reg = Registry()
        ConsensusMetrics(reg)
        MempoolMetrics(reg)
        text = reg.expose()
        assert "tendermint_consensus_height" in text
        assert "tendermint_mempool_size" in text

    def test_nop_costs_nothing_visible(self):
        m = ConsensusMetrics.nop()
        m.height.set(5)  # must not raise, registers nowhere
        m.total_txs.inc()


class TestLabeledZeroSamples:
    def test_labeled_counter_with_no_samples_exposes_no_series(self):
        c = Counter("reqs_total", "help", ("code",))
        assert c.collect() == []

    def test_labeled_gauge_with_no_samples_exposes_no_series(self):
        g = Gauge("lanes", "help", ("engine",))
        assert g.collect() == []

    def test_unlabeled_zero_state_still_exposed(self):
        # zero-config instruments keep their `name 0` line: scrapers
        # see the series exists before the first increment
        assert Counter("a_total", "h").collect() == ["a_total 0"]
        assert Gauge("b", "h").collect() == ["b 0"]

    def test_label_values_escaped(self):
        c = Counter("errs_total", "help", ("reason",))
        c.labels(reason='quote " backslash \\ newline \n end').inc()
        (line,) = c.collect()
        assert line == (
            'errs_total{reason="quote \\" backslash \\\\ '
            'newline \\n end"} 1'
        )


# --- exposition conformance --------------------------------------------------

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _parse_exposition(text):
    """Minimal Prometheus text-format parser: returns ({name: type},
    {name: help}, [(series_name, {labels}, value)])."""
    types, helps, series = {}, {}, []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, h = line[len("# HELP "):].partition(" ")
            helps[name] = h
            continue
        if line.startswith("# TYPE "):
            name, _, t = line[len("# TYPE "):].partition(" ")
            types[name] = t
            continue
        assert not line.startswith("#"), f"unknown comment line: {line}"
        lhs, _, value = line.rpartition(" ")
        assert lhs and value, f"malformed series line: {line}"
        if "{" in lhs:
            sname, _, rest = lhs.partition("{")
            assert rest.endswith("}"), f"unclosed label set: {line}"
            labels = dict(_LABEL_RE.findall(rest[:-1]))
        else:
            sname, labels = lhs, {}
        series.append((sname, labels, float(value)))
    return types, helps, series


def _populated_full_registry():
    reg = Registry()
    consensus = ConsensusMetrics(reg)
    MempoolMetrics(reg)
    P2PMetrics(reg)
    StateMetrics(reg)
    ops = OpsMetrics(reg)
    consensus.height.set(7)
    consensus.step_duration_seconds.labels(step="propose").observe(0.004)
    consensus.step_duration_seconds.labels(step="commit").observe(2.0)
    ops.verify_stage_seconds.labels(stage="prep", engine="ed25519").observe(
        0.0004
    )
    ops.verify_stage_seconds.labels(stage="prep", engine="ed25519").observe(
        0.2
    )
    ops.inflight_lanes.labels(engine="ed25519").inc(64)
    ops.precompute_hits.inc(3)
    return reg


class TestExpositionConformance:
    def test_every_series_has_type_and_help(self):
        reg = _populated_full_registry()
        types, helps, series = _parse_exposition(reg.expose())
        assert set(types) == set(helps)  # pairing
        for sname, _labels, _v in series:
            base = sname
            for suffix in ("_bucket", "_sum", "_count"):
                if sname.endswith(suffix) and sname[: -len(suffix)] in types:
                    base = sname[: -len(suffix)]
                    break
            assert base in types, f"series {sname} lacks # TYPE"
            if base != sname:
                assert types[base] == "histogram"

    def test_histogram_buckets_cumulative_monotone(self):
        reg = _populated_full_registry()
        types, _helps, series = _parse_exposition(reg.expose())
        groups = {}
        counts = {}
        for sname, labels, v in series:
            if sname.endswith("_bucket"):
                base = sname[: -len("_bucket")]
                key = (base, tuple(sorted(
                    (k, lv) for k, lv in labels.items() if k != "le"
                )))
                groups.setdefault(key, []).append((labels["le"], v))
            elif sname.endswith("_count") and types.get(
                sname[: -len("_count")]
            ) == "histogram":
                counts[(sname[: -len("_count")], tuple(sorted(
                    labels.items()
                )))] = v
        assert groups  # the registry does expose histograms
        for key, buckets in groups.items():
            finite = [
                (float(le), v) for le, v in buckets if le != "+Inf"
            ]
            finite.sort()
            values = [v for _le, v in finite]
            assert values == sorted(values), f"non-monotone buckets: {key}"
            inf = [v for le, v in buckets if le == "+Inf"]
            assert len(inf) == 1
            assert inf[0] >= (values[-1] if values else 0)
            assert counts[key] == inf[0]  # +Inf bucket equals _count

    def test_no_unlabeled_series_for_labeled_metrics(self):
        reg = _populated_full_registry()
        _types, _helps, series = _parse_exposition(reg.expose())
        labeled = {
            m.name: set(m.label_names)
            for m in reg._metrics
            if m.label_names
        }
        for sname, labels, _v in series:
            for base, names in labeled.items():
                if sname == base or (
                    sname.startswith(base + "_")
                    and sname[len(base):] in ("_bucket", "_sum", "_count")
                ):
                    got = set(labels) - {"le"}
                    assert got == names, (
                        f"{sname}: expected labels {names}, got {got}"
                    )


class TestMetricsAudit:
    def test_no_dead_instruments(self):
        import importlib.util
        import os

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
            "check_metrics.py",
        )
        spec = importlib.util.spec_from_file_location("check_metrics", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.find_dead_instruments() == []
        # and the audit actually saw the instrument inventory
        assert len(mod.declared_instruments()) >= 30


class TestLogger:
    def test_levels_filter(self):
        sink = io.StringIO()
        log = Logger(level="warn", sink=sink)
        log.debug("d")
        log.info("i")
        log.warn("w")
        log.error("e")
        out = sink.getvalue()
        assert "WRN w" in out and "ERR e" in out
        assert "INF" not in out and "DBG" not in out

    def test_fields_and_kv(self):
        sink = io.StringIO()
        log = Logger(level="info", sink=sink, moniker="n0")
        log.with_fields(module="consensus").info(
            "committed block", height=5, hash=b"\xab\xcd" * 16
        )
        line = sink.getvalue().strip()
        assert "committed block" in line
        assert "height=5" in line
        assert "module=consensus" in line
        assert "moniker=n0" in line
        assert "abcd" in line  # bytes render as truncated hex

    def test_spaces_quoted(self):
        sink = io.StringIO()
        Logger(level="info", sink=sink).info("msg", err="two words")
        assert 'err="two words"' in sink.getvalue()

    def test_nop_logger_silent_and_chainable(self):
        NOP_LOGGER.with_fields(a=1).error("nothing happens")

    def test_bad_level_rejected(self):
        with pytest.raises(ValueError):
            Logger(level="loud")

    def test_dead_sink_never_raises(self):
        class Dead:
            def write(self, s):
                raise OSError("gone")

        Logger(level="info", sink=Dead()).info("still fine")


class TestLiveNodeMetrics:
    def test_metrics_endpoint_reflects_consensus(self, tmp_path):
        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.abci.kvstore import KVStoreApplication
        from tendermint_tpu.node.node import Node, NodeConfig
        from tendermint_tpu.privval.file_pv import FilePV
        from tests.test_node import CHAIN, fast_genesis, wait_for

        pv = FilePV.generate(
            str(tmp_path / "pk.json"), str(tmp_path / "ps.json")
        )
        node = Node(
            NodeConfig(
                chain_id=CHAIN,
                blocksync=False,
                wal_enabled=False,
                rpc_laddr="127.0.0.1:0",
            ),
            fast_genesis([pv]),
            LocalClient(KVStoreApplication()),
            priv_validator=pv,
        )
        node.start()
        try:
            assert wait_for(lambda: node.height >= 2, timeout=30)
            node.submit_tx(b"metrics=on")
            assert wait_for(
                lambda: node.height >= 4, timeout=30
            )
            with urllib.request.urlopen(
                f"{node.rpc_server.url}/metrics", timeout=5
            ) as resp:
                assert resp.headers["Content-Type"].startswith("text/plain")
                text = resp.read().decode()
            metrics = {}
            for line in text.splitlines():
                if line.startswith("#") or not line.strip():
                    continue
                name, _, value = line.rpartition(" ")
                metrics[name] = float(value)
            assert metrics["tendermint_consensus_height"] >= 2
            assert metrics["tendermint_consensus_validators"] == 1
            assert metrics["tendermint_consensus_total_txs"] >= 1
            assert metrics["tendermint_state_block_processing_time_count"] >= 2
            # wal_enabled=False -> NilWAL: the counter must NOT report
            # writes that were never persisted
            assert metrics["tendermint_consensus_wal_writes"] == 0
            assert metrics["tendermint_consensus_block_size_bytes"] > 0
            assert "tendermint_mempool_size" in metrics
            assert "tendermint_p2p_peers" in metrics
        finally:
            node.stop()


class TestDebugTracesEndpoint:
    """GET /debug/traces serves the global tracer's Chrome-trace JSON —
    exercised against a bare RPCServer (the same handler the node's
    operator surface mounts next to /metrics)."""

    @pytest.fixture
    def server(self):
        from tendermint_tpu.rpc.server import RPCServer

        srv = RPCServer(routes={}, metrics_registry=Registry())
        srv.start()
        yield srv
        srv.stop()

    @pytest.fixture
    def ring_tracer(self):
        from tendermint_tpu.libs import tracing

        tracing.tracer.set_metrics_observer(None)
        tracing.configure("ring")
        tracing.tracer.clear()
        yield tracing.tracer
        tracing.configure("off")
        tracing.tracer.clear()

    def _get(self, url):
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            return json.loads(resp.read().decode())

    def test_serves_bounded_valid_json(self, server, ring_tracer):
        from tendermint_tpu.libs import tracing

        for i in range(12):
            with tracing.span("rpc_traced", i=i):
                pass
        doc = self._get(f"{server.url}/debug/traces")
        spans = [
            e for e in doc["traceEvents"] if e.get("name") == "rpc_traced"
        ]
        assert len(spans) == 12
        assert doc["displayTimeUnit"] == "ms"
        assert doc["otherData"]["mode"] == "ring"

        # ?limit bounds the response
        doc = self._get(f"{server.url}/debug/traces?limit=5")
        spans = [
            e for e in doc["traceEvents"] if e.get("name") == "rpc_traced"
        ]
        assert len(spans) == 5
        assert [e["args"]["i"] for e in spans] == list(range(7, 12))

    def test_clear_drains_ring(self, server, ring_tracer):
        from tendermint_tpu.libs import tracing

        with tracing.span("once"):
            pass
        self._get(f"{server.url}/debug/traces?clear=1")
        doc = self._get(f"{server.url}/debug/traces")
        assert not [
            e for e in doc["traceEvents"] if e.get("ph") == "X"
        ]

    def test_off_mode_serves_empty_document(self, server):
        from tendermint_tpu.libs import tracing

        tracing.configure("off")
        tracing.tracer.clear()
        doc = self._get(f"{server.url}/debug/traces")
        assert doc["otherData"]["mode"] == "off"
        assert not [
            e for e in doc["traceEvents"] if e.get("ph") == "X"
        ]
