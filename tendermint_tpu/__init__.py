"""tendermint_tpu — a TPU-native BFT state-machine-replication framework.

A ground-up re-design of the capabilities of Tendermint Core
(reference: /root/reference, pure Go) for TPU hardware:

- The signature-verification hot path (commit verification, vote ingest,
  light-client header verification, blocksync catch-up) runs as batched,
  vmapped Ed25519 verification on TPU via JAX/XLA (see
  ``tendermint_tpu.ops``), sharded over device meshes with
  ``jax.sharding`` for very large validator sets.
- The control plane (consensus state machine, p2p, mempool, storage,
  RPC) stays on host, mirroring the reference's layering
  (SURVEY.md section 1) but built Python/C++-native rather than Go.

Layer map (bottom-up), mirroring reference layers 0-15:
  utils/      — service lifecycle, events, bitarray       (ref: libs/)
  encoding/   — protobuf wire codec (canonical bytes)     (ref: proto/ generated)
  crypto/     — keys, batch verifier dispatch, merkle     (ref: crypto/)
  ops/        — JAX/TPU device kernels: GF(2^255-19),
                Edwards curve, batched Ed25519 verify     (ref: curve25519-voi dep)
  parallel/   — meshes, shard_map batch sharding          (ref: goroutine concurrency)
  types/      — Block/Vote/Commit/ValidatorSet/params     (ref: types/)
  abci/       — ABCI++ application boundary               (ref: abci/)
  storage/    — KV abstraction + block/state stores       (ref: internal/store, tm-db)
  state/      — BlockExecutor, State                      (ref: internal/state)
  consensus/  — BFT state machine, WAL, timeouts          (ref: internal/consensus)
  mempool/    — priority mempool                          (ref: internal/mempool)
  p2p/        — router, peers, encrypted transport        (ref: internal/p2p)
  light/      — light client verifier/bisection           (ref: light/)
  privval/    — signers with double-sign protection       (ref: privval/)
  rpc/        — JSON-RPC surface                          (ref: rpc/)
  node/       — node assembly                             (ref: node/)
"""

__version__ = "0.1.0"
