"""ristretto255 group encoding over edwards25519.

The prime-order group sr25519 (schnorrkel) operates in. Builds on the
extended-coordinate edwards arithmetic in
:mod:`tendermint_tpu.crypto.ed25519_ref` — points are the usual
``(X, Y, Z, T)`` tuples; ristretto adds the quotient-group encode/decode
and coset-aware equality (RFC 9496).

Reference behavior: curve25519-voi's ristretto/sr25519 primitives backing
crypto/sr25519/pubkey.go:49 and crypto/sr25519/batch.go:15-47.
"""

from __future__ import annotations

from typing import Optional, Tuple

from tendermint_tpu.crypto.ed25519_ref import (
    B_POINT,
    D,
    IDENT,
    L,
    P,
    pt_add,
    pt_mul,
    pt_neg,
)

Point = Tuple[int, int, int, int]

# sqrt(-1) = 2^((p-1)/4), choosing the value that is "nonnegative"
# (even canonical encoding) per RFC 9496 §3.1.
SQRT_M1 = pow(2, (P - 1) // 4, P)
if SQRT_M1 & 1:
    SQRT_M1 = P - SQRT_M1

_A = P - 1  # curve coefficient a = -1


def _is_negative(x: int) -> bool:
    """RFC 9496 §3.1: negative iff the canonical encoding's low bit is set."""
    return (x % P) & 1 == 1


def _abs(x: int) -> int:
    x %= P
    return P - x if _is_negative(x) else x


def sqrt_ratio_m1(u: int, v: int) -> Tuple[bool, int]:
    """Compute sqrt(u/v) per RFC 9496 §4.2 (SQRT_RATIO_M1).

    Returns ``(was_square, r)`` with r nonnegative. When u/v is not a
    square, r = sqrt(SQRT_M1 * u / v).
    """
    u %= P
    v %= P
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u_neg = (P - u) % P
    correct = check == u
    flipped = check == u_neg
    flipped_i = check == u_neg * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    return (correct or flipped), _abs(r)


def invsqrt(x: int) -> Tuple[bool, int]:
    return sqrt_ratio_m1(1, x)


INVSQRT_A_MINUS_D = invsqrt((_A - D) % P)[1]


def decompress(data: bytes) -> Optional[Point]:
    """Decode a 32-byte ristretto255 encoding; None if invalid (RFC 9496 §4.3.1)."""
    if len(data) != 32:
        return None
    s = int.from_bytes(data, "little")
    # canonical and nonnegative
    if s >= P or s & 1:
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    # v = -(D * u1^2) - u2^2
    v = (-(D * u1 % P * u1 % P) - u2_sqr) % P
    ok, i = invsqrt(v * u2_sqr % P)
    if not ok:
        return None
    dx = i * u2 % P
    dy = i * dx % P * v % P
    x = _abs(2 * s % P * dx % P)
    y = u1 * dy % P
    t = x * y % P
    if _is_negative(t) or y == 0:
        return None
    return (x, y, 1, t)


def compress(p: Point) -> bytes:
    """Encode a point to its canonical 32-byte ristretto255 form (RFC 9496 §4.3.2)."""
    x0, y0, z0, t0 = p
    u1 = (z0 + y0) % P * ((z0 - y0) % P) % P
    u2 = x0 * y0 % P
    _, inv = invsqrt(u1 * u2 % P * u2 % P)
    den1 = inv * u1 % P
    den2 = inv * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    ix = x0 * SQRT_M1 % P
    iy = y0 * SQRT_M1 % P
    enchanted = den1 * INVSQRT_A_MINUS_D % P
    if _is_negative(t0 * z_inv % P):
        x, y = iy, ix
        den_inv = enchanted
    else:
        x, y = x0, y0
        den_inv = den2
    if _is_negative(x * z_inv % P):
        y = (P - y) % P
    s = _abs(den_inv * ((z0 - y) % P) % P)
    return s.to_bytes(32, "little")


def equals(p: Point, q: Point) -> bool:
    """Ristretto (coset-aware) equality: X1·Y2 == Y1·X2 or Y1·Y2 == X1·X2."""
    x1, y1, _, _ = p
    x2, y2, _, _ = q
    return (x1 * y2 - y1 * x2) % P == 0 or (y1 * y2 - x1 * x2) % P == 0


def is_identity(p: Point) -> bool:
    return equals(p, IDENT)


def scalar_from_wide(data: bytes) -> int:
    """64 uniform bytes → scalar mod L (Scalar::from_bytes_mod_order_wide)."""
    if len(data) != 64:
        raise ValueError("wide scalar input must be 64 bytes")
    return int.from_bytes(data, "little") % L


def scalar_from_canonical(data: bytes) -> Optional[int]:
    """32 bytes → scalar, requiring canonical (< L) encoding."""
    if len(data) != 32:
        return None
    s = int.from_bytes(data, "little")
    if s >= L:
        return None
    return s


__all__ = [
    "B_POINT",
    "IDENT",
    "L",
    "P",
    "Point",
    "SQRT_M1",
    "INVSQRT_A_MINUS_D",
    "compress",
    "decompress",
    "equals",
    "invsqrt",
    "is_identity",
    "pt_add",
    "pt_mul",
    "pt_neg",
    "scalar_from_canonical",
    "scalar_from_wide",
    "sqrt_ratio_m1",
]
