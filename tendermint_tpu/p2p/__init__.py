"""P2P networking (reference: internal/p2p/): encrypted, multiplexed
TCP control plane. The accelerator is a data-plane sidecar — consensus
wire traffic stays on sockets (SURVEY.md §5, distributed backend)."""

from tendermint_tpu.p2p.key import NodeID, NodeKey

__all__ = ["NodeID", "NodeKey"]
