"""sr25519 (Schnorrkel over ristretto255) — sign, verify, batch verify.

Schnorr signatures on the ristretto255 prime-order group with Merlin
transcripts, wire-compatible with w3f schnorrkel / curve25519-voi as used
by the reference (crypto/sr25519/pubkey.go:49-61, privkey.go:44-66,
batch.go:15-47):

- signing context: ``Transcript("SigningContext")`` + empty context label,
  message appended under ``sign-bytes`` (privkey.go:18 ``signingCtx``).
- protocol: ``proto-name = "Schnorr-sig"``; commit pubkey under
  ``sign:pk``, R under ``sign:R``; 64-byte challenge under ``sign:c``
  reduced to a scalar.
- keys: 32-byte MiniSecretKey expanded ExpandEd25519-style
  (privkey.go:131): SHA-512, clamp, divide by cofactor; nonce = h[32:64].
- signatures: ``R || s`` with the schnorrkel marker bit (s[31] |= 0x80)
  set on encode and required on decode.

Transcript hashing is host-side (sequential Keccak duplex — SURVEY §7
"Hard parts"); batch verification reduces to one multiscalar equation,
checked with a random linear combination.
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

from tendermint_tpu.crypto import ristretto
from tendermint_tpu.crypto.keys import (
    ADDRESS_LEN,
    SR25519_KEY_TYPE,
    PrivKey,
    PubKey,
)
from tendermint_tpu.crypto.merlin import MerlinTranscript
from tendermint_tpu.crypto.ristretto import (
    B_POINT,
    L,
    Point,
    compress,
    decompress,
    is_identity,
    pt_add,
    pt_mul,
    pt_neg,
    scalar_from_canonical,
    scalar_from_wide,
)

PUBKEY_SIZE = 32
SIGNATURE_SIZE = 64
SEED_SIZE = 32


def _signing_transcript(msg: bytes) -> MerlinTranscript:
    """signingCtx.NewTranscriptBytes(msg) with the empty signing context."""
    t = MerlinTranscript(b"SigningContext")
    t.append_message(b"", b"")
    t.append_message(b"sign-bytes", msg)
    return t


def _challenge(
    t: MerlinTranscript, pub_bytes: bytes, r_bytes: bytes
) -> int:
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub_bytes)
    t.append_message(b"sign:R", r_bytes)
    return scalar_from_wide(t.challenge_bytes(b"sign:c", 64))


def expand_seed(seed: bytes) -> Tuple[int, bytes]:
    """MiniSecretKey.ExpandEd25519 → (secret scalar, 32-byte nonce)."""
    if len(seed) != SEED_SIZE:
        raise ValueError("sr25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    # divide by the cofactor: clamping zeroed the low 3 bits, so a 256-bit
    # right shift is exact
    scalar = int.from_bytes(bytes(key), "little") >> 3
    return scalar % L, h[32:64]


def pubkey_from_seed(seed: bytes) -> bytes:
    scalar, _ = expand_seed(seed)
    return compress(pt_mul(scalar, B_POINT))


def sign(
    seed: bytes,
    msg: bytes,
    _expanded: Optional[Tuple[int, bytes, bytes]] = None,
) -> bytes:
    """Sign msg under the Tendermint signing context; returns R || s(marked).

    ``_expanded`` lets keepers of a long-lived key (Sr25519PrivKey) skip
    re-deriving (scalar, nonce, pub_bytes) on every signature.
    """
    if _expanded is not None:
        scalar, nonce, pub_bytes = _expanded
    else:
        scalar, nonce = expand_seed(seed)
        pub_bytes = compress(pt_mul(scalar, B_POINT))
    t = _signing_transcript(msg)
    # Witness scalar via the transcript RNG, rekeyed with the secret nonce
    # and fresh OS entropy (merlin TranscriptRngBuilder — any r is valid,
    # verifiers never recompute it).
    rng = (
        t.build_rng()
        .rekey_with_witness_bytes(b"signing", nonce)
        .finalize(os.urandom(32))
    )
    r = scalar_from_wide(rng.fill_bytes(64))
    if r == 0:  # pragma: no cover - 2^-252 probability
        r = 1
    r_bytes = compress(pt_mul(r, B_POINT))
    k = _challenge(t, pub_bytes, r_bytes)
    s = (k * scalar + r) % L
    s_bytes = bytearray(s.to_bytes(32, "little"))
    s_bytes[31] |= 0x80  # schnorrkel marker
    return r_bytes + bytes(s_bytes)


def _parse_signature(sig: bytes) -> Optional[Tuple[bytes, int]]:
    """Split R-bytes and canonical s; None unless the marker bit is set."""
    if len(sig) != SIGNATURE_SIZE:
        return None
    if not sig[63] & 0x80:
        return None  # not marked as schnorrkel
    s_bytes = bytearray(sig[32:64])
    s_bytes[31] &= 0x7F
    s = scalar_from_canonical(bytes(s_bytes))
    if s is None:
        return None
    return sig[:32], s


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single verify: R == s·B − k·A (checked via ristretto equality)."""
    if len(pub) != PUBKEY_SIZE:
        return False
    a_point = decompress(pub)
    if a_point is None:
        return False
    parsed = _parse_signature(sig)
    if parsed is None:
        return False
    r_bytes, s = parsed
    r_point = decompress(r_bytes)
    if r_point is None:
        return False
    k = _challenge(_signing_transcript(msg), pub, r_bytes)
    # s·B − k·A − R must be the (ristretto) identity
    check = pt_add(
        pt_mul(s, B_POINT),
        pt_add(pt_mul((L - k) % L, a_point), pt_neg(r_point)),
    )
    return is_identity(check)


class Sr25519PubKey(PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUBKEY_SIZE:
            raise ValueError("sr25519 pubkey must be 32 bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return hashlib.sha256(self._bytes).digest()[:ADDRESS_LEN]

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        # Reachable from untrusted wire input via pubkey_from_proto:
        # must return bool, never raise.
        try:
            return verify(self._bytes, msg, sig)
        except Exception:
            return False

    @property
    def type(self) -> str:
        return SR25519_KEY_TYPE


class Sr25519PrivKey(PrivKey):
    """MiniSecretKey-seeded signer (reference crypto/sr25519/privkey.go)."""

    __slots__ = ("_seed", "_scalar", "_nonce", "_pub_bytes")

    def __init__(self, seed: bytes):
        if len(seed) != SEED_SIZE:
            raise ValueError("sr25519 seed must be 32 bytes")
        self._seed = bytes(seed)
        self._scalar, self._nonce = expand_seed(self._seed)
        self._pub_bytes = compress(pt_mul(self._scalar, B_POINT))

    @classmethod
    def generate(cls) -> "Sr25519PrivKey":
        return cls(os.urandom(SEED_SIZE))

    @classmethod
    def from_secret(cls, secret: bytes) -> "Sr25519PrivKey":
        """GenPrivKeyFromSecret: SHA-256 the secret into a seed."""
        return cls(hashlib.sha256(secret).digest())

    def bytes(self) -> bytes:
        return self._seed

    def sign(self, msg: bytes) -> bytes:
        return sign(
            self._seed,
            msg,
            _expanded=(self._scalar, self._nonce, self._pub_bytes),
        )

    def pub_key(self) -> Sr25519PubKey:
        return Sr25519PubKey(self._pub_bytes)

    @property
    def type(self) -> str:
        return SR25519_KEY_TYPE


_OPS_IMPORT_WARNED = False  # one warning per process for a jax-less install


class Sr25519BatchVerifier:
    """Batch verifier with a device path and a host fallback.

    Above ``device_threshold`` entries the batch rides the ristretto
    Straus kernel (ops/sr25519_batch.py — per-entry verdicts, no
    re-verify needed for attribution). Below it, or when the device is
    unusable, one random-linear-combination multiscalar check on host:
    Σ zᵢ·(sᵢ·B − kᵢ·Aᵢ − Rᵢ) = 0 with random 128-bit zᵢ
    (reference batch.go:46 → curve25519-voi BatchVerifier.Verify),
    falling back to per-entry verifies for attribution on failure
    (types/validation.go:244-251).
    """

    def __init__(self, device_threshold: Optional[int] = None,
                 use_device: Optional[bool] = None):
        from tendermint_tpu.crypto.batch import DEVICE_THRESHOLD

        self._entries: List[Tuple[bytes, bytes, bytes]] = []
        self.device_threshold = (
            DEVICE_THRESHOLD if device_threshold is None else device_threshold
        )
        self.use_device = use_device  # None = auto

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        if pub_key.type != SR25519_KEY_TYPE:
            raise ValueError("sr25519 batch: pubkey is not sr25519")
        self._entries.append((pub_key.bytes(), msg, sig))

    def __len__(self) -> int:
        return len(self._entries)

    def verify(self) -> Tuple[bool, List[bool]]:
        n = len(self._entries)
        if n == 0:
            return False, []
        use_device = self.use_device
        if use_device is None:
            use_device = n >= self.device_threshold
        if use_device:
            try:
                from tendermint_tpu.ops.sr25519_batch import verify_batch_sr
            except ImportError:
                # No device engine in this install (jax absent): warn
                # once, then stop trying for the life of the process.
                global _OPS_IMPORT_WARNED
                if not _OPS_IMPORT_WARNED:
                    _OPS_IMPORT_WARNED = True
                    import warnings

                    warnings.warn(
                        "sr25519 device engine unavailable (ops import "
                        "failed); using host batch verification"
                    )
                self.use_device = False
            else:
                # verify_batch_sr handles device failures itself
                # (warn + shared sticky policy) and returns host-oracle
                # verdicts on fallback.
                oks = verify_batch_sr(
                    [e[0] for e in self._entries],
                    [e[1] for e in self._entries],
                    [e[2] for e in self._entries],
                )
                return all(oks), list(oks)
        parsed = []
        for pub, msg, sig in self._entries:
            a_point = decompress(pub) if len(pub) == PUBKEY_SIZE else None
            sp = _parse_signature(sig)
            r_point = decompress(sp[0]) if sp else None
            if a_point is None or sp is None or r_point is None:
                parsed.append(None)
                continue
            k = _challenge(_signing_transcript(msg), pub, sp[0])
            parsed.append((a_point, r_point, sp[1], k))
        if all(p is not None for p in parsed):
            s_coeff = 0
            acc: Point = ristretto.IDENT
            for a_point, r_point, s, k in parsed:  # type: ignore[misc]
                z = int.from_bytes(os.urandom(16), "little") | 1
                s_coeff = (s_coeff + z * s) % L
                acc = pt_add(acc, pt_mul(z * k % L, a_point))
                acc = pt_add(acc, pt_mul(z, r_point))
            check = pt_add(pt_mul(s_coeff, B_POINT), pt_neg(acc))
            if is_identity(check):
                return True, [True] * n
        # Attribution path: re-check each entry from its already-parsed
        # points/challenge (transcript hashing and decompression are the
        # expensive host-side steps — don't redo them).
        oks = []
        for p in parsed:
            if p is None:
                oks.append(False)
                continue
            a_point, r_point, s, k = p
            check = pt_add(
                pt_mul(s, B_POINT),
                pt_add(pt_mul((L - k) % L, a_point), pt_neg(r_point)),
            )
            oks.append(is_identity(check))
        return all(oks), oks
