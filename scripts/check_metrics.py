#!/usr/bin/env python3
"""Thin shim over the tpulint metrics checkers (scripts/analysis).

The dead-instrument and exposition-name audits now live in
``scripts/analysis/metrics_checks.py`` (codes TPM001/TPM002) so they
run with the rest of the static-analysis suite; this script keeps the
historical entry point (``python scripts/check_metrics.py``, used by
ci_checks.sh and loaded by file path in tests/test_metrics.py) working
with the same public functions and exit-code contract.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "tendermint_tpu")
METRICS_PY = os.path.join(PACKAGE, "libs", "metrics.py")

# this file is also loaded by path (importlib.spec_from_file_location),
# where the scripts package is not importable without the repo root
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.analysis import metrics_checks as _mc  # noqa: E402
from scripts.analysis.core import Module, Project, load_modules  # noqa: E402


def _load(path: str) -> Module:
    with open(path, "r") as fh:
        return Module(path, fh.read(), rel=os.path.relpath(path, REPO))


def declared_instruments(path: str = METRICS_PY) -> dict:
    """Map attribute name -> (class, lineno) for every ``self.X =
    reg.counter|gauge|histogram(...)`` assignment."""
    return _mc.declared_instruments(_load(path))


def referenced_attrs(root: str = PACKAGE, skip: str = METRICS_PY) -> set:
    """Attribute names referenced as ``.X`` anywhere under ``root``
    except the declaration file itself."""
    modules = load_modules([root], repo_root=REPO)
    skip_rel = os.path.relpath(os.path.abspath(skip), REPO).replace(
        os.sep, "/"
    )
    return _mc.referenced_attrs(Project(modules), skip_rel)


def declared_names(path: str = METRICS_PY) -> dict:
    """{"names": {full name -> (class, lineno)}, "problems": [str]} —
    the historical shape, rebuilt from TPM002 findings."""
    mod = _load(path)
    problems = [
        f"{f.path}:{f.line}: {f.message}" for f in _mc.name_findings(mod)
    ]
    # names map, recomputed the cheap way (problems already reported)
    names: dict = {}
    for attr, (cls, lineno) in _mc.declared_instruments(mod).items():
        names.setdefault(attr, (cls, lineno))
    return {"names": names, "problems": problems}


def find_dead_instruments() -> list:
    decls = declared_instruments()
    refs = referenced_attrs()
    return sorted(
        (name, cls, lineno)
        for name, (cls, lineno) in decls.items()
        if name not in refs
    )


def find_exemplar_problems() -> list:
    """TPM003 findings as strings: exemplar-bearing observe call sites
    whose instrument is undeclared or not a histogram."""
    modules = load_modules([PACKAGE], repo_root=REPO)
    metrics_mod = _load(METRICS_PY)
    return [
        f"{f.path}:{f.line}: {f.message}"
        for f in _mc.exemplar_findings(Project(modules), metrics_mod)
    ]


def find_bucket_problems() -> list:
    """TPM004 findings as strings: ``.labels(bucket=...)`` call sites
    whose value does not route through introspect.bucket_label."""
    modules = load_modules([PACKAGE], repo_root=REPO)
    return [
        f"{f.path}:{f.line}: {f.message}"
        for f in _mc.bucket_findings(Project(modules))
    ]


def main() -> int:
    decls = declared_instruments()
    dead = find_dead_instruments()
    rc = 0
    if dead:
        for name, cls, lineno in dead:
            print(
                f"DEAD INSTRUMENT {cls}.{name} "
                f"(libs/metrics.py:{lineno}): declared but never "
                f"referenced under tendermint_tpu/",
                file=sys.stderr,
            )
        rc = 1
    hygiene = declared_names()
    for problem in hygiene["problems"]:
        print(f"METRIC NAME {problem}", file=sys.stderr)
        rc = 1
    exemplar_problems = find_exemplar_problems()
    for problem in exemplar_problems:
        print(f"EXEMPLAR BINDING {problem}", file=sys.stderr)
        rc = 1
    bucket_problems = find_bucket_problems()
    for problem in bucket_problems:
        print(f"BUCKET CARDINALITY {problem}", file=sys.stderr)
        rc = 1
    if rc == 0:
        print(
            f"ok: all {len(decls)} declared instruments are referenced;"
            f" {len(hygiene['names'])} exposition names unique and"
            f" well-formed; exemplar-bearing histograms bound;"
            f" bucket labels bounded"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
