"""Metrics checkers (TPM): the check_metrics.py audits, in-framework.

- TPM001 — dead instrument: ``self.X = reg.counter|gauge|histogram(...)``
  declared in ``libs/metrics.py`` but ``.X`` never referenced anywhere
  else in the package. Dead instruments cost every /metrics scrape and
  usually mean an instrumentation seam fell off in a refactor.
- TPM002 — exposition-name hygiene: every instrument's full name must
  resolve statically (``_name(s, "...")`` with a literal per-class
  ``s = "<subsystem>"``, or a string literal), match
  ``tendermint_[a-z0-9_]+``, and be globally unique.
- TPM003 — exemplar binding: every ``observe(..., exemplar=...)`` call
  site must chain from an instrument attribute that is declared in
  ``libs/metrics.py`` **as a histogram**. TPM001 only catches the
  declared-but-unreferenced direction; an exemplar-bearing call site
  whose instrument was renamed away (or points at a counter/gauge,
  where exemplars silently never render) is the reverse failure and
  would otherwise ship dead trace-ID links.
- TPM004 — bucket-label cardinality: every ``.labels(bucket=...)``
  call site must pass a value produced by
  ``ops/introspect.bucket_label`` (directly, or via a local name
  assigned from it in the same function). That helper is the ONE
  place batch sizes collapse to power-of-two buckets with an
  ``other`` overflow; a raw ``bucket=str(n)`` call site would mint a
  label value per distinct batch size and blow up every scrape.

This is a project-level checker (it needs the whole package to find
references), which is exactly why ``check_metrics.py`` could not stay a
standalone script once the framework existed: it is now a thin shim over
these functions so existing invocations and tests keep working.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Set, Tuple

from scripts.analysis.core import Checker, Finding, Module, Project

_FACTORIES = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"tendermint_[a-z0-9_]+")
METRICS_REL = "tendermint_tpu/libs/metrics.py"


def declared_instruments(module: Module) -> Dict[str, Tuple[str, int]]:
    """attr -> (class, lineno) for every instrument declaration."""
    return {
        attr: (cls, lineno)
        for attr, (cls, lineno, _kind) in instrument_kinds(module).items()
    }


def instrument_kinds(module: Module) -> Dict[str, Tuple[str, int, str]]:
    """attr -> (class, lineno, factory kind) for every instrument
    declaration (kind is ``counter``/``gauge``/``histogram``)."""
    out: Dict[str, Tuple[str, int, str]] = {}
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                continue
            call = node.value
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in _FACTORIES
            ):
                continue
            out[tgt.attr] = (cls.name, node.lineno, call.func.attr)
    return out


def referenced_attrs(project: Project, skip_rel: str) -> Set[str]:
    refs: Set[str] = set()
    for mod in project.modules:
        if mod.rel == skip_rel:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                refs.add(node.attr)
    return refs


def _exemplar_instrument_attr(call: ast.Call) -> Tuple[str, bool]:
    """For an ``observe(...)`` call, resolve the instrument attribute at
    the base of the chain (``X`` in ``...metrics.X.labels(...).observe``
    or ``...metrics.X.observe``). Returns ("", False) when the base is a
    bare name (local alias — not statically resolvable)."""
    base = call.func.value  # type: ignore[attr-defined]
    # unwrap a .labels(...) hop
    if (
        isinstance(base, ast.Call)
        and isinstance(base.func, ast.Attribute)
        and base.func.attr == "labels"
    ):
        base = base.func.value
    if isinstance(base, ast.Attribute):
        return base.attr, True
    return "", False


def exemplar_findings(
    project: Project, metrics_mod: Module
) -> Iterator[Finding]:
    """TPM003: every exemplar-bearing observe must bind to a declared
    histogram (see module docstring)."""
    kinds = instrument_kinds(metrics_mod)
    for mod in project.modules:
        if mod.rel == metrics_mod.rel or not mod.rel.startswith(
            "tendermint_tpu/"
        ):
            continue
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "observe"
                and any(kw.arg == "exemplar" for kw in node.keywords)
            ):
                continue
            attr, resolved = _exemplar_instrument_attr(node)
            if not resolved:
                continue  # local alias; the dynamic path still works
            if attr not in kinds:
                yield Finding(
                    mod.rel,
                    node.lineno,
                    "TPM003",
                    f"exemplar observed on '{attr}', which is not a "
                    "declared instrument in libs/metrics.py (renamed "
                    "away? the trace-ID link is dead)",
                )
            elif kinds[attr][2] != "histogram":
                yield Finding(
                    mod.rel,
                    node.lineno,
                    "TPM003",
                    f"exemplar observed on '{attr}', a "
                    f"{kinds[attr][2]} — exemplars only render on "
                    "histogram buckets and would be silently dropped",
                )


def _is_bucket_label_call(node: ast.AST) -> bool:
    """A direct ``bucket_label(...)`` / ``introspect.bucket_label(...)``
    call expression."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "bucket_label"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "bucket_label"
    return False


def _blessed_bucket_names(fn: ast.AST) -> Set[str]:
    """Local names assigned (anywhere in this function) from a
    bucket_label call — the value is bounded no matter which branch
    assigned it."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_bucket_label_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def bucket_findings(project: Project) -> Iterator[Finding]:
    """TPM004: bounded bucket labels (see module docstring)."""
    for mod in project.modules:
        if not mod.rel.startswith("tendermint_tpu/"):
            continue
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            blessed = None  # computed lazily: most functions have no sites
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"
                ):
                    continue
                for kw in node.keywords:
                    if kw.arg != "bucket":
                        continue
                    if _is_bucket_label_call(kw.value):
                        continue
                    if blessed is None:
                        blessed = _blessed_bucket_names(fn)
                    if isinstance(kw.value, ast.Name) and kw.value.id in blessed:
                        continue
                    yield Finding(
                        mod.rel,
                        node.lineno,
                        "TPM004",
                        "bucket= label value does not come from "
                        "introspect.bucket_label — unbounded label "
                        "cardinality (one value per distinct batch size)",
                    )


def name_findings(module: Module) -> Iterator[Finding]:
    namespace = "tendermint"
    for node in ast.walk(module.tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "NAMESPACE"
            and isinstance(node.value, ast.Constant)
        ):
            namespace = node.value.value
    names: Dict[str, Tuple[str, int]] = {}
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        subsystem = None
        for node in ast.walk(cls):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "s"
                and isinstance(node.value, ast.Constant)
            ):
                subsystem = node.value.value
        for node in ast.walk(cls):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _FACTORIES
                and node.args
            ):
                continue
            arg = node.args[0]
            full = None
            if (
                isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id == "_name"
                and len(arg.args) == 2
                and isinstance(arg.args[1], ast.Constant)
            ):
                if subsystem is None:
                    yield Finding(
                        module.rel,
                        node.lineno,
                        "TPM002",
                        f"{cls.name}: _name(s, ...) without a literal "
                        's = "..." subsystem assignment',
                    )
                    continue
                full = f"{namespace}_{subsystem}_{arg.args[1].value}"
            elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                full = arg.value
            else:
                yield Finding(
                    module.rel,
                    node.lineno,
                    "TPM002",
                    f"{cls.name}: instrument name is not a static "
                    '_name(s, "...") or string literal',
                )
                continue
            if not _NAME_RE.fullmatch(full):
                yield Finding(
                    module.rel,
                    node.lineno,
                    "TPM002",
                    f"{cls.name}: bad metric name {full!r}",
                )
            if full in names:
                other = names[full]
                yield Finding(
                    module.rel,
                    node.lineno,
                    "TPM002",
                    f"{cls.name}: duplicate metric name {full!r} "
                    f"(also declared at {other[0]}:{other[1]})",
                )
            names[full] = (cls.name, node.lineno)


class MetricsChecker(Checker):
    name = "metrics"
    codes = {
        "TPM001": "instrument declared but never referenced (dead weight)",
        "TPM002": "metric exposition-name hygiene violation",
        "TPM003": "exemplar bound to an undeclared or non-histogram "
        "instrument",
        "TPM004": "bucket label value not routed through "
        "introspect.bucket_label (unbounded cardinality)",
    }

    def check_project(self, project: Project) -> Iterator[Finding]:
        yield from bucket_findings(project)
        metrics_mod = project.module(METRICS_REL)
        if metrics_mod is None:
            return
        yield from name_findings(metrics_mod)
        yield from exemplar_findings(project, metrics_mod)
        # the dead-instrument audit is only meaningful against the whole
        # package — on a partial file list every instrument looks dead
        if not any(
            not m.rel.startswith("tendermint_tpu/libs/")
            for m in project.modules
        ):
            return
        decls = declared_instruments(metrics_mod)
        refs = referenced_attrs(project, metrics_mod.rel)
        for attr, (cls, lineno) in sorted(decls.items()):
            if attr not in refs:
                yield Finding(
                    metrics_mod.rel,
                    lineno,
                    "TPM001",
                    f"{cls}.{attr} declared but never referenced "
                    "under tendermint_tpu/",
                )
