"""Node: the dependency-injection container wiring every component.

Mirrors node/node.go makeNode (node.go:121-400) + OnStart ordering
(node.go:403-519): stores -> genesis/state -> ABCI client -> mempool /
evidence -> executor -> consensus -> router + reactors -> (optionally)
blocksync until caught up, then consensus.
"""

from __future__ import annotations

import os
import threading
import time as _time
from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from tendermint_tpu.abci.client import AbciClient, LocalClient
from tendermint_tpu.abci import types as abci_types
from tendermint_tpu import eventbus as events_mod
from tendermint_tpu.blocksync.reactor import BlockSyncReactor
from tendermint_tpu.blocksync.syncer import BlockSyncer
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.wal import WAL, NilWAL
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.evidence.reactor import EvidenceReactor
from tendermint_tpu.mempool.mempool import MempoolConfig, TxMempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.peermanager import PeerAddress, PeerManager
from tendermint_tpu.p2p.pex import PexReactor
from tendermint_tpu.p2p.router import Router
from tendermint_tpu.p2p.transport import (
    MemoryNetwork,
    NodeInfo,
    TCPTransport,
    Transport,
)
from tendermint_tpu.privval import FilePV
from tendermint_tpu.privval.base import PrivValidator
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.statesync import StateSyncConfig, StateSyncReactor, StateSyncer
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.storage import open_db
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.types.genesis import GenesisDoc


@dataclass
class NodeConfig:
    """config/config.go condensed: the knobs the node assembly needs."""

    home: str = ""
    chain_id: str = ""
    listen_addr: str = "127.0.0.1:0"
    persistent_peers: List[str] = dc_field(default_factory=list)
    mempool: MempoolConfig = dc_field(default_factory=MempoolConfig)
    blocksync: bool = True
    wal_enabled: bool = True
    max_connections: int = 16
    moniker: str = "tpu-node"
    rpc_laddr: str = ""  # "host:port" enables the RPC server ("" = off)
    rpc_unsafe: bool = False  # register unsafe operator routes
    tx_index: bool = True
    # Event sinks (indexer/sink.py): any of "kv", "null", "sql"
    # (reference internal/state/indexer/sink/; all configured sinks
    # receive every block, indexer_service.go).
    tx_index_sinks: List[str] = dc_field(default_factory=lambda: ["kv"])
    # tm-db backend selection (config/db.go:29): "memdb" or "filedb".
    # filedb requires `home` (data lands in <home>/data/*.fdb).
    db_backend: str = "memdb"
    # Remote signer (config PrivValidator.ListenAddr, node/node.go:186):
    # when set (tcp://... or unix://...), the node listens here for an
    # out-of-process signer and uses it instead of a local FilePV.
    priv_validator_laddr: str = ""
    # How long node construction waits for the signer to dial in.
    signer_connect_timeout: float = 60.0
    # Structured logging level: debug/info/warn/error/none (libs/log).
    # "none" keeps embedded/test nodes silent; the CLI defaults to info.
    log_level: str = "none"
    # Per-peer connection rate limits (config.go P2P SendRate/RecvRate).
    p2p_send_rate: int = 5120000
    p2p_recv_rate: int = 5120000
    # Per-peer send-queue discipline (router.go:216-238).
    p2p_queue_type: str = "fifo"
    # Refuse to join consensus if our key signed a commit within the
    # last N blocks (config.go:961 double-sign-check-height; 0 = off).
    double_sign_check_height: int = 0
    # State sync (config/config.go StateSyncConfig): None disables.
    statesync: Optional["StateSyncConfig"] = None
    # Verify-pipeline span tracing: "" inherits TENDERMINT_TPU_TRACE
    # (default off), "ring" keeps a bounded in-memory ring served at
    # GET /debug/traces, any other value is a Chrome-trace JSON path
    # flushed at exit. "off" disables recording explicitly.
    trace: str = ""
    # Remote verification service ([ops] verify_remote / the
    # TENDERMINT_TPU_VERIFY_REMOTE env var): "host:port" routes
    # device-worthy signature batches to a verifyd daemon instead of a
    # local accelerator ("" = local verification).
    verify_remote: str = ""
    # Tenant/chain namespace the remote verifier files this node's
    # traffic under ([ops] verify_tenant): per-tenant budgets, quotas,
    # and metrics server-side. "" = the default tenant.
    verify_tenant: str = ""
    # Devices the sharded verify engine may span ([ops] mesh_devices /
    # the TENDERMINT_TPU_MESH env var): 0 = all available, 1 disables
    # sharding (parallel/mesh.py).
    mesh_devices: int = 0
    # Device-resident precompute table store ([ops] resident_tables /
    # the TENDERMINT_TPU_RESIDENT env var): "auto" | "on" | "off",
    # "" defers to the env var (ops/resident.py).
    resident_tables: str = ""
    # Shared-memory slab-ring transport to a co-located verifyd
    # ([ops] verify_shm / the TENDERMINT_TPU_SHM env var): "auto" |
    # "on" | "off", "" defers to the env var (verifyd/shm.py).
    verify_shm: str = ""


class Node:
    def __init__(
        self,
        config: NodeConfig,
        genesis: GenesisDoc,
        app_client: AbciClient,
        priv_validator: Optional[PrivValidator] = None,
        node_key: Optional[NodeKey] = None,
        transport: Optional[Transport] = None,
        memory_network: Optional[MemoryNetwork] = None,
    ):
        self.config = config
        self.genesis = genesis
        self.app = app_client

        # --- identity (node.go:85-103) --------------------------------------
        if node_key is None:
            if config.home:
                os.makedirs(config.home, exist_ok=True)
                node_key = NodeKey.load_or_gen(
                    os.path.join(config.home, "node_key.json")
                )
            else:
                node_key = NodeKey.generate()
        self.node_key = node_key
        self._signer_endpoint = None
        self._owned_signer = None  # gRPC signer client the node must close
        # Fatal error raised by a background routine (e.g. a post-sync
        # double-sign refusal); the operator loop polls this.
        self.failed: Optional[Exception] = None
        if priv_validator is None and config.priv_validator_laddr.startswith(
            "grpc://"
        ):
            # gRPC remote signer (privval/grpc/client.go): the node DIALS
            # the signer's server — inverse of the socket flavor below.
            from tendermint_tpu.privval.grpc import GrpcSignerClient

            host, _, port = config.priv_validator_laddr[7:].rpartition(":")
            priv_validator = GrpcSignerClient(
                host or "127.0.0.1", int(port), genesis.chain_id
            )
            # Same grace the socket flavor gives wait_for_connection: the
            # signer may come up moments after the node does.
            import time as _time

            deadline = _time.monotonic() + config.signer_connect_timeout
            while True:
                try:
                    priv_validator.get_pub_key()
                    break
                except (ConnectionError, OSError):
                    if _time.monotonic() >= deadline:
                        raise
                    _time.sleep(0.5)
            self._owned_signer = priv_validator
        if priv_validator is None and config.priv_validator_laddr:
            # Remote signer (node/node.go:186 createPrivval → signer
            # listener): listen here, wait for the signer to dial in.
            from tendermint_tpu.privval.remote import (
                SignerClient,
                SignerListenerEndpoint,
            )

            self._signer_endpoint = SignerListenerEndpoint(
                config.priv_validator_laddr, node_priv=None
            )
            self._signer_endpoint.start()
            # If construction fails past this point the exception frees the
            # half-built node; release the bound listener with it rather
            # than waiting for the socket's own GC close.
            import weakref

            weakref.finalize(self, self._signer_endpoint.close)
            # Construction below asks the signer for its pubkey; wait here
            # with retry-on-garbage-dials so an absent signer surfaces as
            # a clear error instead of a raw accept() timeout deep inside
            # consensus setup (SignerClient.WaitForConnection analog).
            self._signer_endpoint.wait_for_connection(
                config.signer_connect_timeout
            )
            priv_validator = SignerClient(
                self._signer_endpoint, genesis.chain_id
            )
        if priv_validator is None and config.home:
            priv_validator = FilePV.load_or_generate(
                os.path.join(config.home, "priv_validator_key.json"),
                os.path.join(config.home, "priv_validator_state.json"),
            )
        self.priv_validator = priv_validator

        # --- stores + state (node.go:136-156, initDBs) ------------------------
        db_dir = os.path.join(config.home, "data") if config.home else ""
        self._dbs = [open_db(config.db_backend, db_dir, n) for n in ("state", "blockstore")]
        self.state_store = StateStore(self._dbs[0])
        self.block_store = BlockStore(self._dbs[1])
        stored = self.state_store.load()
        if stored is None:
            self.sm_state = state_from_genesis(genesis)
            app_client.start()
            init = app_client.init_chain(
                abci_types.RequestInitChain(
                    time=genesis.genesis_time,
                    chain_id=genesis.chain_id,
                    consensus_params=genesis.consensus_params,
                    validators=[],
                    app_state_bytes=genesis.app_state,
                    initial_height=genesis.initial_height,
                )
            )
            if init.app_hash:
                self.sm_state.app_hash = init.app_hash
            if init.validators:
                from tendermint_tpu.types.validator_set import ValidatorSet

                vals = [vu.to_validator() for vu in init.validators]
                self.sm_state.validators = ValidatorSet(vals)
                self.sm_state.next_validators = ValidatorSet(vals)
                self.sm_state.next_validators.increment_proposer_priority(1)
            self.state_store.save(self.sm_state)
        else:
            self.sm_state = stored
            app_client.start()

        # --- event bus + indexer (node.go:158-184) ---------------------------
        # Indexing is synchronous inside _fire_events rather than via a
        # pubsub subscription: subscriptions are bounded queues that DROP
        # under backpressure (fine for RPC subscribers, lossy for an
        # index). The reference's indexer subscription is lossless /
        # publisher-blocking for the same reason (indexer_service.go).
        self.event_bus = events_mod.EventBus()
        self.indexer = None
        self.event_sink = None
        if config.tx_index:
            from tendermint_tpu.indexer.sink import (
                KVEventSink,
                MultiSink,
                NullEventSink,
                SQLEventSink,
            )

            sinks = []
            # dedupe AFTER normalizing aliases ("psql" == "sql"):
            # duplicates must not open the same store twice (the
            # reference errors on duplicates).
            normalized = [
                "sql" if s == "psql" else s
                for s in (config.tx_index_sinks or ["kv"])
            ]
            for sink_name in dict.fromkeys(normalized):
                if sink_name == "kv":
                    from tendermint_tpu.indexer import KVIndexer

                    idx_db = open_db(config.db_backend, db_dir, "tx_index")
                    self._dbs.append(idx_db)
                    self.indexer = KVIndexer(idx_db)
                    sinks.append(KVEventSink(self.indexer))
                elif sink_name == "null":
                    sinks.append(NullEventSink())
                elif sink_name == "sql":
                    # The psql schema over stdlib sqlite3 (see
                    # indexer/sink.py for the postgres swap).
                    import sqlite3

                    sql_path = (
                        os.path.join(db_dir, "tx_events.sqlite")
                        if db_dir
                        else ":memory:"
                    )
                    if db_dir:
                        os.makedirs(db_dir, exist_ok=True)
                    conn = sqlite3.connect(sql_path, check_same_thread=False)
                    sinks.append(SQLEventSink(conn, genesis.chain_id))
                else:
                    raise ValueError(
                        f"unknown indexer sink {sink_name!r} (kv|null|sql)"
                    )
            if sinks:
                self.event_sink = MultiSink(sinks)

        # --- observability (node.go:158-184 metrics, libs/log) ----------------
        from tendermint_tpu.libs.log import Logger
        from tendermint_tpu.libs.metrics import (
            ConsensusMetrics,
            MempoolMetrics,
            OpsMetrics,
            P2PMetrics,
            Registry,
            StateMetrics,
        )

        self.metrics_registry = Registry()
        self.logger = Logger(
            level=config.log_level or "none", moniker=config.moniker
        )
        consensus_metrics = ConsensusMetrics(self.metrics_registry)
        mempool_metrics = MempoolMetrics(self.metrics_registry)
        p2p_metrics = P2PMetrics(self.metrics_registry)
        state_metrics = StateMetrics(self.metrics_registry)
        ops_metrics = OpsMetrics(self.metrics_registry)
        # Mirror the process-wide device health machine into this node's
        # registry so /metrics exposes degradation and recovery.
        from tendermint_tpu.ops.device_policy import shared as _device_health

        _device_health.bind_metrics(ops_metrics)
        # Same for the precompute + result caches (ops/precompute.py).
        from tendermint_tpu.ops import precompute as _precompute

        _precompute.bind_metrics(ops_metrics)
        # Kernel-campaign units: the device-resident table store, the
        # on-device challenge hasher, and the field-mul autotuner.
        from tendermint_tpu.ops import autotune as _autotune
        from tendermint_tpu.ops import hash512 as _hash512
        from tendermint_tpu.ops import resident as _resident

        if config.resident_tables:
            _resident.configure(config.resident_tables)
        _resident.bind_metrics(ops_metrics)
        _hash512.bind_metrics(ops_metrics)
        _autotune.bind_metrics(ops_metrics)
        # And the verify mesh (parallel/mesh.py): apply the configured
        # device cap and mirror sharded-dispatch activity.
        from tendermint_tpu.parallel import mesh as _mesh

        _mesh.manager.configure(config.mesh_devices)
        _mesh.manager.bind_metrics(ops_metrics)
        # Device-tier introspection (ops/introspect.py): mirror the
        # byte ledger + compile counters into this registry and install
        # the continuous kernel profiler as the tracer's profile sink.
        from tendermint_tpu.ops import introspect as _introspect

        _introspect.bind_metrics(ops_metrics)
        _introspect.install()
        # Span tracer: honor an explicit config knob (env otherwise), and
        # feed span durations into the stage/step histograms regardless of
        # whether the ring is recording.
        from tendermint_tpu.libs import tracing as _tracing

        if config.trace:
            _tracing.configure(config.trace)
        _tracing.tracer.set_metrics_observer(
            _tracing.metrics_observer(
                ops=ops_metrics, consensus=consensus_metrics
            )
        )
        # Remote verification backend (verifyd/client.py): a configured
        # address makes every device-worthy batch go over the wire; the
        # client keeps a local host-verify fallback, so a dead daemon
        # degrades to CPU verification rather than failing commits.
        if config.verify_remote:
            from tendermint_tpu.verifyd import client as _vclient

            _vclient.set_remote_addr(config.verify_remote)
            if config.verify_tenant:
                _vclient.set_remote_tenant(config.verify_tenant)
        # Zero-copy ingress mode for that remote (verifyd/shm.py):
        # auto/on/off, applied process-wide so the cached client
        # negotiates (or refuses) the slab-ring transport accordingly.
        if config.verify_shm:
            from tendermint_tpu.verifyd import shm as _vshm

            _vshm.set_shm_mode(config.verify_shm)

        # --- pools + executor (node.go:258-297) ------------------------------
        self.mempool = TxMempool(
            config.mempool, app_client, metrics=mempool_metrics
        )
        self.evidence_pool = EvidencePool(
            state_store=self.state_store, block_store=self.block_store
        )
        self.evidence_pool.set_state(self.sm_state)
        self.block_exec = BlockExecutor(
            self.state_store,
            app_client,
            self.block_store,
            mempool=self.mempool,
            evidence_pool=self.evidence_pool,
            event_publisher=self._fire_events,
            metrics=state_metrics,
        )

        # --- ABCI handshake (node.go:422 -> replay.go:204-550) ----------------
        # On restart, replay stored blocks into the app until its height
        # matches the store (the app may have lost state or trail by one).
        if stored is not None:
            from tendermint_tpu.consensus.replay import Handshaker

            handshaker = Handshaker(
                self.state_store, self.block_store, self.block_exec, genesis
            )
            self.sm_state = handshaker.handshake(app_client, self.sm_state)
            self.evidence_pool.set_state(self.sm_state)

        # --- p2p (node.go:206-256) -------------------------------------------
        if transport is None:
            if memory_network is not None:
                transport = memory_network.transport(config.listen_addr)
            else:
                from tendermint_tpu.p2p.mconn import MConnConfig

                transport = TCPTransport(
                    self.node_key,
                    mconn_config=MConnConfig(
                        send_rate=config.p2p_send_rate,
                        recv_rate=config.p2p_recv_rate,
                    ),
                )
                transport.listen(config.listen_addr)
        self.transport = transport
        listen_addr = getattr(transport, "listen_addr", config.listen_addr)
        self.node_info = NodeInfo(
            node_id=self.node_key.node_id,
            network=genesis.chain_id,
            moniker=config.moniker,
            listen_addr=listen_addr,
        )
        self.peer_manager = PeerManager(
            self.node_key.node_id, max_connected=config.max_connections
        )
        self.router = Router(
            self.node_info,
            self.peer_manager,
            transport,
            metrics=p2p_metrics,
            logger=self.logger,
            queue_type=config.p2p_queue_type,
        )

        # --- consensus (node.go:297-325) -------------------------------------
        wal: WAL
        if config.wal_enabled and config.home:
            wal = WAL(os.path.join(config.home, "cs.wal"))
        else:
            wal = NilWAL()
        self.consensus = ConsensusState(
            self.sm_state,
            self.block_exec,
            self.block_store,
            priv_validator=self.priv_validator,
            wal=wal,
            metrics=consensus_metrics,
            logger=self.logger,
            double_sign_check_height=config.double_sign_check_height,
        )
        self.consensus.event_bus = self.event_bus
        self.consensus_reactor = ConsensusReactor(self.consensus, self.router)
        self.mempool_reactor = MempoolReactor(self.mempool, self.router)
        self.evidence_reactor = EvidenceReactor(self.evidence_pool, self.router)

        # --- blocksync (node.go:327-356) -------------------------------------
        self._caught_up_event = threading.Event()
        if config.blocksync:
            self.syncer = BlockSyncer(
                self.sm_state,
                self.block_exec,
                self.block_store,
                transport=None,
                on_caught_up=self._switch_to_consensus,
            )
        else:
            self.syncer = None
        self.blocksync_reactor = BlockSyncReactor(
            self.syncer, self.block_store, self.router
        )
        self.pex_reactor = PexReactor(self.peer_manager, self.router)

        # --- statesync (node.go:358-388) --------------------------------------
        # The reactor always runs (every node serves snapshots/light blocks);
        # the syncer only on fresh nodes with statesync enabled.
        self.statesync_reactor = StateSyncReactor(
            self.router, app_client, self.block_store, self.state_store
        )
        self.statesyncer = None
        self.statesync_error = None
        if (
            config.statesync is not None
            and config.statesync.enabled
            and self.sm_state.last_block_height == 0
        ):
            self.statesyncer = StateSyncer(
                self.statesync_reactor,
                app_client,
                self.state_store,
                self.block_store,
                genesis,
                config.statesync,
            )

        # --- RPC (node.go:512, internal/rpc/core) ----------------------------
        self.rpc_server = None
        if config.rpc_laddr:
            from tendermint_tpu.rpc.core import Environment
            from tendermint_tpu.rpc.server import RPCServer

            host, _, port = config.rpc_laddr.rpartition(":")
            env = Environment(
                node_info=self.node_info,
                genesis=self.genesis,
                block_store=self.block_store,
                state_store=self.state_store,
                consensus=self.consensus,
                mempool=self.mempool,
                evidence_pool=self.evidence_pool,
                app_client=self.app,
                event_bus=self.event_bus,
                indexer=self.indexer,
                peer_manager=self.peer_manager,
                get_state=lambda: self.consensus.state,
                is_syncing=lambda: not self._caught_up_event.is_set(),
                consensus_reactor=self.consensus_reactor,
                router=self.router,
                unsafe=config.rpc_unsafe,
            )
            self.rpc_env = env
            self.rpc_server = RPCServer(
                env.routes(),
                host=host or "127.0.0.1",
                port=int(port),
                metrics_registry=self.metrics_registry,
                event_bus=self.event_bus,
            )
        self._started = False

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """OnStart ordering (node.go:403-519)."""
        self.failed = None
        # Double-sign risk check FIRST (state.go:2663 via OnStart:472):
        # the common restart case must fail the whole node start, not a
        # background sync thread later.
        self.consensus.check_double_signing_risk()
        self.router.start()
        self.pex_reactor.start()
        self.evidence_reactor.start()
        self.mempool_reactor.start()
        self.consensus_reactor.start()
        self.statesync_reactor.start()
        self.blocksync_reactor.start(start_syncer=self.statesyncer is None)
        for peer in self.config.persistent_peers:
            self.peer_manager.add_address(PeerAddress.parse(peer), persistent=True)
        if self.statesyncer is not None:
            threading.Thread(
                target=self._statesync_then_blocksync, daemon=True
            ).start()
        elif self.syncer is None:
            self._switch_to_consensus(self.sm_state)
        else:
            # If there's nothing to sync from within a grace period, start
            # consensus anyway (single node / all peers at same height).
            threading.Thread(
                target=self._blocksync_grace, daemon=True
            ).start()
        if self.rpc_server is not None:
            self.rpc_server.start()
        self._started = True

    def _statesync_then_blocksync(self) -> None:
        """node.go:358-388: snapshot restore, then block sync from the
        restored height, then consensus. Statesync failure degrades to
        plain block sync from genesis."""
        from tendermint_tpu.statesync.syncer import StateSyncFatalError

        try:
            state = self.statesyncer.sync()
            self.event_bus.publish_event_state_sync_status(
                events_mod.EventDataStateSyncStatus(
                    complete=True, height=state.last_block_height
                )
            )
            self.sm_state = state
            self.evidence_pool.set_state(state)
            if self.syncer is not None:
                from tendermint_tpu.blocksync.pool import BlockPool

                self.syncer.state = state
                self.syncer.pool = BlockPool(state.last_block_height + 1)
        except StateSyncFatalError as exc:
            # The app already holds restored state: block-syncing from
            # genesis on top of it would produce wrong app hashes. Halt
            # sync instead of degrading (the reference treats this as
            # fatal at node startup).
            self.statesync_error = exc
            self.event_bus.publish_event_state_sync_status(
                events_mod.EventDataStateSyncStatus(complete=False, height=0)
            )
            import warnings

            warnings.warn(f"state sync failed fatally; node halted: {exc}")
            return
        except Exception as exc:
            # Pre-restore failure (no snapshots, bad anchor, no peers):
            # the app is untouched, so degrading to a full block sync
            # from the current state is sound.
            self.statesync_error = exc
            self.event_bus.publish_event_state_sync_status(
                events_mod.EventDataStateSyncStatus(complete=False, height=0)
            )
            import warnings

            warnings.warn(f"state sync failed; falling back to block sync: {exc}")
        if self.syncer is None:
            self._switch_to_consensus(self.sm_state)
            return
        self.blocksync_reactor.start_syncing()
        self._blocksync_grace()

    def _blocksync_grace(self) -> None:
        deadline = _time.monotonic() + 2.0
        while _time.monotonic() < deadline:
            if self._caught_up_event.is_set():
                return
            if self.syncer.pool.max_peer_height() > self.block_store.height():
                return  # real sync in progress; on_caught_up will fire
            _time.sleep(0.1)
        if not self._caught_up_event.is_set():
            self._switch_to_consensus(self.syncer.state)

    def _switch_to_consensus(self, state) -> None:
        """blocksync reactor.go:507-529 SwitchToConsensus."""
        if self._caught_up_event.is_set():
            return
        self._caught_up_event.set()
        if self.syncer is not None:
            self.syncer.stop()
            state = self.syncer.state  # adopt the synced state
        if (
            self.syncer is not None
            or state.last_block_height > self.consensus.state.last_block_height
        ):
            self.consensus._reconstruct_and_update(state)
        self.event_bus.publish_event_block_sync_status(
            events_mod.EventDataBlockSyncStatus(
                complete=True, height=state.last_block_height
            )
        )
        try:
            self.consensus.start()
        except Exception as exc:
            # Refusals after a sync (our signatures found in blocks we
            # just synced) happen on a background thread; record them so
            # the operator loop can exit instead of running a zombie
            # node that never joins consensus.
            self.failed = exc
            self.logger.error("consensus refused to start", err=str(exc))

    def stop(self) -> None:
        if self.rpc_server is not None:
            try:
                self.rpc_server.stop()
            except Exception:
                pass
        try:
            self.consensus.stop()
        except Exception:
            pass
        for r in (
            self.blocksync_reactor,
            self.statesync_reactor,
            self.consensus_reactor,
            self.mempool_reactor,
            self.evidence_reactor,
            self.pex_reactor,
        ):
            try:
                r.stop()
            except Exception:
                pass
        self.router.stop()
        if self._signer_endpoint is not None:
            try:
                self._signer_endpoint.close()
            except Exception:
                pass
        if self._owned_signer is not None:
            try:
                self._owned_signer.close()
            except Exception:
                pass
        if self.event_sink is not None:
            try:
                self.event_sink.close()
            except Exception:
                pass
        for db in getattr(self, "_dbs", []):
            try:
                db.close()
            except Exception:
                pass
        self._started = False

    def _fire_events(self, block, block_id, fres, validator_updates) -> None:
        """execution.go:600-648 fireEvents: publish NewBlock, header, one
        event per tx, and validator-set updates onto the bus."""
        if self.event_sink is not None:
            self.event_sink.index_finalized_block(
                block.header.height, block.data.txs, fres
            )
        bus = self.event_bus
        bus.publish_event_new_block(
            events_mod.EventDataNewBlock(
                block=block, block_id=block_id, result_finalize_block=fres
            )
        )
        bus.publish_event_new_block_header(
            events_mod.EventDataNewBlockHeader(
                header=block.header, num_txs=len(block.data.txs)
            )
        )
        txs = list(block.data.txs)
        for i, r in enumerate(fres.tx_results):
            if i >= len(txs):
                break
            bus.publish_event_tx(
                events_mod.EventDataTx(
                    height=block.header.height, index=i, tx=txs[i], result=r
                )
            )
        if validator_updates:
            bus.publish_event_validator_set_updates(
                events_mod.EventDataValidatorSetUpdates(
                    validator_updates=list(validator_updates)
                )
            )

    # --- convenience ---------------------------------------------------------

    @property
    def height(self) -> int:
        return self.block_store.height()

    def submit_tx(self, tx: bytes) -> None:
        """Local tx submission: CheckTx + gossip (the RPC broadcast path)."""
        self.mempool_reactor.check_and_broadcast_tx(tx)
