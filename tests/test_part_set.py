"""PartSet and BitArray tests (types/part_set_test.go, libs/bits)."""

import os

import pytest

from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.part_set import Part, PartSet


class TestBitArray:
    def test_set_get(self):
        ba = BitArray(10)
        assert not ba.get_index(3)
        assert ba.set_index(3, True)
        assert ba.get_index(3)
        assert not ba.set_index(10, True)  # out of range
        assert not ba.get_index(10)

    def test_ops(self):
        a = BitArray.from_indices(8, [0, 1, 2])
        b = BitArray.from_indices(8, [2, 3])
        assert a.or_(b).get_true_indices() == [0, 1, 2, 3]
        assert a.and_(b).get_true_indices() == [2]
        assert a.sub(b).get_true_indices() == [0, 1]
        assert a.not_().get_true_indices() == [3, 4, 5, 6, 7]

    def test_full_empty(self):
        ba = BitArray(9)
        assert ba.is_empty() and not ba.is_full()
        for i in range(9):
            ba.set_index(i, True)
        assert ba.is_full()

    def test_pick_random(self):
        ba = BitArray.from_indices(64, [7, 21])
        idx, ok = ba.pick_random()
        assert ok and idx in (7, 21)
        _, ok = BitArray(4).pick_random()
        assert not ok


class TestPartSet:
    def test_from_data_complete(self):
        data = os.urandom(5000)
        ps = PartSet.from_data(data, part_size=1024)
        assert ps.total == 5
        assert ps.is_complete()
        assert ps.get_reader() == data

    def test_incremental_assembly(self):
        data = os.urandom(5000)
        src = PartSet.from_data(data, part_size=1024)
        dst = PartSet(src.header())
        for i in reversed(range(src.total)):
            assert dst.add_part(src.get_part(i))
        assert dst.is_complete()
        assert dst.get_reader() == data

    def test_duplicate_part_ignored(self):
        src = PartSet.from_data(os.urandom(3000), part_size=1024)
        dst = PartSet(src.header())
        assert dst.add_part(src.get_part(0))
        assert not dst.add_part(src.get_part(0))

    def test_bad_proof_rejected(self):
        src = PartSet.from_data(os.urandom(3000), part_size=1024)
        other = PartSet.from_data(os.urandom(3000), part_size=1024)
        dst = PartSet(src.header())
        with pytest.raises(ValueError, match="proof"):
            dst.add_part(other.get_part(0))

    def test_tampered_bytes_rejected(self):
        src = PartSet.from_data(os.urandom(3000), part_size=1024)
        dst = PartSet(src.header())
        p = src.get_part(1)
        bad = Part(index=1, bytes=b"\x00" + p.bytes[1:], proof=p.proof)
        with pytest.raises(ValueError, match="proof"):
            dst.add_part(bad)

    def test_part_proto_roundtrip(self):
        src = PartSet.from_data(os.urandom(3000), part_size=1024)
        p = src.get_part(2)
        back = Part.from_proto_bytes(p.to_proto_bytes())
        assert back.index == p.index and back.bytes == p.bytes
        assert back.proof == p.proof
