"""lightd: the light-client serving tier (PR 9 tentpole).

One lightd fronts a LightClient with a verified-header cache
(light/cache.py) and serves JSON-RPC over the shared selector event
loop, so 10k+ concurrent light clients multiplex onto one loop thread
plus a bounded worker pool. The hot path is:

  light_header(height) -> cache hit  -> memoized result dict (no store,
                                        no encoding, no device work)
                       -> cache miss -> single-flight skipping
                          verification (one scheduler super-batch per
                          bisection round, light/batch.py), then the
                          result + trust path are memoized.

Single-flight: a thundering herd on one cold height does ONE
verification; followers wait on the leader's event and re-read the
cache. On fork evidence (``DivergedHeaderError``) every cached entry
for the chain is invalidated before the error surfaces — a proven
attack poisons all memoized trust paths.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from tendermint_tpu.libs.metrics import LightMetrics
from tendermint_tpu.light.cache import HeaderCache
from tendermint_tpu.light.client import DivergedHeaderError, LightClient
from tendermint_tpu.rpc import encoding as enc
from tendermint_tpu.rpc.server import (
    INTERNAL_ERROR,
    INVALID_PARAMS,
    RPCError,
    RPCServer,
)

# How long a follower waits for the in-flight leader before taking over
# (covers a leader that died without filling the cache).
FOLLOWER_WAIT = 60.0


class LightServer:
    """Route table + lifecycle for one lightd instance."""

    def __init__(
        self,
        client: LightClient,
        host: str = "127.0.0.1",
        port: int = 0,
        cache: Optional[HeaderCache] = None,
        cache_capacity: int = 10_000,
        metrics: Optional[LightMetrics] = None,
        registry=None,
        evloop: Optional[bool] = None,
        evloop_metrics=None,
        workers: Optional[int] = None,
    ):
        self.client = client
        self.metrics = metrics or LightMetrics.nop()
        self.cache = cache or HeaderCache(
            capacity=cache_capacity, metrics=self.metrics
        )
        self._sf_mtx = threading.Lock()
        # height -> Event set by the verification leader when done
        self._inflight: Dict[int, threading.Event] = {}  # guarded-by: _sf_mtx
        self.server = RPCServer(
            self.routes(),
            host=host,
            port=port,
            metrics_registry=registry,
            evloop=evloop,
            evloop_metrics=evloop_metrics,
            workers=workers,
        )

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    @property
    def url(self) -> str:
        return self.server.url

    @property
    def address(self):
        return self.server.address

    # --- routes --------------------------------------------------------------

    def routes(self) -> Dict[str, Callable]:
        return {
            "health": self.health,
            "light_header": self.light_header,
            "light_status": self.light_status,
        }

    def health(self) -> Dict[str, Any]:
        return {}

    def light_header(self, height=None) -> Dict[str, Any]:
        t0 = time.monotonic()
        outcome = "error"
        try:
            result, outcome = self._serve(height)
            return result
        finally:
            self.metrics.serve_latency_seconds.labels(outcome=outcome).observe(
                time.monotonic() - t0
            )

    def light_status(self) -> Dict[str, Any]:
        trusted = self.client.latest_trusted()
        return {
            "chain_id": self.client.chain_id,
            "trusted_height": str(trusted.height) if trusted else "0",
            "num_witnesses": len(self.client.witnesses),
            "cache": self.cache.stats(),
        }

    # --- serving core --------------------------------------------------------

    def _serve(self, height):
        try:
            h = int(height)
        except (TypeError, ValueError):
            raise RPCError(INVALID_PARAMS, "height required")
        if h <= 0:
            raise RPCError(INVALID_PARAMS, "height must be positive")
        chain = self.client.chain_id
        entry = self.cache.get(chain, h)
        if entry is not None:
            return entry.payload, "hit"
        while True:
            with self._sf_mtx:
                evt = self._inflight.get(h)
                leader = evt is None
                if leader:
                    evt = threading.Event()
                    self._inflight[h] = evt
            if leader:
                break
            # Follower: wait out the leader, then re-read the cache. If
            # the leader failed (nothing cached), loop and become the
            # next leader — the error should reproduce for us too.
            evt.wait(FOLLOWER_WAIT)
            entry = self.cache.get(chain, h)
            if entry is not None:
                return entry.payload, "hit"
        try:
            entry = self._verify_and_fill(chain, h)
            return entry.payload, "miss"
        finally:
            with self._sf_mtx:
                self._inflight.pop(h, None)
            evt.set()

    def _verify_and_fill(self, chain: str, h: int):
        store = self.client.store
        before = set(store.heights())
        try:
            lb = self.client.verify_light_block_at_height(h)
        except DivergedHeaderError as e:
            dropped = self.cache.invalidate_chain(chain)
            raise RPCError(
                INTERNAL_ERROR,
                f"light client attack detected: {e}",
                data=f"invalidated {dropped} cached headers",
            )
        except RPCError:
            raise
        except Exception as e:
            raise RPCError(INVALID_PARAMS, f"light verification failed: {e}")
        # Memoized trust path: the pivots this verification persisted,
        # plus the target itself (already-trusted anchors stay implicit).
        path = sorted((set(store.heights()) - before) | {h})
        payload = {
            "header": enc.header_json(lb.header),
            "commit": enc.commit_json(lb.signed_header.commit),
            "hash": enc.hex_bytes(lb.hash()),
            "height": str(lb.height),
            "trust_path": [str(p) for p in path],
        }
        return self.cache.put(chain, lb, trust_path=tuple(path),
                              payload=payload)
