"""Router: connects transports, the peer manager, and reactor channels.

Mirrors internal/p2p/router.go:142-976: reactors open Channels
(send/receive queue pairs per channel id); the router runs accept and
dial loops, spawns per-peer send/receive threads, and routes Envelopes
between channel queues and peer connections. Broadcast envelopes fan out
to every connected peer.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional

from tendermint_tpu.p2p.conn_tracker import ConnTracker
from tendermint_tpu.p2p.key import NodeID
from tendermint_tpu.p2p.peermanager import PeerAddress, PeerManager
from tendermint_tpu.p2p.transport import (
    Connection,
    ConnectionClosed,
    NodeInfo,
    Transport,
)


@dataclass
class Envelope:
    """internal/p2p/channel.go Envelope."""

    channel_id: int
    message: bytes
    from_peer: NodeID = ""
    to_peer: NodeID = ""  # empty + broadcast=False is invalid for sends
    broadcast: bool = False


class Channel:
    """A reactor's handle: send envelopes out, iterate received ones."""

    def __init__(self, channel_id: int, router: "Router"):
        self.channel_id = channel_id
        self._router = router
        self.in_queue: "queue.Queue[Envelope]" = queue.Queue(maxsize=10000)

    def send(self, env: Envelope) -> None:
        env.channel_id = self.channel_id
        self._router._route_out(env)

    def broadcast(self, message: bytes) -> None:
        self.send(Envelope(self.channel_id, message, broadcast=True))

    def receive(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        try:
            return self.in_queue.get(timeout=timeout)
        except queue.Empty:
            return None


class Router:
    def __init__(
        self,
        node_info: NodeInfo,
        peer_manager: PeerManager,
        transport: Transport,
        metrics=None,
        logger=None,
        max_incoming_per_ip: int = 16,
        queue_type: str = "fifo",
        channel_priorities=None,
    ):
        from tendermint_tpu.libs.log import NOP_LOGGER
        from tendermint_tpu.libs.metrics import P2PMetrics
        from tendermint_tpu.p2p.pqueue import QUEUE_TYPES

        if queue_type not in QUEUE_TYPES:
            raise ValueError(
                f"unknown p2p queue type {queue_type!r} (one of {QUEUE_TYPES})"
            )
        # Per-peer send-queue discipline (router.go:216-238): fifo,
        # priority (WDRR), or simple-priority.
        self.queue_type = queue_type
        self.channel_priorities = channel_priorities
        self.node_info = node_info
        self.peer_manager = peer_manager
        self.transport = transport
        self.metrics = metrics or P2PMetrics.nop()
        self.logger = (logger or NOP_LOGGER).with_fields(module="p2p")
        self._channels: Dict[int, Channel] = {}
        self._peer_conns: Dict[NodeID, Connection] = {}
        self._peer_send_queues: Dict[NodeID, "queue.Queue"] = {}
        self._peer_ips: Dict[NodeID, str] = {}
        self._conn_tracker = ConnTracker(max_per_ip=max_incoming_per_ip)
        self._mtx = threading.RLock()
        self._stop_flag = threading.Event()
        self._threads: List[threading.Thread] = []

    # --- channels ------------------------------------------------------------

    def open_channel(self, channel_id: int) -> Channel:
        """router.go OpenChannel."""
        with self._mtx:
            if channel_id in self._channels:
                raise ValueError(f"channel {channel_id} already open")
            ch = Channel(channel_id, self)
            self._channels[channel_id] = ch
            if channel_id not in self.node_info.channels:
                self.node_info.channels.append(channel_id)
            return ch

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._stop_flag.clear()
        self._spawn(self._accept_loop, "router-accept")
        self._spawn(self._dial_loop, "router-dial")

    def stop(self) -> None:
        self._stop_flag.set()
        self.transport.close()
        with self._mtx:
            for conn in self._peer_conns.values():
                conn.close()
            self._peer_conns.clear()
            self._peer_send_queues.clear()
            # release per-IP reservations: threads that exit on the stop
            # flag never reach _disconnect, and stale counts would reject
            # legitimate inbound after a restart
            for ip in self._peer_ips.values():
                self._conn_tracker.remove(ip)
            self._peer_ips.clear()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()

    def _spawn(self, fn, name: str, *args) -> None:
        t = threading.Thread(target=fn, args=args, name=name, daemon=True)
        t.start()
        self._threads.append(t)

    # --- accept / dial loops --------------------------------------------------

    def _accept_loop(self) -> None:
        """router.go acceptPeers:444 (+ conn_tracker.go per-IP limit)."""
        while not self._stop_flag.is_set():
            try:
                conn = self.transport.accept(timeout=0.2)
            except (TimeoutError, OSError, queue.Empty):
                continue
            except Exception:
                if self._stop_flag.is_set():
                    return
                continue
            if self._quarantined():
                try:
                    conn.close()
                except Exception:
                    pass
                continue
            ip = getattr(conn, "remote_ip", None)
            if ip is not None and not self._conn_tracker.add(ip):
                self.logger.info("inbound rejected: per-IP limit", ip=ip)
                try:
                    conn.close()
                except Exception:
                    pass
                continue
            self._spawn(self._handshake_peer, "router-handshake", conn, None)

    def _dial_loop(self) -> None:
        """router.go dialPeers:528."""
        while not self._stop_flag.is_set():
            if self._quarantined():
                self._stop_flag.wait(0.2)
                continue
            address = self.peer_manager.dial_next()
            if address is None:
                self._stop_flag.wait(0.1)
                continue
            try:
                conn = self.transport.dial(address.addr)
            except Exception:
                self.peer_manager.dial_failed(address)
                continue
            self._spawn(self._handshake_peer, "router-handshake", conn, address)

    def _handshake_peer(
        self, conn: Connection, dialed: Optional[PeerAddress]
    ) -> None:
        try:
            peer_info = conn.handshake(self.node_info)
            self.node_info.compatible_with(peer_info)
            if dialed is not None and peer_info.node_id != dialed.node_id:
                raise ValueError(
                    f"expected to dial {dialed.node_id}, got {peer_info.node_id}"
                )
            if dialed is not None:
                self.peer_manager.dialed(dialed)
            else:
                self.peer_manager.accepted(peer_info.node_id)
            # Record the peer's advertised listen address so PEX can hand
            # it to other peers (the reference learns this from NodeInfo
            # during the handshake too).
            if peer_info.listen_addr:
                self.peer_manager.add_address(
                    PeerAddress(peer_info.node_id, peer_info.listen_addr)
                )
        except Exception:
            if dialed is not None:
                self.peer_manager.dial_failed(dialed)
            else:
                ip = getattr(conn, "remote_ip", None)
                if ip is not None:
                    self._conn_tracker.remove(ip)
            conn.close()
            return
        if self._quarantined():
            # disconnect_all fired while this handshake was in flight: a
            # peer must not install itself during the quarantine.
            ip = getattr(conn, "remote_ip", None)
            if dialed is None and ip is not None:
                self._conn_tracker.remove(ip)
            conn.close()
            return
        peer_id = peer_info.node_id
        from tendermint_tpu.p2p.pqueue import make_send_queue

        send_q = make_send_queue(
            self.queue_type, 10000, self.channel_priorities
        )
        with self._mtx:
            old = self._peer_conns.pop(peer_id, None)
            old_ip = self._peer_ips.pop(peer_id, None)
            if old is not None:
                old.close()
            if old_ip is not None:
                self._conn_tracker.remove(old_ip)
            self._peer_conns[peer_id] = conn
            self._peer_send_queues[peer_id] = send_q
            if dialed is None:
                ip = getattr(conn, "remote_ip", None)
                if ip is not None:
                    self._peer_ips[peer_id] = ip
        self._spawn(self._send_peer, f"router-send-{peer_id[:8]}", peer_id, conn, send_q)
        self._spawn(self._receive_peer, f"router-recv-{peer_id[:8]}", peer_id, conn)
        self.peer_manager.ready(peer_id)
        with self._mtx:
            self.metrics.peers.set(len(self._peer_conns))
        self.logger.info("peer connected", peer=peer_id[:16])

    # --- per-peer routines ----------------------------------------------------

    def _send_peer(self, peer_id: NodeID, conn: Connection, send_q) -> None:
        """router.go sendPeer:843."""
        while not self._stop_flag.is_set():
            env = send_q.get(timeout=0.2)
            if env is None:
                if send_q.closed:
                    return
                continue  # timeout: poll the stop flag
            try:
                conn.send(env.channel_id, env.message)
                self.metrics.message_send_bytes_total.labels(
                    chID=str(env.channel_id)
                ).inc(len(env.message))
            except Exception:
                self._disconnect(peer_id, expected_conn=conn)
                return

    def _receive_peer(self, peer_id: NodeID, conn: Connection) -> None:
        """router.go receivePeer:791."""
        while not self._stop_flag.is_set():
            try:
                channel_id, msg = conn.receive()
            except (ConnectionClosed, Exception):
                self._disconnect(peer_id, expected_conn=conn)
                return
            self.metrics.message_receive_bytes_total.labels(
                chID=str(channel_id)
            ).inc(len(msg))
            ch = self._channels.get(channel_id)
            if ch is None:
                continue  # unknown channel: drop (router logs in reference)
            try:
                ch.in_queue.put_nowait(
                    Envelope(channel_id, msg, from_peer=peer_id)
                )
            except queue.Full:
                pass  # backpressure: drop (priority queues in reference)

    def _disconnect(
        self, peer_id: NodeID, expected_conn: Optional[Connection] = None
    ) -> None:
        """Evict peer_id's connection. When ``expected_conn`` is given,
        only evict if it is still the installed one — a send/recv thread
        of an OLD connection must not tear down the replacement a
        reconnect just installed."""
        with self._mtx:
            current = self._peer_conns.get(peer_id)
            if expected_conn is not None and current is not expected_conn:
                return
            conn = self._peer_conns.pop(peer_id, None)
            sq = self._peer_send_queues.pop(peer_id, None)
            ip = self._peer_ips.pop(peer_id, None)
            self.metrics.peers.set(len(self._peer_conns))
        if ip is not None:
            self._conn_tracker.remove(ip)
        if conn is not None:
            self.logger.info("peer disconnected", peer=peer_id[:16])
            conn.close()
            if sq is not None:
                sq.close()
            self.peer_manager.disconnected(peer_id)

    # --- routing --------------------------------------------------------------

    def _route_out(self, env: Envelope) -> None:
        """router.go routeChannel:301. The queue discipline decides what
        a full queue drops (pqueue.py); drops are silent here, as in the
        reference."""
        if env.broadcast:
            with self._mtx:
                targets = list(self._peer_send_queues.items())
            for peer_id, sq in targets:
                sq.put(Envelope(env.channel_id, env.message, to_peer=peer_id))
        else:
            with self._mtx:
                sq = self._peer_send_queues.get(env.to_peer)
            if sq is not None:
                sq.put(env)

    def connected_peers(self) -> List[NodeID]:
        with self._mtx:
            return list(self._peer_conns.keys())

    def disconnect_all(self, duration: float = 5.0) -> int:
        """Drop every peer connection and refuse dial/accept for
        ``duration`` seconds — the process-level analog of the e2e
        runner's docker-network `disconnect` perturbation
        (test/e2e/runner/perturb.go:42-72). Returns the number of peers
        dropped; reconnection happens through the normal persistent-peer
        retry path once the quarantine lapses."""
        import time as _t

        with self._mtx:
            peers = list(self._peer_conns.keys())
            self._quarantine_until = _t.monotonic() + duration
        for peer_id in peers:
            self._disconnect(peer_id)
        return len(peers)

    def _quarantined(self) -> bool:
        import time as _t

        return _t.monotonic() < getattr(self, "_quarantine_until", 0.0)
