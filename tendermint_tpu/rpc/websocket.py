"""JSON-RPC over WebSocket: /websocket endpoint with event subscriptions.

The reference serves subscribe/unsubscribe/unsubscribe_all exclusively
over websocket (internal/rpc/core/routes.go:31-34, rpc/jsonrpc/server
websocket handler); this is a from-scratch RFC 6455 server endpoint
grafted onto the stdlib HTTP server the RPC layer already runs:

- handshake: Sec-WebSocket-Accept = b64(SHA1(key + GUID)), 101 upgrade
- frames: client-masked text/ping/close handled; server replies unmasked
- JSON-RPC: every request on the socket goes through the normal route
  table, PLUS the three websocket-only methods backed by the event bus.

Event delivery matches the reference contract: each match is pushed as a
JSON-RPC response whose id is the original subscribe request id and
whose result carries {query, data: {type, value}, events}.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading
from typing import Any, Dict, Optional

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BIN = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

MAX_WS_FRAME = 16 << 20


class WSClosed(Exception):
    pass


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def is_upgrade_request(headers) -> bool:
    return (
        headers.get("Upgrade", "").lower() == "websocket"
        and "upgrade" in headers.get("Connection", "").lower()
        and headers.get("Sec-WebSocket-Key") is not None
    )


class WSConn:
    """One upgraded connection: framed send/recv over the raw socket."""

    def __init__(self, rfile, wfile):
        self._rfile = rfile
        self._wfile = wfile
        self._send_lock = threading.Lock()
        self.closed = threading.Event()

    # --- frame IO -----------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._rfile.read(n - len(buf))
            if not chunk:
                raise WSClosed("connection closed")
            buf += chunk
        return buf

    def recv_message(self) -> Optional[str]:
        """Next text message; None when the peer closes. Handles ping,
        pong, fragmentation, and masking (clients MUST mask: RFC 6455
        §5.1)."""
        fragments = []
        while True:
            hdr = self._read_exact(2)
            fin = bool(hdr[0] & 0x80)
            opcode = hdr[0] & 0x0F
            masked = bool(hdr[1] & 0x80)
            length = hdr[1] & 0x7F
            if length == 126:
                (length,) = struct.unpack(">H", self._read_exact(2))
            elif length == 127:
                (length,) = struct.unpack(">Q", self._read_exact(8))
            if length > MAX_WS_FRAME:
                raise WSClosed("frame too large")
            mask = self._read_exact(4) if masked else b""
            payload = self._read_exact(length)
            if masked:
                payload = bytes(
                    b ^ mask[i % 4] for i, b in enumerate(payload)
                )
            if opcode == OP_CLOSE:
                try:
                    self._send_frame(OP_CLOSE, payload[:2])
                except Exception:
                    pass
                return None
            if opcode == OP_PING:
                self._send_frame(OP_PONG, payload)
                continue
            if opcode == OP_PONG:
                continue
            if opcode in (OP_TEXT, OP_BIN, OP_CONT):
                fragments.append(payload)
                # the per-frame cap must also bound the reassembled
                # message, or endless continuations grow without limit
                if sum(len(f) for f in fragments) > MAX_WS_FRAME:
                    raise WSClosed("message too large")
                if fin:
                    return b"".join(fragments).decode("utf-8", "replace")
                continue
            raise WSClosed(f"unsupported opcode {opcode}")

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        hdr = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            hdr.append(n)
        elif n < 1 << 16:
            hdr.append(126)
            hdr += struct.pack(">H", n)
        else:
            hdr.append(127)
            hdr += struct.pack(">Q", n)
        with self._send_lock:
            self._wfile.write(bytes(hdr) + payload)
            self._wfile.flush()

    def send_json(self, doc: Dict[str, Any]) -> None:
        self._send_frame(
            OP_TEXT, json.dumps(doc, separators=(",", ":")).encode()
        )

    def close(self) -> None:
        if not self.closed.is_set():
            self.closed.set()
            try:
                self._send_frame(OP_CLOSE, b"")
            except Exception:
                pass


class WSSession:
    """JSON-RPC dispatch + subscription pump for one websocket client
    (rpc/jsonrpc/server ws handler + internal/rpc/core/events.go)."""

    _ids = threading.Lock()
    _next_id = [0]

    def __init__(self, conn: WSConn, routes: Dict[str, Any], event_bus):
        self.conn = conn
        self.routes = routes
        self.event_bus = event_bus
        with self._ids:
            self._next_id[0] += 1
            self.subscriber = f"ws-{self._next_id[0]}"

    # --- main loop ----------------------------------------------------------

    def run(self) -> None:
        try:
            while True:
                raw = self.conn.recv_message()
                if raw is None:
                    return
                try:
                    req = json.loads(raw)
                except json.JSONDecodeError:
                    self.conn.send_json(
                        _err(None, -32700, "parse error")
                    )
                    continue
                self._dispatch(req)
        except WSClosed:
            pass
        finally:
            self._teardown()

    def _teardown(self) -> None:
        if self.event_bus is not None:
            try:
                self.event_bus.unsubscribe_all(self.subscriber)
            except Exception:
                pass
        self.conn.close()

    # --- dispatch -----------------------------------------------------------

    def _dispatch(self, req: Dict[str, Any]) -> None:
        rid = req.get("id")
        method = req.get("method")
        params = req.get("params") or {}
        if not isinstance(params, dict):
            self.conn.send_json(_err(rid, -32602, "params must be a map"))
            return
        try:
            if method in ("subscribe", "unsubscribe", "unsubscribe_all"):
                if self.event_bus is None:
                    self.conn.send_json(
                        _err(rid, -32603, "event bus not configured")
                    )
                    return
                if method == "subscribe":
                    self._subscribe(rid, params)
                elif method == "unsubscribe":
                    query = params.get("query", "")
                    self.event_bus.unsubscribe(self.subscriber, query)
                    self.conn.send_json(_ok(rid, {}))
                else:
                    self.event_bus.unsubscribe_all(self.subscriber)
                    self.conn.send_json(_ok(rid, {}))
            elif method in self.routes:
                result = self.routes[method](**params)
                self.conn.send_json(_ok(rid, result))
            else:
                self.conn.send_json(
                    _err(rid, -32601, f"method not found: {method}")
                )
        except WSClosed:
            raise
        except Exception as e:
            code = getattr(e, "code", -32603)
            self.conn.send_json(_err(rid, code, str(e)))

    def _subscribe(self, rid, params: Dict[str, Any]) -> None:
        query = params.get("query", "")
        if not query:
            self.conn.send_json(_err(rid, -32602, "query required"))
            return
        sub = self.event_bus.subscribe(self.subscriber, query, capacity=256)
        self.conn.send_json(_ok(rid, {}))

        def pump():
            from tendermint_tpu.rpc.core import _event_data_json

            while not self.conn.closed.is_set() and not sub.cancelled.is_set():
                msg = sub.next(timeout=0.5)
                if msg is None:
                    continue
                data = _event_data_json(msg.data)
                try:
                    self.conn.send_json(
                        _ok(
                            rid,
                            {
                                "query": query,
                                "data": data,
                                "events": _events_json(msg.events),
                            },
                        )
                    )
                except Exception:
                    self.conn.close()
                    return

        threading.Thread(
            target=pump, name=f"{self.subscriber}-pump", daemon=True
        ).start()


def _events_json(events) -> Dict[str, list]:
    out: Dict[str, list] = {}
    try:
        for key, values in events.items():
            out[key] = [str(v) for v in values]
    except AttributeError:
        pass
    return out


def _ok(rid, result) -> Dict[str, Any]:
    return {"jsonrpc": "2.0", "id": rid, "result": result}


def _err(rid, code: int, message: str) -> Dict[str, Any]:
    return {
        "jsonrpc": "2.0",
        "id": rid,
        "error": {"code": code, "message": message, "data": ""},
    }
