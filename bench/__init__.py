"""Relay-resilient benchmark harness (ISSUE 6).

``bench.py`` at the repo root is the CLI entry point; this package is
the implementation:

- ``sections``  — the section registry + measurement bodies
- ``runner``    — per-section subprocess orchestration, watchdog,
                  retry/degradation ladder, resume, merged output
- ``heartbeat`` — child progress spool + parent watchdog
- ``results``   — partial-result JSON, per-section status, merging
- ``child``     — the per-section child / backend-probe entry points
- ``workload``  — shared signature/header fixtures
"""
