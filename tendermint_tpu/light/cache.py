"""Verified-header cache with trust-path memoization (lightd tier).

The serving tier (light/lightd.py) fronts a LightClient whose skipping
verification costs scheduler super-batches. Once a height is verified
the proof never changes (headers are immutable), so lightd memoizes the
result: the verified LightBlock, the bisection trust path that proved
it, and the pre-built JSON-RPC result dict. A warm request is a pure
dict lookup — no store round-trip, no re-encoding, no device work.

Invalidation: on fork evidence (``DivergedHeaderError``) the whole
chain's entries are dropped — a proven attack means every memoized
trust path anchored in that chain is suspect. Eviction is plain LRU
with a bounded capacity; both paths count into
``tendermint_light_cache_evictions_total``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from tendermint_tpu.libs.metrics import LightMetrics

DEFAULT_CAPACITY = 10_000


class CacheEntry:
    """One verified height: the block, the memoized proof, the payload."""

    __slots__ = ("chain_id", "height", "header_hash", "block", "trust_path",
                 "payload")

    def __init__(self, chain_id: str, height: int, header_hash: bytes,
                 block, trust_path: Tuple[int, ...] = (), payload=None):
        self.chain_id = chain_id
        self.height = height
        self.header_hash = header_hash
        self.block = block
        # Heights of the pivots (ending at `height`) whose verification
        # proved this entry — the memoized skipping trust path.
        self.trust_path = tuple(trust_path)
        # Pre-built JSON-RPC result dict, served verbatim on a hit.
        self.payload = payload


class HeaderCache:
    """Bounded LRU over (chain_id, height) -> CacheEntry.

    ``get`` optionally pins the header hash so a caller holding an
    expected hash (e.g. a follower replicating another lightd) can never
    be served a stale entry after an invalidate/re-verify cycle.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 metrics: Optional[LightMetrics] = None):
        if capacity <= 0:
            raise ValueError("cache capacity must be positive")
        self.capacity = capacity
        self.metrics = metrics or LightMetrics.nop()
        self._mtx = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, int], CacheEntry]" = (
            OrderedDict()
        )  # guarded-by: _mtx
        self.hits = 0  # guarded-by: _mtx
        self.misses = 0  # guarded-by: _mtx
        self.evictions = 0  # guarded-by: _mtx

    def get(self, chain_id: str, height: int,
            header_hash: Optional[bytes] = None) -> Optional[CacheEntry]:
        key = (chain_id, height)
        with self._mtx:
            entry = self._entries.get(key)
            if entry is not None and (
                header_hash is None or entry.header_hash == header_hash
            ):
                self._entries.move_to_end(key)
                self.hits += 1
                hit = entry
            else:
                self.misses += 1
                hit = None
        if hit is None:
            self.metrics.cache_misses.inc()
        else:
            self.metrics.cache_hits.inc()
        return hit

    def put(self, chain_id: str, block, trust_path: Tuple[int, ...] = (),
            payload=None) -> CacheEntry:
        entry = CacheEntry(
            chain_id, block.height, block.hash(), block, trust_path, payload
        )
        key = (chain_id, block.height)
        evicted = 0
        with self._mtx:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
            self.evictions += evicted
        if evicted:
            self.metrics.cache_evictions.inc(evicted)
        return entry

    def invalidate_chain(self, chain_id: str) -> int:
        """Drop every entry for `chain_id` (fork evidence: the memoized
        trust paths can no longer be trusted). Returns the count."""
        with self._mtx:
            doomed = [k for k in self._entries if k[0] == chain_id]
            for k in doomed:
                del self._entries[k]
            self.evictions += len(doomed)
        if doomed:
            self.metrics.cache_evictions.inc(len(doomed))
        return len(doomed)

    def invalidate(self, chain_id: str, height: int) -> bool:
        with self._mtx:
            gone = self._entries.pop((chain_id, height), None) is not None
            if gone:
                self.evictions += 1
        if gone:
            self.metrics.cache_evictions.inc()
        return gone

    def __len__(self) -> int:
        with self._mtx:
            return len(self._entries)

    def stats(self) -> dict:
        with self._mtx:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0,
            }
