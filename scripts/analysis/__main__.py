"""CLI for tpulint: ``python -m scripts.analysis [paths...]``.

Default target is the ``tendermint_tpu`` package. Findings print as
``path:line: CODE message``; exit status is 0 when every finding is
covered by the baseline (and the baseline has no stale entries), 1
otherwise. ``--update-baseline`` rewrites the baseline to the current
finding set — use it only to grandfather debt you are explicitly
choosing not to fix in this change.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from scripts.analysis import checker_registry
from scripts.analysis.core import (
    DEFAULT_BASELINE,
    REPO_ROOT,
    Runner,
    diff_baseline,
    load_baseline,
    load_modules,
    write_baseline,
)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m scripts.analysis",
        description="tpulint: project-specific static analysis",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories to check (default: tendermint_tpu/)",
    )
    p.add_argument(
        "--list-checkers",
        action="store_true",
        help="print the checker and code catalogue, then exit",
    )
    p.add_argument(
        "--enable",
        action="append",
        default=[],
        metavar="NAME",
        help="run only these checkers (repeatable)",
    )
    p.add_argument(
        "--disable",
        action="append",
        default=[],
        metavar="NAME",
        help="skip these checkers (repeatable)",
    )
    p.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file (default: scripts/analysis/baseline.txt)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to the current finding set",
    )
    return p


def main(argv: List[str] = None) -> int:
    args = _build_parser().parse_args(argv)
    registry = checker_registry()

    if args.list_checkers:
        for name, cls in registry.items():
            print(f"{name}:")
            for code, desc in sorted(cls.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    for name in args.enable + args.disable:
        if name not in registry:
            print(
                f"tpulint: unknown checker {name!r} "
                f"(known: {', '.join(sorted(registry))})",
                file=sys.stderr,
            )
            return 2
    enabled = list(args.enable) or list(registry)
    enabled = [n for n in enabled if n not in set(args.disable)]
    checkers = [registry[n]() for n in enabled]

    roots = args.paths or [os.path.join(REPO_ROOT, "tendermint_tpu")]
    modules = load_modules(roots)
    findings = Runner(checkers).run(modules)

    if args.update_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"tpulint: baseline updated with {len(findings)} finding(s) "
            f"-> {os.path.relpath(args.baseline, REPO_ROOT)}"
        )
        return 0

    if args.no_baseline:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"tpulint: {n} finding(s), baseline ignored")
        return 1 if findings else 0

    pruned: List[str] = []
    baseline = load_baseline(args.baseline, pruned=pruned)
    for key in pruned:
        print(f"tpulint: note: pruned baseline entry for deleted file: {key}")
    new, stale = diff_baseline(findings, baseline)
    for f in new:
        print(f.render())
    rc = 0
    if new:
        print(f"tpulint: {len(new)} new finding(s) not in baseline")
        rc = 1
    if stale:
        for key in stale:
            print(f"tpulint: stale baseline entry (fixed? remove it): {key}")
        rc = 1
    if rc == 0:
        grandfathered = len(findings)
        print(
            f"tpulint: ok ({len(modules)} files, "
            f"{grandfathered} grandfathered finding(s) in baseline)"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
