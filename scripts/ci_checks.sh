#!/usr/bin/env bash
# Repo CI gate: byte-compile, static analysis, sanitizer-enabled
# concurrency tests, metrics audit, tier-1 tests.
#
# The tier-1 line is the ROADMAP.md "Tier-1 verify" command verbatim —
# keep the two in sync. DOTS_PASSED is the per-test pass count the
# driver compares against the seed.
set -u

rc_total=0

echo "== compileall =="
python -m compileall -q tendermint_tpu tests scripts bench.py || rc_total=1

echo "== analysis (tpulint) =="
# project-specific static analysis: lock discipline, JAX purity,
# wire compat, hygiene, metrics. New findings (not in the committed
# baseline) fail the gate.
python -m scripts.analysis || rc_total=1

echo "== sanitizer-enabled concurrency tests =="
# the lock-order sanitizer records the acquisition-order graph while
# the concurrency-heavy modules run their tests; an AB/BA inversion
# prints a LOCK-ORDER CYCLE marker even when no run deadlocks.
rm -f /tmp/_sanitize.log
timeout -k 10 600 env TENDERMINT_TPU_SANITIZE=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/test_scheduler.py tests/test_verifyd.py \
    tests/test_device_policy.py -q -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_sanitize.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "LOCK-ORDER CYCLE" /tmp/_sanitize.log; then
    echo "sanitizer: lock-order cycle detected (potential deadlock)" >&2
    rc_total=1
fi
# IO-UNDER-LOCK lines in the log are report-only: the grpc client
# deliberately holds its connection mutex across a unary call.

echo "== check_metrics =="
python scripts/check_metrics.py || rc_total=1

echo "== tier-1 pytest =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && rc_total=1

exit $rc_total
