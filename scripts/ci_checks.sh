#!/usr/bin/env bash
# Repo CI gate: byte-compile, static analysis, sanitizer-enabled
# concurrency tests, metrics audit, tier-1 tests.
#
# The tier-1 line is the ROADMAP.md "Tier-1 verify" command verbatim —
# keep the two in sync. DOTS_PASSED is the per-test pass count the
# driver compares against the seed.
set -u

rc_total=0

echo "== compileall =="
python -m compileall -q tendermint_tpu tests scripts bench bench.py || rc_total=1

echo "== analysis (tpulint) =="
# project-specific static analysis: lock discipline, JAX purity,
# wire compat, hygiene, metrics. New findings (not in the committed
# baseline) fail the gate.
python -m scripts.analysis || rc_total=1

echo "== tpuflow: taint analysis + deterministic wire fuzz =="
# The TPT family rides the tpulint run above against the committed
# baseline; this stage additionally requires the taint family to be
# clean WITHOUT the baseline — no TPT finding is ever grandfathered,
# every wire-tainted bound must carry a real guard (or an audited
# `# tpuflow: sanitized=` annotation).
python -m scripts.analysis --no-baseline --enable taint || {
    echo "tpuflow: unbaselined TPT findings (see above)" >&2
    rc_total=1
}
# The runtime half: 10 fixed seeds of structured mutations over the
# checked-in corpus, all four decode surfaces. Any hang, uncaught
# struct.error/IndexError/MemoryError, or silent wrong decode fails
# the stage; the failing seed replays byte-identically.
for seed in 0 1 2 3 4 5 6 7 8 9; do
    timeout -k 10 60 env JAX_PLATFORMS=cpu \
        python tests/fuzz_wire.py --seed $seed --smoke || {
        echo "tpuflow fuzz: FAILED under seed $seed — replay with" \
             "python tests/fuzz_wire.py --seed $seed" >&2
        rc_total=1
    }
done

echo "== sanitizer-enabled concurrency tests =="
# the lock-order sanitizer records the acquisition-order graph while
# the concurrency-heavy modules run their tests; an AB/BA inversion
# prints a LOCK-ORDER CYCLE marker even when no run deadlocks.
rm -f /tmp/_sanitize.log
timeout -k 10 600 env TENDERMINT_TPU_SANITIZE=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/test_scheduler.py tests/test_verifyd.py \
    tests/test_device_policy.py -q -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_sanitize.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "LOCK-ORDER CYCLE" /tmp/_sanitize.log; then
    echo "sanitizer: lock-order cycle detected (potential deadlock)" >&2
    rc_total=1
fi
# IO-UNDER-LOCK lines in the log are report-only: the grpc client
# deliberately holds its connection mutex across a unary call.

echo "== check_metrics =="
python scripts/check_metrics.py || rc_total=1

echo "== mesh engine tests (virtual 8-device mesh) =="
# The sharded verify engine (parallel/mesh + parallel/sharding) under
# the same virtual 8-mesh tests/conftest.py forces; run as its own
# stage so a mesh regression is visible even when tier-1 passes.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/test_mesh.py tests/test_parallel.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc_total=1

echo "== bench smoke (multichip scaling section) =="
# The multichip section must produce its scaling curve on the virtual
# mesh and land status=ok in both the merged and partial JSON. Tiny
# lanes/rounds keep the stage inside the wall budget; the DEFAULT
# heartbeat window stays (sharded compiles legitimately exceed 5s).
rm -rf /tmp/_bench_mesh && mkdir -p /tmp/_bench_mesh
timeout -k 10 420 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=multichip BENCH_MULTICHIP_LANES=512 \
    BENCH_MULTICHIP_DEVICES=1,2 BENCH_MULTICHIP_ROUNDS=1 \
    BENCH_SECTION_TIMEOUT=360 BENCH_SECTION_ATTEMPTS=1 \
    BENCH_PARTIAL=/tmp/_bench_mesh/partial.json \
    python bench.py > /tmp/_bench_mesh/out.json 2>/tmp/_bench_mesh/err.log
if [ "$?" -ne 0 ]; then
    echo "bench multichip smoke: non-zero rc" >&2
    tail -5 /tmp/_bench_mesh/err.log >&2
    rc_total=1
fi
python - <<'EOF' || rc_total=1
import json
merged = json.load(open("/tmp/_bench_mesh/out.json"))
assert merged["sections"]["multichip"]["status"] == "ok", merged["sections"]
mc = merged["multichip"]
assert mc["ok"] is True, mc
assert set(mc["sigs_per_s"]) == {"1", "2"}, mc
partial = json.load(open("/tmp/_bench_mesh/partial.json"))
assert partial["sections"]["multichip"]["status"] == "ok", partial["sections"]
print("bench multichip smoke ok: %s" % mc["sigs_per_s"])
EOF

echo "== bench smoke (section runner vs a hanging section) =="
# The relay-resilience contract (ISSUE 6): one deliberately-hanging
# section must NOT zero the round. Tiny no-jax sections keep this
# stage fast; the injected hang must die by heartbeat watchdog (well
# under the 60s wall budget), the run must not end in a whole-run
# rc=124, and the partial JSON must carry the healthy section's number.
rm -rf /tmp/_bench_smoke && mkdir -p /tmp/_bench_smoke
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=host_ref,_chaos BENCH_CHAOS=hang \
    BENCH_HEARTBEAT_TIMEOUT=5 BENCH_SECTION_TIMEOUT=60 \
    BENCH_SECTION_ATTEMPTS=1 BENCH_HOST_REF_SIGS=4 \
    BENCH_PARTIAL=/tmp/_bench_smoke/partial.json \
    BENCH_PROBE_LOG=/tmp/_bench_smoke/probe.md \
    TENDERMINT_TPU_FLIGHTREC_DIR=/tmp/_bench_smoke/flightrec \
    python bench.py > /tmp/_bench_smoke/out.json 2>/tmp/_bench_smoke/err.log
bench_rc=$?
if [ "$bench_rc" -eq 124 ]; then
    echo "bench smoke: whole-run timeout (rc=124) — section isolation broken" >&2
    rc_total=1
elif [ "$bench_rc" -ne 3 ]; then
    # 3 = partial evidence (healthy sections ok, the injected hang honest)
    echo "bench smoke: expected partial-evidence rc=3, got rc=$bench_rc" >&2
    tail -5 /tmp/_bench_smoke/err.log >&2
    rc_total=1
fi
python - <<'EOF' || rc_total=1
import json
merged = json.load(open("/tmp/_bench_smoke/out.json"))
secs = merged["sections"]
assert secs["host_ref"]["status"] == "ok", secs
assert merged["host_ref"]["sigs_per_s"] > 0, merged
assert secs["_chaos"]["status"] == "timeout", secs
assert "heartbeat silence" in (secs["_chaos"]["note"] or ""), secs
# killed by the heartbeat watchdog inside its window, not the wall budget
assert secs["_chaos"]["duration_s"] < 30, secs
partial = json.load(open("/tmp/_bench_smoke/partial.json"))  # schema-valid
# flight recorder (ISSUE 15): the watchdog kill must leave a parseable
# post-mortem dump referenced from the partial JSON — the child dies by
# SIGKILL, so the PARENT's ring (which emits the kill instant) is the
# dump under test
dumps = [
    d for d in partial.get("flightrec_dumps", [])
    if d.get("reason") == "watchdog_kill"
]
assert dumps, partial.get("flightrec_dumps")
rec = json.load(open(dumps[0]["path"]))
assert rec["schema"].startswith("tendermint-tpu-flightrec/"), rec["schema"]
assert any(
    r["name"] == "bench_watchdog_kill" for r in rec["records"]
), [r["name"] for r in rec["records"]][:20]
assert merged.get("flightrec_dumps") == partial["flightrec_dumps"], (
    "merged doc lost the dump references"
)
print(
    "bench smoke ok: hang killed by watchdog in %.1fs, healthy section "
    "kept, flight recorder dumped %d records"
    % (secs["_chaos"]["duration_s"], len(rec["records"]))
)
EOF

echo "== kernel campaign (resident tables + device hash + autotuner) =="
# ISSUE 8 stage: the device-resident table store, fused SHA-512
# challenge hashing, and the field-mul autotuner forced ON on the CPU
# backend (their auto modes keep CPU off, so tier-1 alone would never
# execute these paths), plus the hashing parity battery. Both forced
# TENDERMINT_TPU_FIELD_MUL values pin verify parity under each impl.
rm -rf /tmp/_kcamp && mkdir -p /tmp/_kcamp
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    TENDERMINT_TPU_RESIDENT=on TENDERMINT_TPU_DEVICE_HASH=1 \
    TENDERMINT_TPU_AUTOTUNE=on \
    TENDERMINT_TPU_AUTOTUNE_CACHE=/tmp/_kcamp/autotune.json \
    python -m pytest tests/test_resident.py tests/test_device_hash.py \
    tests/test_autotune.py -q -p no:cacheprovider -p no:xdist \
    -p no:randomly || rc_total=1
for mul in vpu mxu; do
    timeout -k 10 300 env JAX_PLATFORMS=cpu TENDERMINT_TPU_FIELD_MUL=$mul \
        python -m pytest tests/test_ops_ed25519.py -q \
        -p no:cacheprovider -p no:xdist -p no:randomly || {
        echo "kernel campaign: parity failed under FIELD_MUL=$mul" >&2
        rc_total=1
    }
done

echo "== lightd serving tier (evloop suites + light_serve smoke) =="
# PR 9 stage: the selector event loop must keep both wire protocols
# byte-identical (grpc + verifyd regression suites and the evloop
# regressions proper), and a 200-client light_serve smoke on CPU must
# land status=ok with a nonzero warm-phase cache hit rate.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_grpc.py tests/test_verifyd.py \
    tests/test_evloop.py tests/test_lightd.py -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly || rc_total=1
rm -rf /tmp/_bench_light && mkdir -p /tmp/_bench_light
timeout -k 10 420 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=light_serve BENCH_LIGHT_SERVE_CLIENTS=200 \
    BENCH_LIGHT_SERVE_HEIGHTS=24 BENCH_LIGHT_SERVE_REQUESTS=1000 \
    BENCH_SECTION_TIMEOUT=360 BENCH_SECTION_ATTEMPTS=1 \
    BENCH_PARTIAL=/tmp/_bench_light/partial.json \
    python bench.py > /tmp/_bench_light/out.json 2>/tmp/_bench_light/err.log
if [ "$?" -ne 0 ]; then
    echo "bench light_serve smoke: non-zero rc" >&2
    tail -5 /tmp/_bench_light/err.log >&2
    rc_total=1
fi
python - <<'EOF' || rc_total=1
import json
merged = json.load(open("/tmp/_bench_light/out.json"))
assert merged["sections"]["light_serve"]["status"] == "ok", merged["sections"]
ls = merged["light_serve"]
assert ls["errors"] == 0, ls
assert ls["cache_hit_rate"] > 0, ls
assert ls["warm_headers_per_s"] > 0, ls
print(
    "bench light_serve smoke ok: %s clients, %.0f headers/s warm, "
    "hit rate %.2f" % (ls["clients"], ls["warm_headers_per_s"],
                       ls["cache_hit_rate"])
)
EOF

echo "== verifyd chaos battery (sanitized) + tenant bench smoke =="
# ISSUE 11 stage: the serving-tier chaos battery (device faults
# mid-dispatch, torn frames, slow readers, tenant floods, kill/restart)
# runs with the lock-order sanitizer ON — the continuous-batching
# dispatch workers share the scheduler mutex with the accumulator, so
# an inversion here is exactly the regression this stage exists to
# catch. Then the verifyd_tenants bench section must show explicit
# sheds under flood and continuous batching no worse than the barrier
# path on victim p99 (observed ~0.96x; 1.25x margin absorbs CI noise).
rm -f /tmp/_chaos.log
timeout -k 10 600 env TENDERMINT_TPU_SANITIZE=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/test_verifyd_chaos.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_chaos.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "LOCK-ORDER CYCLE" /tmp/_chaos.log; then
    echo "verifyd chaos: lock-order cycle detected (potential deadlock)" >&2
    rc_total=1
fi
rm -rf /tmp/_bench_tenants && mkdir -p /tmp/_bench_tenants
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=verifyd_tenants BENCH_SECTION_TIMEOUT=240 \
    BENCH_SECTION_ATTEMPTS=1 \
    BENCH_PARTIAL=/tmp/_bench_tenants/partial.json \
    python bench.py > /tmp/_bench_tenants/out.json \
    2>/tmp/_bench_tenants/err.log
if [ "$?" -ne 0 ]; then
    echo "bench verifyd_tenants smoke: non-zero rc" >&2
    tail -5 /tmp/_bench_tenants/err.log >&2
    rc_total=1
fi
python - <<'EOF' || rc_total=1
import json
merged = json.load(open("/tmp/_bench_tenants/out.json"))
assert merged["sections"]["verifyd_tenants"]["status"] == "ok", \
    merged["sections"]
vt = merged["verifyd_tenants"]
cont, barrier = vt["continuous"], vt["barrier"]
# the flood tenant hit its budget and was shed EXPLICITLY (the barrier
# mode sheds too, but its count sits near zero at this load — the
# budget mechanism itself is mode-independent and chaos-tested)
assert cont["flood_sheds"] > 0, vt
assert cont["tenants"]["flood"]["sheds"] == cont["flood_sheds"], cont
# continuous batching actually pipelined (hand-offs only exist there)
assert cont["dispatch_handoffs"] > 0, cont
assert barrier["dispatch_handoffs"] == 0, barrier
# mixed-load victim p99: continuous must not lose to the barrier path
assert cont["victim_p99_ms"] <= barrier["victim_p99_ms"] * 1.25, vt
print(
    "bench verifyd_tenants smoke ok: victim p99 %.1fms continuous vs "
    "%.1fms barrier, flood sheds %d/%d"
    % (cont["victim_p99_ms"], barrier["victim_p99_ms"],
       cont["flood_sheds"], barrier["flood_sheds"])
)
EOF

echo "== tpusan: happens-before race detection =="
# PR 12 stage: vector-clock happens-before detection over the
# concurrent serving stack (scheduler hand-off, verifyd brownout/chaos,
# evloop lifecycle). Any DATA RACE marker is a gate failure — the
# report carries both access stacks and the lock sets held.
rm -f /tmp/_tpusan_hb.log
timeout -k 10 850 env TENDERMINT_TPU_SANITIZE=hb JAX_PLATFORMS=cpu \
    python -m pytest tests/test_scheduler.py tests/test_verifyd_chaos.py \
    tests/test_evloop.py -q -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_tpusan_hb.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "DATA RACE" /tmp/_tpusan_hb.log; then
    echo "tpusan: data race detected (stacks above)" >&2
    rc_total=1
fi
if grep -q "LOCK-ORDER CYCLE" /tmp/_tpusan_hb.log; then
    echo "tpusan: lock-order cycle detected" >&2
    rc_total=1
fi

echo "== tpusan: deterministic schedule exploration (10 seeds) =="
# The continuous-batching scheduler under 10 seeded interleavings.
# Same seed -> same schedule, byte-stable report: a failure here
# reproduces exactly with TENDERMINT_TPU_SANITIZE=explore:<seed>.
for seed in 0 1 2 3 4 5 6 7 8 9; do
    timeout -k 10 180 env TENDERMINT_TPU_SANITIZE=explore:$seed \
        JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_scheduler.py::TestContinuousBatching" -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        > /tmp/_tpusan_explore.log 2>&1 || {
        echo "tpusan explore: FAILED under seed $seed — replay with" \
             "TENDERMINT_TPU_SANITIZE=explore:$seed" >&2
        tail -20 /tmp/_tpusan_explore.log >&2
        rc_total=1
    }
done

echo "== shm ingress: sanitized slab-ring tests + seeded explore =="
# PR 13 stage (mirrors the PR 12 contract): the zero-copy slab-ring
# state machine runs under happens-before race detection — any DATA
# RACE or LOCK-ORDER CYCLE marker fails the gate — and then the ring
# state machine explores 10 seeded interleavings (acquire/fill/commit
# vs drain/retire/free is the exact cursor hand-off a bad schedule
# would tear).
rm -f /tmp/_tpusan_shm.log
timeout -k 10 850 env TENDERMINT_TPU_SANITIZE=hb JAX_PLATFORMS=cpu \
    python -m pytest tests/test_verifyd_shm.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_tpusan_shm.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "DATA RACE" /tmp/_tpusan_shm.log; then
    echo "shm ingress: data race detected (stacks above)" >&2
    rc_total=1
fi
if grep -q "LOCK-ORDER CYCLE" /tmp/_tpusan_shm.log; then
    echo "shm ingress: lock-order cycle detected" >&2
    rc_total=1
fi
for seed in 0 1 2 3 4 5 6 7 8 9; do
    timeout -k 10 180 env TENDERMINT_TPU_SANITIZE=explore:$seed \
        JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_verifyd_shm.py::TestRingStateMachine" -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        > /tmp/_tpusan_shm_explore.log 2>&1 || {
        echo "shm explore: FAILED under seed $seed — replay with" \
             "TENDERMINT_TPU_SANITIZE=explore:$seed" >&2
        tail -20 /tmp/_tpusan_shm_explore.log >&2
        rc_total=1
    }
done

echo "== bench smoke (verifyd_shm A/B) =="
# The zero-copy acceptance: at 8192 lanes the slab path must beat the
# TCP codec on p50 outright and report the codec bytes it skipped.
# The noop verifier is declared in the JSON (verify=noop) — the A/B
# isolates transport + codec cost, which is the claim under test.
rm -rf /tmp/_bench_shm && mkdir -p /tmp/_bench_shm
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=verifyd_shm BENCH_SHM_ROUNDS=8 \
    BENCH_SECTION_TIMEOUT=240 BENCH_SECTION_ATTEMPTS=1 \
    BENCH_PARTIAL=/tmp/_bench_shm/partial.json \
    python bench.py > /tmp/_bench_shm/out.json 2>/tmp/_bench_shm/err.log
if [ "$?" -ne 0 ]; then
    echo "bench verifyd_shm smoke: non-zero rc" >&2
    tail -5 /tmp/_bench_shm/err.log >&2
    rc_total=1
fi
python - <<'EOF' || rc_total=1
import json
merged = json.load(open("/tmp/_bench_shm/out.json"))
assert merged["sections"]["verifyd_shm"]["status"] == "ok", merged["sections"]
vs = merged["verifyd_shm"]
assert vs["verify"] == "noop", vs  # the knob is declared, not hidden
big = vs["sizes"]["8192"]
assert big["shm"]["transport"] == "shm", big
assert big["shm"]["p50_ms"] < big["tcp"]["p50_ms"], big
assert big["shm"]["codec_bytes_avoided"] > 0, big
assert vs["server"]["shm_torn_slabs"] == 0, vs["server"]
print(
    "bench verifyd_shm smoke ok: p50 %.2fms shm vs %.2fms tcp at 8192 "
    "lanes, %d codec bytes avoided"
    % (big["shm"]["p50_ms"], big["tcp"]["p50_ms"],
       big["shm"]["codec_bytes_avoided"])
)
EOF

echo "== flight recorder: sanitized ring tests + seeded explore =="
# ISSUE 15 stage: the always-on flight recorder records from every
# tracer span, metric increment, and fault hook concurrently — its
# byte-accounting ring runs under happens-before race detection, then
# the producer/reader/dumper hand-off explores 10 seeded
# interleavings (TestRingConcurrency is the designated target class).
rm -f /tmp/_tpusan_flightrec.log
timeout -k 10 300 env TENDERMINT_TPU_SANITIZE=hb JAX_PLATFORMS=cpu \
    python -m pytest tests/test_flightrec.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_tpusan_flightrec.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "DATA RACE" /tmp/_tpusan_flightrec.log; then
    echo "flightrec: data race detected (stacks above)" >&2
    rc_total=1
fi
if grep -q "LOCK-ORDER CYCLE" /tmp/_tpusan_flightrec.log; then
    echo "flightrec: lock-order cycle detected" >&2
    rc_total=1
fi
for seed in 0 1 2 3 4 5 6 7 8 9; do
    timeout -k 10 180 env TENDERMINT_TPU_SANITIZE=explore:$seed \
        JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_flightrec.py::TestRingConcurrency" -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        > /tmp/_tpusan_flightrec_explore.log 2>&1 || {
        echo "flightrec explore: FAILED under seed $seed — replay with" \
             "TENDERMINT_TPU_SANITIZE=explore:$seed" >&2
        tail -20 /tmp/_tpusan_flightrec_explore.log >&2
        rc_total=1
    }
done

echo "== adaptive serving: sanitized controller tests + seeded explore =="
# ISSUE 17 stage: the dyn-batch controller and per-tenant SLO budget
# machinery under happens-before race detection — the controller's
# observe_flush/limits sites run on dispatch workers while stats()
# snapshots from serving threads, so a missing lock here is a real
# race, not a theoretical one. Then the tenant-SLO suite (breach ->
# scoped shed -> hysteresis recovery, end to end) explores 10 seeded
# interleavings.
rm -f /tmp/_tpusan_adaptive.log
timeout -k 10 600 env TENDERMINT_TPU_SANITIZE=hb JAX_PLATFORMS=cpu \
    python -m pytest tests/test_adaptive.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_tpusan_adaptive.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "DATA RACE" /tmp/_tpusan_adaptive.log; then
    echo "adaptive: data race detected (stacks above)" >&2
    rc_total=1
fi
if grep -q "LOCK-ORDER CYCLE" /tmp/_tpusan_adaptive.log; then
    echo "adaptive: lock-order cycle detected" >&2
    rc_total=1
fi
for seed in 0 1 2 3 4 5 6 7 8 9; do
    timeout -k 10 180 env TENDERMINT_TPU_SANITIZE=explore:$seed \
        JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_adaptive.py::TestTenantSlo" -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        > /tmp/_tpusan_adaptive_explore.log 2>&1 || {
        echo "adaptive explore: FAILED under seed $seed — replay with" \
             "TENDERMINT_TPU_SANITIZE=explore:$seed" >&2
        tail -20 /tmp/_tpusan_adaptive_explore.log >&2
        rc_total=1
    }
done

echo "== bench smoke (slo_replay: adaptive holds budget at 2x static) =="
# The adaptive-serving acceptance on the checked-in diurnal trace: the
# static ladder is cut to ONE rung (SAT_STEPS=1 — the x1 run anchors
# the saturation point either way), never the trace itself: a trace
# shorter than the controller's ramp window would score cold-start
# and fail for the wrong reason. The section self-asserts p99-within-
# budget and served>=70%; the heredoc re-checks both from the JSON so
# a silently-weakened section assert still fails the gate.
rm -rf /tmp/_bench_slo && mkdir -p /tmp/_bench_slo
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=slo_replay BENCH_SLO_SAT_STEPS=1 \
    BENCH_SECTION_TIMEOUT=240 BENCH_SECTION_ATTEMPTS=1 \
    BENCH_PARTIAL=/tmp/_bench_slo/partial.json \
    python bench.py > /tmp/_bench_slo/out.json 2>/tmp/_bench_slo/err.log
if [ "$?" -ne 0 ]; then
    echo "bench slo_replay smoke: non-zero rc" >&2
    tail -5 /tmp/_bench_slo/err.log >&2
    rc_total=1
fi
python - <<'EOF' || rc_total=1
import json
merged = json.load(open("/tmp/_bench_slo/out.json"))
assert merged["sections"]["slo_replay"]["status"] == "ok", merged["sections"]
sr = merged["slo_replay"]
tip = sr["adaptive"]["tip"]
budget = sr["trace"]["tip_slo_ms"]
assert sr["adaptive"]["dyn_batch"] is True, sr["adaptive"]
assert tip["p99_ms"] is not None and tip["p99_ms"] <= budget, tip
assert tip["served"] >= 0.7 * max(1, tip["scored"]), tip
# the adaptive run records the scheduler knobs it actually converged
# to (ISSUE 17 satellite: resolved knobs in every artifact)
assert sr["adaptive"]["knobs"], sr["adaptive"]
assert "dyn_batch" in sr["adaptive"]["knobs"], sr["adaptive"]["knobs"]
print(
    "bench slo_replay smoke ok: adaptive tip p99 %.1fms <= %dms budget "
    "at x%g (2x static saturation), served %d/%d"
    % (tip["p99_ms"], budget, sr["adaptive_mult"], tip["served"],
       tip["scored"])
)
EOF

echo "== introspection: sanitized suites + sentinel + profiler overhead =="
# ISSUE 18 stage. (a) The device-byte accountant and profiler digests
# run under happens-before race detection — the ledger is written from
# resident-store refresh, shm register/unregister, and compile paths
# concurrently, so a missing lock is a real race. (b) The bench_diff
# sentinel's documented acceptance pair: r01 -> r05 shows the relay
# throughput collapse and MUST exit 4 (regression); the identity diff
# MUST exit 0. (c) Profiler overhead: the host_ref throughput section
# with the profiler on must land within 5% of a profiler-off run, and
# the merged JSON must carry the profile fragment.
rm -f /tmp/_tpusan_introspect.log
timeout -k 10 600 env TENDERMINT_TPU_SANITIZE=hb JAX_PLATFORMS=cpu \
    python -m pytest tests/test_introspect.py tests/test_bench_diff.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
    | tee /tmp/_tpusan_introspect.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "DATA RACE" /tmp/_tpusan_introspect.log; then
    echo "introspect: data race detected (stacks above)" >&2
    rc_total=1
fi
python -m scripts.bench_diff BENCH_r01.json BENCH_r05.json \
    > /tmp/_bench_diff_accept.log 2>&1
if [ "$?" -ne 4 ]; then
    echo "bench_diff acceptance: r01 -> r05 must exit 4 (regression)" >&2
    rc_total=1
fi
python -m scripts.bench_diff BENCH_r05.json BENCH_r05.json >/dev/null \
    || { echo "bench_diff acceptance: identity diff must exit 0" >&2; \
         rc_total=1; }
rm -rf /tmp/_bench_prof && mkdir -p /tmp/_bench_prof
for prof in on off; do
    timeout -k 10 180 env JAX_PLATFORMS=cpu TENDERMINT_TPU_PROFILE=$prof \
        BENCH_SECTIONS=host_ref BENCH_HOST_REF_SIGS=64 \
        BENCH_SECTION_TIMEOUT=150 BENCH_SECTION_ATTEMPTS=1 \
        BENCH_PARTIAL=/tmp/_bench_prof/partial_$prof.json \
        python bench.py > /tmp/_bench_prof/out_$prof.json \
        2>/tmp/_bench_prof/err_$prof.log || {
        echo "bench profiler smoke ($prof): non-zero rc" >&2
        tail -5 /tmp/_bench_prof/err_$prof.log >&2
        rc_total=1
    }
done
python - <<'EOF' || rc_total=1
import json
on = json.load(open("/tmp/_bench_prof/out_on.json"))
off = json.load(open("/tmp/_bench_prof/out_off.json"))
# the profile fragment rides in every merged doc; its enabled flag
# reflects the knob
assert on["profile"]["enabled"] is True, on.get("profile")
assert off["profile"]["enabled"] is False, off.get("profile")
t_on = on["host_ref"]["sigs_per_s"]
t_off = off["host_ref"]["sigs_per_s"]
overhead = (t_off - t_on) / t_off * 100.0
assert overhead <= 5.0, (
    "profiler overhead %.1f%% exceeds the 5%% budget "
    "(%.1f sigs/s on vs %.1f off)" % (overhead, t_on, t_off)
)
print(
    "profiler overhead ok: %.1f sigs/s on vs %.1f off (%.1f%%)"
    % (t_on, t_off, overhead)
)
EOF
# smoke diff against the checked-in CPU fingerprint: the generous
# tolerance absorbs hardware variance; what it still catches is an
# order-of-magnitude collapse or a section/metric falling out of the
# merged doc entirely (--strict-missing)
python -m scripts.bench_diff --tolerance 75 --strict-missing \
    BENCH_cpu_smoke_baseline.json /tmp/_bench_prof/out_on.json \
    || { echo "introspect: smoke diff vs checked-in fingerprint failed" >&2; \
         rc_total=1; }

echo "== verifyd federation: sanitized suites + seeded failover explore =="
# ISSUE 19 stage: the digest-routed shard federation. The routing and
# failover suites (plus the shard-kill chaos test) run under
# happens-before race detection — the FederationClient's membership
# state (_dead/_owner/route_epoch) is @instrument_attrs-instrumented,
# so a racy ladder walk surfaces as a DATA RACE marker, not a flake.
rm -f /tmp/_tpusan_fed.log
timeout -k 10 850 env TENDERMINT_TPU_SANITIZE=hb JAX_PLATFORMS=cpu \
    python -m pytest tests/test_federation.py tests/test_verifyd_chaos.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
    | tee /tmp/_tpusan_fed.log
[ "${PIPESTATUS[0]}" -ne 0 ] && rc_total=1
if grep -q "DATA RACE" /tmp/_tpusan_fed.log; then
    echo "federation: data race detected (stacks above)" >&2
    rc_total=1
fi
if grep -q "LOCK-ORDER CYCLE" /tmp/_tpusan_fed.log; then
    echo "federation: lock-order cycle detected" >&2
    rc_total=1
fi
# the failover ladder under 10 seeded interleavings: mark-dead vs
# revive vs concurrent group dispatch is the exact hand-off a bad
# schedule would tear (same seed -> same schedule, exact replay)
for seed in 0 1 2 3 4 5 6 7 8 9; do
    timeout -k 10 180 env TENDERMINT_TPU_SANITIZE=explore:$seed \
        JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_federation.py::TestFailover" -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        > /tmp/_tpusan_fed_explore.log 2>&1 || {
        echo "federation explore: FAILED under seed $seed — replay with" \
             "TENDERMINT_TPU_SANITIZE=explore:$seed" >&2
        tail -20 /tmp/_tpusan_fed_explore.log >&2
        rc_total=1
    }
done

echo "== bench smoke (verifyd_fleet, 2 shards) =="
# The federation acceptance, over the wire: 2 spawned shard processes
# must pin strictly disjoint resident-table slices (the section fails
# itself on any overlap or coverage gap), aggregate modeled sigs/s
# must scale >= 1.5x over one shard, and the mid-load SIGKILL round
# must finish with zero silent drops.
rm -rf /tmp/_bench_fleet && mkdir -p /tmp/_bench_fleet
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    BENCH_SECTIONS=verifyd_fleet BENCH_FLEET_MAX_SHARDS=2 \
    BENCH_FLEET_ROUNDS=4 \
    BENCH_SECTION_TIMEOUT=240 BENCH_SECTION_ATTEMPTS=1 \
    BENCH_PARTIAL=/tmp/_bench_fleet/partial.json \
    python bench.py > /tmp/_bench_fleet/out.json || {
    echo "bench verifyd_fleet smoke: non-zero rc" >&2
    rc_total=1
}
python - <<'EOF' || rc_total=1
import json
doc = json.load(open("/tmp/_bench_fleet/out.json"))
sec = doc["sections"]["verifyd_fleet"]
assert sec["status"] == "ok", "verifyd_fleet section: %s" % sec
fleet = doc["verifyd_fleet"]
assert fleet["verify"] == "modeled", fleet  # honesty declared
two = fleet["shards"]["2"]
assert two["disjoint"] is True, two
pinned = two["pinned_keys"]
assert len(pinned) == 2 and all(n > 0 for n in pinned.values()), pinned
assert sum(pinned.values()) == fleet["committees"] * 4, pinned
assert two["max_shard_bytes_vs_single"] < 1.0, two
assert fleet["scaling_2x_over_1x"] >= 1.5, fleet["scaling_2x_over_1x"]
fo = fleet["failover"]
assert fo["zero_silent_drops"] is True, fo
assert fo["unexplained_false_lanes"] == 0, fo
print(
    "verifyd_fleet smoke ok: %.2fx scaling, pinned split %s, "
    "%d lanes rerouted on shard kill"
    % (fleet["scaling_2x_over_1x"], pinned, fo["rerouted_lanes"])
)
EOF

echo "== tier-1 pytest =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && rc_total=1

exit $rc_total
