"""Fault flight recorder (libs/flightrec.py): ring bounds, dump
triggers, handler-chain installation, and the concurrency class the
tpusan hb/explore CI stages target.

The subprocess tests exercise the real fault paths (SIGTERM, unhandled
exception) end to end — a dump written by a dying process is the whole
point of the recorder, so those paths are not faked with direct calls.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from tendermint_tpu.libs import flightrec, tracing
from tendermint_tpu.libs.flightrec import (
    KIND_INSTANT,
    KIND_MARK,
    KIND_METRIC,
    KIND_SPAN,
    FlightRecorder,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rec(tmp_path, monkeypatch):
    monkeypatch.setenv(flightrec.DIR_ENV, str(tmp_path))
    monkeypatch.delenv(flightrec.ENABLE_ENV, raising=False)
    return FlightRecorder(cap_bytes=8192, window_s=30.0)


# --- ring mechanics ----------------------------------------------------------


class TestRing:
    def test_byte_bound_evicts_oldest(self, rec):
        for i in range(500):
            rec.record(KIND_MARK, "m%d" % i, {"pad": "x" * 100})
        stats = rec.stats()
        assert stats["bytes"] <= rec.cap_bytes
        assert stats["evicted"] > 0
        assert stats["recorded"] == 500
        # the survivors are the NEWEST records
        names = [r["name"] for r in rec.snapshot()]
        assert names[-1] == "m499"
        assert "m0" not in names

    def test_payload_cap_truncates_not_raises(self, rec):
        rec.record(KIND_MARK, "big", {"blob": "y" * 4096})
        rows = rec.snapshot()
        assert len(rows) == 1
        # a truncated payload decodes to the sentinel, never raises
        assert rows[0]["name"] in ("big", "<truncated>")

    def test_unserializable_payload_keeps_name(self, rec):
        rec.record(KIND_MARK, "odd", {"obj": object()})
        assert rec.snapshot()[0]["name"] == "odd"

    def test_window_filters_old_records(self, rec):
        rec.record(KIND_MARK, "now", {})
        assert rec.snapshot(window_s=3600) != []
        assert rec.snapshot(window_s=1e-9) == []

    def test_kind_decoding_and_duration(self, rec):
        rec.record(KIND_SPAN, "s", {"a": 1}, dur_s=0.25)
        rec.record(KIND_INSTANT, "i", {})
        rec.record(KIND_METRIC, "m", {"v": 2.0})
        rows = rec.snapshot()
        assert [r["kind"] for r in rows] == ["span", "instant", "metric"]
        assert rows[0]["dur_us"] == 250000
        assert rows[0]["fields"] == {"a": 1}


# --- dumps -------------------------------------------------------------------


class TestDump:
    def test_dump_writes_parseable_schema_doc(self, rec, tmp_path):
        rec.mark("before_fault", step=7)
        path = rec.dump("unit_test")
        assert path is not None and os.path.exists(path)
        doc = json.load(open(path))
        assert doc["schema"] == flightrec.DUMP_SCHEMA
        assert doc["reason"] == "unit_test"
        assert doc["pid"] == os.getpid()
        assert any(r["name"] == "before_fault" for r in doc["records"])
        assert rec.last_dump_path() == path

    def test_dump_budget_caps_disk_spam(self, rec):
        paths = [rec.dump("spam%d" % i) for i in range(flightrec.MAX_DUMPS + 5)]
        assert all(p is not None for p in paths[: flightrec.MAX_DUMPS])
        assert all(p is None for p in paths[flightrec.MAX_DUMPS :])

    def test_disabled_env_suppresses_dump(self, rec, monkeypatch):
        monkeypatch.setenv(flightrec.ENABLE_ENV, "0")
        assert rec.dump("nope") is None

    def test_watchdog_instant_auto_dumps(self, rec):
        rec.flight_sink(
            "instant", "bench_watchdog_kill", {"section": "x"}, 0.0, 0.0
        )
        path = rec.last_dump_path()
        assert path is not None
        doc = json.load(open(path))
        assert doc["reason"] == "watchdog_kill"
        assert any(
            r["name"] == "bench_watchdog_kill" for r in doc["records"]
        )

    def test_device_health_escalation_auto_dumps(self, rec):
        rec.flight_sink(
            "instant",
            "device_health_transition",
            {"to_state": "COOLDOWN"},
            0.0,
            0.0,
        )
        assert rec.last_dump_path() is not None
        doc = json.load(open(rec.last_dump_path()))
        assert doc["reason"] == "device_cooldown"

    def test_healthy_transition_does_not_dump(self, rec):
        rec.flight_sink(
            "instant",
            "device_health_transition",
            {"to_state": "healthy"},
            0.0,
            0.0,
        )
        assert rec.last_dump_path() is None

    def test_span_sink_records_without_dumping(self, rec):
        rec.flight_sink("span", "bench_watchdog_kill_lookalike", {}, 0.0, 0.1)
        rec.flight_sink("span", "bench_watchdog_kill", {}, 0.0, 0.1)
        # spans never trigger (only instants are fault signals)
        assert rec.last_dump_path() is None
        assert len(rec) == 2


# --- installation ------------------------------------------------------------


class TestInstall:
    def test_install_wires_tracer_sink(self, rec):
        assert rec.install(signals=False)
        try:
            with tracing.tracer.span("flightrec_probe", n=1):
                pass
            tracing.instant("flightrec_probe_instant", k=2)
            names = [r["name"] for r in rec.snapshot()]
            assert "flightrec_probe" in names
            assert "flightrec_probe_instant" in names
        finally:
            rec.uninstall()
        # after uninstall the sink is detached
        before = len(rec)
        with tracing.tracer.span("flightrec_after", n=1):
            pass
        assert len(rec) == before

    def test_install_is_idempotent(self, rec):
        try:
            assert rec.install(signals=False)
            assert rec.install(signals=False)
            assert rec.stats()["installed"]
        finally:
            rec.uninstall()
        assert not rec.stats()["installed"]

    def test_metric_sink_records_deltas(self, rec):
        rec.metric_sink("tendermint_x_total", {"k": "v"}, 3.0)
        row = rec.snapshot()[0]
        assert row["kind"] == "metric"
        assert row["fields"]["v"] == 3.0
        assert row["fields"]["labels"] == {"k": "v"}

    def test_sigterm_dump_from_real_process(self, tmp_path):
        code = textwrap.dedent(
            """
            import os, signal, time
            from tendermint_tpu.libs import flightrec
            assert flightrec.install()
            flightrec.mark("about_to_die", step=1)
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(10)  # unreachable: the chained handler re-kills
            """
        )
        env = dict(os.environ)
        env[flightrec.DIR_ENV] = str(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            cwd=REPO,
            capture_output=True,
            timeout=60,
        )
        assert proc.returncode != 0  # died by signal, as intended
        dumps = sorted(tmp_path.glob("flightrec-*-sigterm-*.json"))
        assert len(dumps) == 1, list(tmp_path.iterdir())
        doc = json.load(open(dumps[0]))
        names = [r["name"] for r in doc["records"]]
        assert "about_to_die" in names
        assert "sigterm" in names

    def test_unhandled_exception_dump_from_real_process(self, tmp_path):
        code = textwrap.dedent(
            """
            from tendermint_tpu.libs import flightrec
            assert flightrec.install()
            flightrec.mark("last_good_step")
            raise RuntimeError("injected crash")
            """
        )
        env = dict(os.environ)
        env[flightrec.DIR_ENV] = str(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode != 0
        assert "injected crash" in proc.stderr  # chained hook still ran
        dumps = sorted(
            tmp_path.glob("flightrec-*-unhandled_exception-*.json")
        )
        assert len(dumps) == 1, list(tmp_path.iterdir())
        doc = json.load(open(dumps[0]))
        names = [r["name"] for r in doc["records"]]
        assert "last_good_step" in names
        assert "unhandled_exception" in names


# --- concurrency (tpusan hb + seeded-explore target) -------------------------


class TestRingConcurrency:
    """Producers hammer the ring while a reader snapshots and a dumper
    dumps: every byte-accounting invariant must hold under any
    interleaving (the CI explore stage replays this class under 10
    deterministic schedules)."""

    def test_concurrent_producers_keep_byte_invariant(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv(flightrec.DIR_ENV, str(tmp_path))
        rec = FlightRecorder(cap_bytes=16384, window_s=30.0)
        n_threads, per_thread = 4, 200
        errors = []

        def producer(t):
            try:
                for i in range(per_thread):
                    rec.record(KIND_MARK, "t%d-%d" % (t, i), {"p": "z" * 40})
            except Exception as exc:  # pragma: no cover - the assertion
                errors.append(repr(exc))

        def reader():
            try:
                for _ in range(20):
                    rec.snapshot()
                    rec.stats()
                    time.sleep(0.001)
            except Exception as exc:  # pragma: no cover
                errors.append(repr(exc))

        threads = [
            threading.Thread(target=producer, args=(t,))
            for t in range(n_threads)
        ] + [threading.Thread(target=reader)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert errors == []
        stats = rec.stats()
        assert stats["recorded"] == n_threads * per_thread
        assert stats["bytes"] <= rec.cap_bytes
        assert stats["recorded"] - stats["evicted"] == len(rec)

    def test_concurrent_dump_and_record(self, tmp_path, monkeypatch):
        monkeypatch.setenv(flightrec.DIR_ENV, str(tmp_path))
        rec = FlightRecorder(cap_bytes=16384, window_s=30.0)
        stop = threading.Event()
        errors = []

        def producer():
            i = 0
            try:
                while not stop.is_set():
                    rec.record(KIND_MARK, "p%d" % i, {})
                    i += 1
            except Exception as exc:  # pragma: no cover
                errors.append(repr(exc))

        th = threading.Thread(target=producer)
        th.start()
        try:
            paths = [rec.dump("concurrent%d" % i) for i in range(3)]
        finally:
            stop.set()
            th.join()
        assert errors == []
        for p in paths:
            assert p is not None
            json.load(open(p))  # parseable mid-traffic
