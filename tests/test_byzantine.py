"""Byzantine behavior: an equivocating validator yields committed evidence.

The in-process analog of internal/consensus/byzantine_test.go: one of
four validators double-signs prevotes (same height/round, conflicting
block IDs). Honest peers detect the conflict in their vote sets
(types/vote_set.go conflicting-vote tracking), turn it into
DuplicateVoteEvidence (evidence pool reportConflictingVotes), gossip
it, and a later proposer commits it into a block.
"""

import time

import pytest

from tendermint_tpu.types.block import BlockID, Vote
from tendermint_tpu.types.evidence import DuplicateVoteEvidence

from tests.test_node import fast_genesis, make_node, wait_for, four_privs  # noqa: F401
from tendermint_tpu.p2p.transport import MemoryNetwork
from tendermint_tpu.encoding.canonical import SIGNED_MSG_TYPE_PREVOTE


def _make_equivocator(node, chain_id):
    """Wrap the reactor's broadcast_vote: every non-nil prevote is paired
    with a conflicting nil prevote signed by the same key (the
    double-sign byzantine_test.go injects)."""
    reactor = node.consensus_reactor
    pv = node.priv_validator
    orig = reactor.broadcast_vote

    def byzantine_broadcast(vote: Vote) -> None:
        orig(vote)
        if vote.type == SIGNED_MSG_TYPE_PREVOTE and not vote.block_id.is_nil():
            dup = Vote(
                type=vote.type,
                height=vote.height,
                round=vote.round,
                block_id=BlockID(),  # nil: conflicts with the real prevote
                timestamp=vote.timestamp,
                validator_address=vote.validator_address,
                validator_index=vote.validator_index,
            )
            # Sign directly with the key, bypassing FilePV's double-sign
            # guard — that guard is exactly what a byzantine node ignores.
            dup.signature = pv.priv_key.sign(dup.sign_bytes(chain_id))
            orig(dup)

    reactor.broadcast_vote = byzantine_broadcast


class TestByzantine:
    def test_equivocating_prevoter_gets_evidenced(self, tmp_path, four_privs):
        net = MemoryNetwork()
        nodes = []
        for i in range(4):
            node, _ = make_node(tmp_path, f"node{i}", four_privs, index=i, net=net)
            nodes.append(node)
        for i, node in enumerate(nodes):
            if i > 0:
                node.config.persistent_peers = [
                    f"{nodes[0].node_key.node_id}@node0"
                ]
        _make_equivocator(nodes[2], nodes[2].genesis.chain_id)
        for node in nodes:
            node.start()
        try:
            assert wait_for(
                lambda: all(len(n.router.connected_peers()) >= 1 for n in nodes),
                timeout=10,
            ), "peers failed to connect"

            byz_addr = four_privs[2].get_pub_key().address()

            def committed_duplicate_vote_evidence():
                for n in nodes:
                    for h in range(1, n.height + 1):
                        blk = n.block_store.load_block(h)
                        if blk is None:
                            continue
                        for ev in blk.evidence:
                            if (
                                isinstance(ev, DuplicateVoteEvidence)
                                and ev.vote_a.validator_address == byz_addr
                            ):
                                return True
                return False

            assert wait_for(committed_duplicate_vote_evidence, timeout=90), (
                f"no DuplicateVoteEvidence committed; heights: "
                f"{[n.height for n in nodes]}"
            )
        finally:
            for node in nodes:
                node.stop()
