"""Commit verification: the framework's crypto hot path.

Mirrors types/validation.go exactly: ignore/count predicates per entry
point, tally-then-verify, batch dispatch above a threshold with
single-verify fallback, and first-bad-signature fault attribution on
batch failure (validation.go:244-251).

The batch path feeds ``crypto.batch.create_batch_verifier`` which routes
to the TPU Straus kernel (ops/ed25519_batch.py) for ed25519 — one device
launch verifies every signature in the commit.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.libs import tracing
from tendermint_tpu.types.block import BlockID, Commit, CommitSig, BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.verifyd.client import classify as _classify
from tendermint_tpu.verifyd.protocol import (
    CLASS_BLOCKSYNC as _CLASS_BLOCKSYNC,
    CLASS_CONSENSUS as _CLASS_CONSENSUS,
    CLASS_LIGHT as _CLASS_LIGHT,
)

BATCH_VERIFY_THRESHOLD = 2  # validation.go:12


class Fraction(NamedTuple):
    """libs/math Fraction: unsigned numerator/denominator."""

    numerator: int
    denominator: int


INT64_MAX = 2**63 - 1


def _safe_mul(a: int, b: int) -> tuple:
    """libs/math SafeMul: (result, overflowed) for int64."""
    r = a * b
    if r > INT64_MAX or r < -(2**63):
        return 0, True
    return r, False


class NotEnoughVotingPowerError(Exception):
    def __init__(self, got: int, needed: int):
        self.got = got
        self.needed = needed
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}"
        )


class InvalidCommitError(ValueError):
    pass


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    """validation.go:14-16."""
    return len(commit.signatures) >= BATCH_VERIFY_THRESHOLD and (
        crypto_batch.supports_batch_verifier(vals.get_proposer().pub_key)
    )


def verify_commit(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    """validation.go:28-54: +2/3 signed; checks ALL signatures (ABCI apps
    depend on the full LastCommitInfo for incentivization)."""
    # Outermost-wins workload class: a configured verifyd remote treats
    # full commit verification as consensus-priority (never shed).
    with _classify(_CLASS_CONSENSUS), tracing.span(
        "verify_commit",
        height=height,
        round=commit.round,
        sigs=len(commit.signatures),
    ):
        _verify_basic_vals_and_commit(vals, commit, height, block_id)
        voting_power_needed = vals.total_voting_power() * 2 // 3
        ignore = lambda c: c.block_id_flag == BLOCK_ID_FLAG_ABSENT
        count = lambda c: c.block_id_flag == BLOCK_ID_FLAG_COMMIT
        if _should_batch_verify(vals, commit):
            return _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore, count,
                True, True,
            )
        return _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            True, True,
        )


def verify_commit_light(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    """validation.go:58-87: light-client/blocksync variant; stops at +2/3."""
    # Blocksync-priority by default; the light package classifies its
    # own calls "light" first (outermost wins).
    with _classify(_CLASS_BLOCKSYNC), tracing.span(
        "verify_commit",
        mode="light",
        height=height,
        round=commit.round,
        sigs=len(commit.signatures),
    ):
        _verify_basic_vals_and_commit(vals, commit, height, block_id)
        voting_power_needed = vals.total_voting_power() * 2 // 3
        ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT
        count = lambda c: True
        if _should_batch_verify(vals, commit):
            return _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore, count,
                False, True,
            )
        return _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            False, True,
        )


def verify_commit_light_trusting(
    chain_id: str, vals: ValidatorSet, commit: Commit, trust_level: Fraction
) -> None:
    """validation.go:89-135: trustLevel of a DIFFERENT valset signed;
    lookup is by address, double-signs detected."""
    if vals is None:
        raise InvalidCommitError("nil validator set")
    if trust_level.denominator == 0:
        raise InvalidCommitError("trustLevel has zero Denominator")
    if commit is None:
        raise InvalidCommitError("nil commit")
    total_mul, overflow = _safe_mul(vals.total_voting_power(), trust_level.numerator)
    if overflow:
        raise InvalidCommitError(
            "int64 overflow while calculating voting power needed"
        )
    voting_power_needed = total_mul // trust_level.denominator
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT
    count = lambda c: True
    # Trusting verification only happens on the light-client path.
    with _classify(_CLASS_LIGHT):
        if _should_batch_verify(vals, commit):
            return _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore, count,
                False, False,
            )
        return _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            False, False,
        )


def _verify_commit_batch(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """validation.go:151-258.

    Divergence (improvement): a mixed ed25519+sr25519 commit sub-batches
    per key type (crypto/batch.MultiBatchVerifier), each type on its own
    device kernel — the reference's single-key-type verifier would fail
    the whole commit. Only keys with no batch support at all (secp256k1)
    drop to single verification, which is what the reference's comment
    declares (validation.go:49-50) but its code never does.
    """
    tallied = 0
    seen_vals = {}
    batch_sig_idxs = []
    # Make this set's keys eligible for the device precompute cache —
    # the second commit from the same validators skips its table builds.
    crypto_batch.note_validator_set(vals)
    # Mixed validator sets sub-batch per key type (BASELINE config 5);
    # an unsupported key (secp256k1) raises on add -> single fallback.
    bv = crypto_batch.MultiBatchVerifier()
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from validator {val_idx} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        try:
            bv.add(val.pub_key, vote_sign_bytes, commit_sig.signature)
        except ValueError:
            return _verify_commit_single(
                chain_id,
                vals,
                commit,
                voting_power_needed,
                ignore_sig,
                count_sig,
                count_all_signatures,
                look_up_by_index,
            )
        batch_sig_idxs.append(idx)
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(got=tallied, needed=voting_power_needed)
    ok, valid_sigs = bv.verify()
    if ok:
        return
    for i, sig_ok in enumerate(valid_sigs):
        if not sig_ok:
            idx = batch_sig_idxs[i]
            sig = commit.signatures[idx]
            raise InvalidCommitError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
            )
    raise InvalidCommitError(
        "BUG: batch verification failed with no invalid signatures"
    )


def _verify_commit_single(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """validation.go:262-330."""
    tallied = 0
    seen_vals = {}
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from validator {val_idx} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        if not val.pub_key.verify_signature(vote_sign_bytes, commit_sig.signature):
            raise InvalidCommitError(
                f"wrong signature (#{idx}): {commit_sig.signature.hex().upper()}"
            )
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            return
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(got=tallied, needed=voting_power_needed)


def _verify_basic_vals_and_commit(
    vals: Optional[ValidatorSet],
    commit: Optional[Commit],
    height: int,
    block_id: BlockID,
) -> None:
    """validation.go:334-356."""
    if vals is None:
        raise InvalidCommitError("nil validator set")
    if commit is None:
        raise InvalidCommitError("nil commit")
    if len(vals) != len(commit.signatures):
        raise InvalidCommitError(
            f"invalid commit -- wrong set size: {len(vals)} vs "
            f"{len(commit.signatures)}"
        )
    if height != commit.height:
        raise InvalidCommitError(
            f"invalid commit -- wrong height: {height} vs {commit.height}"
        )
    if block_id != commit.block_id:
        raise InvalidCommitError(
            f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
        )
